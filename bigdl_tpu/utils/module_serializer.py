"""Topology (de)serialization of Module trees — the ModuleSerializer analogue
(reference: utils/serializer/ModuleSerializer.scala:34, ModuleSerializable
reflection path, registry :115).

A module saves as a JSON spec: class name + captured constructor args
(auto-recorded by Module.__init_subclass__) + extra children added after
construction + per-module metadata (name, scales, train mode). Graph modules
serialize their node/edge structure. Weights travel separately (save_tree);
`save_module`/`load_module` in utils.serialization bundle both.

Classes resolve through a registry seeded from ``bigdl_tpu.nn``; user classes
register with :func:`register_module_class`.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

_REGISTRY: Dict[str, type] = {}


def register_module_class(cls: type, name: Optional[str] = None) -> type:
    """Register a custom Module class so save_module/load_module can
    reconstruct it by name (ModuleSerializer.registerModule)."""
    _REGISTRY[name or cls.__name__] = cls
    return cls


def _resolve(name: str) -> type:
    if name in _REGISTRY:
        return _REGISTRY[name]
    import bigdl_tpu.nn as nn
    if hasattr(nn, name):
        return getattr(nn, name)
    import bigdl_tpu.models as models
    if hasattr(models, name):
        return getattr(models, name)
    raise KeyError(
        f"unknown module class {name!r}; register it with "
        "bigdl_tpu.utils.module_serializer.register_module_class")


# ------------------------------------------------------------------ encode

def _encode_value(v) -> Any:
    from bigdl_tpu.nn.module import Module
    from bigdl_tpu.utils.table import Table
    if isinstance(v, Module):
        return {"__module__": to_spec(v)}
    if isinstance(v, (bytes, bytearray)):
        import base64
        return {"__bytes__": base64.b64encode(bytes(v)).decode("ascii")}
    if isinstance(v, (np.ndarray, np.generic, jax.Array)):
        arr = np.asarray(v)
        return {"__ndarray__": arr.tolist(), "dtype": str(arr.dtype)}
    if isinstance(v, Table):
        return {"__table__": {str(k): _encode_value(x)
                              for k, x in v.items()}}
    if isinstance(v, dict):
        return {"__dict__": {k: _encode_value(x) for k, x in v.items()}}
    if isinstance(v, tuple):
        return {"__tuple__": [_encode_value(x) for x in v]}
    if isinstance(v, list):
        return [_encode_value(x) for x in v]
    if isinstance(v, (bool, int, float, str)) or v is None:
        return v
    # value objects (InitializationMethod, Regularizer, schedules...):
    # shallow state capture
    state = {k: _encode_value(x) for k, x in vars(v).items()
             if not k.startswith("_")}
    register_module_class(type(v))
    return {"__obj__": type(v).__name__, "state": state}


def _decode_value(v):
    from bigdl_tpu.utils.table import Table
    if isinstance(v, list):
        return [_decode_value(x) for x in v]
    if not isinstance(v, dict):
        return v
    if "__module__" in v:
        return from_spec(v["__module__"])
    if "__bytes__" in v:
        import base64
        return base64.b64decode(v["__bytes__"])
    if "__ndarray__" in v:
        return np.asarray(v["__ndarray__"], dtype=v["dtype"])
    if "__table__" in v:
        t = Table()
        for k, x in v["__table__"].items():
            t[int(k) if k.lstrip("-").isdigit() else k] = _decode_value(x)
        return t
    if "__dict__" in v:
        return {k: _decode_value(x) for k, x in v["__dict__"].items()}
    if "__tuple__" in v:
        return tuple(_decode_value(x) for x in v["__tuple__"])
    if "__obj__" in v:
        cls = _resolve(v["__obj__"])
        obj = cls.__new__(cls)
        obj.__dict__.update(
            {k: _decode_value(x) for k, x in v["state"].items()})
        return obj
    return {k: _decode_value(x) for k, x in v.items()}


def to_spec(module) -> Dict[str, Any]:
    """Recursive JSON-able spec of a module tree."""
    from bigdl_tpu.nn.container import Container
    from bigdl_tpu.nn.graph import Graph
    from bigdl_tpu.nn.module import Module

    if isinstance(module, Graph):
        return _graph_to_spec(module)

    from jax.sharding import Mesh

    # a device mesh is runtime PLACEMENT, not model identity — snapshots
    # must load on any topology (reattach via the ctor's mesh= after
    # load); a Mesh also cannot round-trip through JSON
    args = [None if isinstance(a, Mesh) else a
            for a in getattr(module, "_init_args", ())]
    kwargs = {k: v for k, v in
              dict(getattr(module, "_init_kwargs", {})).items()
              if not isinstance(v, Mesh)}
    spec: Dict[str, Any] = {
        "class": type(module).__name__,
        "args": [_encode_value(a) for a in args],
        "kwargs": {k: _encode_value(v) for k, v in kwargs.items()},
    }
    _meta_to_spec(module, spec)
    if isinstance(module, Container):
        n_ctor = sum(1 for a in args if isinstance(a, Module))
        extra = module.modules[n_ctor:]
        if extra:
            spec["n_ctor"] = n_ctor
            spec["children"] = [to_spec(m) for m in extra]
    return spec


def _meta_to_spec(module, spec: Dict[str, Any]) -> None:
    if module._name is not None:
        spec["name"] = module._name
    if module.scale_w != 1.0 or module.scale_b != 1.0:
        spec["scales"] = [module.scale_w, module.scale_b]
    if not module.train_mode:
        spec["eval_mode"] = True


def _meta_from_spec(module, spec: Dict[str, Any]) -> None:
    if "name" in spec:
        module.set_name(spec["name"])
    if "scales" in spec:
        module.scale_w, module.scale_b = spec["scales"]
    if spec.get("eval_mode"):
        # set only this module's flag; children restore their own
        module.train_mode = False


def from_spec(spec: Dict[str, Any]):
    """Rebuild a module tree from its spec."""
    if spec.get("class") == "Graph":
        return _graph_from_spec(spec)
    cls = _resolve(spec["class"])
    args = [_decode_value(a) for a in spec.get("args", [])]
    kwargs = {k: _decode_value(v) for k, v in spec.get("kwargs", {}).items()}
    module = cls(*args, **kwargs)
    _meta_from_spec(module, spec)
    children = spec.get("children", [])
    if children:
        # A subclass __init__ may itself have built children beyond those
        # passed as ctor args (e.g. a model class that calls self.add in
        # __init__); those are already present — only add the remainder.
        already_built = len(module.modules) - spec.get("n_ctor", 0)
        for child_spec in children[max(0, already_built):]:
            module.add(from_spec(child_spec))
    return module


# ---------------------------------------------------------- Graph handling

def _graph_to_spec(g) -> Dict[str, Any]:
    """Serialize nodes + edges; node ids are positions in exec_order."""
    idx = {id(n): i for i, n in enumerate(g.exec_order)}
    nodes = [to_spec(n.element) for n in g.exec_order]
    edges: List[List] = []
    for n in g.exec_order:
        for p, e in n.prevs:
            edges.append([idx[id(p)], idx[id(n)], e.from_index])
    spec = {
        "class": "Graph",
        "nodes": nodes,
        "edges": edges,
        "inputs": [idx[id(n)] for n in g.input_nodes],
        "outputs": [idx[id(n)] for n in g.output_nodes],
    }
    _meta_to_spec(g, spec)
    return spec


def _graph_from_spec(spec: Dict[str, Any]):
    from bigdl_tpu.nn.graph import Graph
    from bigdl_tpu.utils.directed_graph import Edge, Node
    nodes = [Node(from_spec(s)) for s in spec["nodes"]]
    for src, dst, from_index in spec["edges"]:
        nodes[src].add(nodes[dst], Edge(from_index))
    g = Graph([nodes[i] for i in spec["inputs"]],
              [nodes[i] for i in spec["outputs"]])
    _meta_from_spec(g, spec)
    return g
