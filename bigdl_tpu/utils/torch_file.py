"""Torch7 .t7 serialization (reference: utils/TorchFile.scala, 1,088 LoC
type-tagged binary walker; public format: torch/File.c).

Read/write of the t7 object graph: numbers, booleans, strings, tables,
torch.*Tensor / torch.*Storage objects (little-endian, index-sharing via
object ids). ``load_torch_model`` additionally converts a saved torch nn
module tree into bigdl_tpu modules (the reference's Module.loadTorch path).
"""
from __future__ import annotations

import struct
from typing import Any, BinaryIO, Dict, Optional

import numpy as np

# t7 type tags (torch/File.c)
TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5
TYPE_FUNCTION = 6
TYPE_RECUR_FUNCTION = 8
TYPE_LEGACY_RECUR_FUNCTION = 7

_TENSOR_DTYPES = {
    "torch.DoubleTensor": np.float64, "torch.FloatTensor": np.float32,
    "torch.LongTensor": np.int64, "torch.IntTensor": np.int32,
    "torch.ShortTensor": np.int16, "torch.ByteTensor": np.uint8,
    "torch.CharTensor": np.int8,
}
_STORAGE_DTYPES = {k.replace("Tensor", "Storage"): v
                   for k, v in _TENSOR_DTYPES.items()}
_NP_TO_TENSOR = {np.dtype(np.float64): "torch.DoubleTensor",
                 np.dtype(np.float32): "torch.FloatTensor",
                 np.dtype(np.int64): "torch.LongTensor",
                 np.dtype(np.int32): "torch.IntTensor",
                 np.dtype(np.int16): "torch.ShortTensor",
                 np.dtype(np.uint8): "torch.ByteTensor",
                 np.dtype(np.int8): "torch.CharTensor"}


class TorchObject:
    """Unconverted torch class instance: .torch_type + .state (table)."""

    def __init__(self, torch_type: str, state):
        self.torch_type = torch_type
        self.state = state

    def __repr__(self):
        return f"TorchObject({self.torch_type})"


class _Reader:
    def __init__(self, f: BinaryIO, long_size: int = 8):
        self.f = f
        self.long_size = long_size
        self.memo: Dict[int, Any] = {}

    def _read(self, fmt: str):
        size = struct.calcsize(fmt)
        data = self.f.read(size)
        if len(data) < size:
            raise EOFError("truncated t7 file")
        return struct.unpack(fmt, data)[0]

    def read_int(self) -> int:
        return self._read("<i")

    def read_long(self) -> int:
        return self._read("<q" if self.long_size == 8 else "<i")

    def read_double(self) -> float:
        return self._read("<d")

    def read_string(self) -> str:
        n = self.read_int()
        return self.f.read(n).decode("latin-1")

    def read_object(self):
        tag = self.read_int()
        if tag == TYPE_NIL:
            return None
        if tag == TYPE_NUMBER:
            v = self.read_double()
            return int(v) if v.is_integer() else v
        if tag == TYPE_STRING:
            return self.read_string()
        if tag == TYPE_BOOLEAN:
            return self.read_int() == 1
        if tag in (TYPE_TABLE, TYPE_TORCH, TYPE_FUNCTION,
                   TYPE_RECUR_FUNCTION, TYPE_LEGACY_RECUR_FUNCTION):
            idx = self.read_int()
            if idx in self.memo:
                return self.memo[idx]
            if tag == TYPE_TABLE:
                return self._read_table(idx)
            if tag == TYPE_TORCH:
                return self._read_torch(idx)
            raise ValueError("t7 functions are not supported")
        raise ValueError(f"bad t7 type tag {tag}")

    def _read_table(self, idx: int):
        n = self.read_int()
        table: Dict[Any, Any] = {}
        self.memo[idx] = table
        for _ in range(n):
            k = self.read_object()
            v = self.read_object()
            table[k] = v
        # dense int-keyed tables (1..n) -> list
        if table and all(isinstance(k, int) for k in table):
            keys = sorted(table)
            if keys == list(range(1, len(keys) + 1)):
                lst = [table[k] for k in keys]
                self.memo[idx] = lst
                return lst
        return table

    def _read_torch(self, idx: int):
        version = self.read_string()
        if version.startswith("V "):
            class_name = self.read_string()
        else:  # pre-versioning files: the string IS the class name
            class_name = version
        placeholder = TorchObject(class_name, None)
        self.memo[idx] = placeholder
        if class_name in _TENSOR_DTYPES:
            obj = self._read_tensor(class_name)
        elif class_name in _STORAGE_DTYPES:
            obj = self._read_storage(class_name)
        else:
            placeholder.state = self.read_object()
            return placeholder
        self.memo[idx] = obj
        return obj

    def _read_tensor(self, class_name: str) -> np.ndarray:
        ndim = self.read_int()
        size = [self.read_long() for _ in range(ndim)]
        stride = [self.read_long() for _ in range(ndim)]
        offset = self.read_long() - 1  # 1-based
        storage = self.read_object()
        if storage is None:
            return np.zeros(size, _TENSOR_DTYPES[class_name])
        arr = np.asarray(storage)
        if ndim == 0:
            return np.zeros((0,), _TENSOR_DTYPES[class_name])
        itemsize = arr.dtype.itemsize
        return np.lib.stride_tricks.as_strided(
            arr[offset:], shape=size,
            strides=[s * itemsize for s in stride]).copy()

    def _read_storage(self, class_name: str) -> np.ndarray:
        n = self.read_long()
        dtype = _STORAGE_DTYPES[class_name]
        return np.frombuffer(self.f.read(n * np.dtype(dtype).itemsize),
                             dtype=dtype).copy()


class _Writer:
    def __init__(self, f: BinaryIO):
        self.f = f
        self.memo: Dict[int, int] = {}
        self.next_idx = 1

    def write_int(self, v: int):
        self.f.write(struct.pack("<i", v))

    def write_long(self, v: int):
        self.f.write(struct.pack("<q", v))

    def write_double(self, v: float):
        self.f.write(struct.pack("<d", v))

    def write_string(self, s: str):
        b = s.encode("latin-1")
        self.write_int(len(b))
        self.f.write(b)

    def write_object(self, obj):
        if obj is None:
            self.write_int(TYPE_NIL)
        elif isinstance(obj, (bool, np.bool_)):
            self.write_int(TYPE_BOOLEAN)
            self.write_int(1 if obj else 0)
        elif isinstance(obj, (int, float, np.integer, np.floating)):
            self.write_int(TYPE_NUMBER)
            self.write_double(float(obj))
        elif isinstance(obj, str):
            self.write_int(TYPE_STRING)
            self.write_string(obj)
        elif isinstance(obj, np.ndarray):
            self._write_tensor(obj)
        elif isinstance(obj, (dict, list, tuple)):
            self._write_table(obj)
        elif isinstance(obj, TorchObject):
            self.write_int(TYPE_TORCH)
            if id(obj) in self.memo:
                self.write_int(self.memo[id(obj)])
                return
            self.write_int(self._alloc(obj))
            self.write_string("V 1")
            self.write_string(obj.torch_type)
            self.write_object(obj.state)
        else:
            raise TypeError(f"cannot serialize {type(obj)} to t7")

    def _alloc(self, obj) -> int:
        idx = self.next_idx
        self.memo[id(obj)] = idx
        self.next_idx += 1
        return idx

    def _write_table(self, obj):
        self.write_int(TYPE_TABLE)
        if id(obj) in self.memo:
            self.write_int(self.memo[id(obj)])
            return
        self.write_int(self._alloc(obj))
        if isinstance(obj, (list, tuple)):
            items = {i + 1: v for i, v in enumerate(obj)}
        else:
            items = obj
        self.write_int(len(items))
        for k, v in items.items():
            self.write_object(k)
            self.write_object(v)

    def _write_tensor(self, arr: np.ndarray):
        self.write_int(TYPE_TORCH)
        if id(arr) in self.memo:
            self.write_int(self.memo[id(arr)])
            return
        self.write_int(self._alloc(arr))
        arr = np.ascontiguousarray(arr)
        tname = _NP_TO_TENSOR[arr.dtype]
        self.write_string("V 1")
        self.write_string(tname)
        self.write_int(arr.ndim)
        for s in arr.shape:
            self.write_long(s)
        strides = [st // arr.dtype.itemsize for st in arr.strides]
        for s in strides:
            self.write_long(s)
        self.write_long(1)  # storage offset (1-based)
        # storage
        self.write_int(TYPE_TORCH)
        self.write_int(self.next_idx)
        self.next_idx += 1
        self.write_string("V 1")
        self.write_string(tname.replace("Tensor", "Storage"))
        self.write_long(arr.size)
        self.f.write(arr.tobytes())


def load(path: str):
    """Read one object from a .t7 file (TorchFile.load)."""
    with open(path, "rb") as f:
        return _Reader(f).read_object()


def save(path: str, obj) -> None:
    """Write one object to a .t7 file (TorchFile.save)."""
    with open(path, "wb") as f:
        _Writer(f).write_object(obj)


# ------------------------------------------------------- module conversion

def _get(state, key, default=None):
    if isinstance(state, dict):
        return state.get(key, default)
    return default


def _to_module(obj) -> "object":
    """Convert a read torch nn.* object into a bigdl_tpu module."""
    import bigdl_tpu.nn as nn
    if not isinstance(obj, TorchObject):
        raise TypeError(f"expected torch object, got {type(obj)}")
    t = obj.torch_type
    s = obj.state or {}
    short = t.split(".")[-1]

    def with_weights(m, weight=None, bias=None, transform=None):
        m.ensure_initialized()
        p = dict(m.get_parameters())
        if weight is not None:
            w = np.asarray(weight, np.float32)
            if transform:
                w = transform(w)
            p["weight"] = w
        if bias is not None and "bias" in p:
            p["bias"] = np.asarray(bias, np.float32)
        m.set_parameters(p)
        return m

    if short == "Sequential":
        seq = nn.Sequential()
        for child in s.get("modules", []):
            seq.add(_to_module(child))
        return seq
    if short == "ConcatTable":
        ct = nn.ConcatTable()
        for child in s.get("modules", []):
            ct.add(_to_module(child))
        return ct
    if short == "Concat":
        c = nn.Concat(int(s.get("dimension", 2)))
        for child in s.get("modules", []):
            c.add(_to_module(child))
        return c
    if short == "Linear":
        w = s["weight"]
        m = nn.Linear(w.shape[1], w.shape[0],
                      with_bias="bias" in s and s["bias"] is not None)
        return with_weights(m, w, s.get("bias"))
    if short == "SpatialConvolution":
        m = nn.SpatialConvolution(
            int(s["nInputPlane"]), int(s["nOutputPlane"]),
            int(s["kW"]), int(s["kH"]), int(s.get("dW", 1)),
            int(s.get("dH", 1)), int(s.get("padW", 0)), int(s.get("padH", 0)))
        w = s["weight"]
        if w.ndim == 2:  # flattened [nOut, nIn*kh*kw]
            w = w.reshape(int(s["nOutputPlane"]), int(s["nInputPlane"]),
                          int(s["kH"]), int(s["kW"]))
        return with_weights(m, w, s.get("bias"))
    if short == "SpatialMaxPooling":
        m = nn.SpatialMaxPooling(int(s["kW"]), int(s["kH"]),
                                 int(s.get("dW", 1)), int(s.get("dH", 1)),
                                 int(s.get("padW", 0)), int(s.get("padH", 0)))
        if s.get("ceil_mode"):
            m.ceil()
        return m
    if short == "SpatialAveragePooling":
        return nn.SpatialAveragePooling(
            int(s["kW"]), int(s["kH"]), int(s.get("dW", 1)),
            int(s.get("dH", 1)), int(s.get("padW", 0)), int(s.get("padH", 0)))
    if short == "SpatialBatchNormalization":
        m = nn.SpatialBatchNormalization(int(s["running_mean"].shape[0]),
                                         eps=float(s.get("eps", 1e-5)),
                                         momentum=float(s.get("momentum",
                                                              0.1)))
        m.ensure_initialized()
        p = dict(m.get_parameters())
        if s.get("weight") is not None:
            p["weight"] = np.asarray(s["weight"], np.float32)
        if s.get("bias") is not None:
            p["bias"] = np.asarray(s["bias"], np.float32)
        m.set_parameters(p)
        st = dict(m.get_state())
        st["running_mean"] = np.asarray(s["running_mean"], np.float32)
        st["running_var"] = np.asarray(s["running_var"], np.float32)
        m.set_state(st)
        return m
    simple = {"ReLU": nn.ReLU, "Tanh": nn.Tanh, "Sigmoid": nn.Sigmoid,
              "LogSoftMax": nn.LogSoftMax, "SoftMax": nn.SoftMax,
              "Identity": nn.Identity}
    if short in simple:
        return simple[short]()
    if short == "Dropout":
        return nn.Dropout(float(s.get("p", 0.5)))
    if short == "View":
        sizes = s.get("size")
        dims = (list(np.asarray(sizes).ravel().astype(int))
                if sizes is not None else [-1])
        return nn.View(tuple(int(d) for d in dims))
    if short == "Reshape":
        sizes = s.get("size")
        return nn.Reshape(tuple(int(d) for d in
                                np.asarray(sizes).ravel().astype(int)))
    raise ValueError(f"unsupported torch module {t}")


def load_torch_model(path: str):
    """Load a torch nn model saved with torch.save into bigdl_tpu modules
    (TorchFile.loadTorch → Module path)."""
    return _to_module(load(path))
