"""TF graph pattern fusion → structured modules (the reference's
TensorflowToBigDL fusion table, utils/tf/TensorflowToBigDL.scala:1).

``TFModule`` (utils/tf_loader.py) executes an imported GraphDef
op-by-op; that runs and trains, but an op soup cannot be ``quantize()``d
(the rewrite looks for Linear/SpatialConvolution modules), re-exported
through the Caffe/module serializers, or inspected as layers. This pass
pattern-matches the node chain into REAL ``bigdl_tpu.nn`` modules:

    Conv2D [+ BiasAdd]        -> SpatialConvolution
    MatMul [+ BiasAdd]        -> Linear
    FusedBatchNorm{,V2,V3}    -> SpatialBatchNormalization (+ stats)
    MaxPool / AvgPool         -> SpatialMaxPooling / SpatialAveragePooling
    Relu / Softmax / Reshape  -> ReLU / SoftMax / View

Layout: TF graphs are NHWC, the nn modules are NCHW. The pass tracks
the live layout and inserts the minimal ``Transpose`` adapters (one
entering the conv stack, one before a TF-semantics flatten/output), so
the fused module's outputs equal the TF graph's EXACTLY — including the
H,W,C flatten order feeding a Linear.

Scope: DAGs of the ops above plus branch/merge structure —
``Concat/ConcatV2`` → JoinTable, two-tensor ``Add/AddV2`` → CAddTable
(the branch-and-concat topology of real Inception-class imports, which
the reference's per-pattern fusion table also covered,
TensorflowToBigDL.scala:1). A pure chain fuses to a ``Sequential``, a
branchy graph to a ``Graph`` of the same modules. An unsupported op
raises with its name — unless ``mixed=True``, which wraps each
unsupported single-tensor-input node in a one-op ``TFModule`` island
(rebuilt from the original NodeDef bytes, so the result still
serializes) and keeps fusing everything around it; the islands are
listed on the returned module's ``fused_islands``.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.utils.tf_loader import TFNode, parse_graphdef


def _require(node: TFNode, attr: str, allowed) -> None:
    """Fail fast on attrs the fusion table cannot express — a silently
    wrong module is worse than falling back to TFModule."""
    v = node.attrs.get(attr)
    if isinstance(v, bytes):
        v = v.decode()
    if v not in allowed:
        raise ValueError(
            f"fusion: {node.op} with {attr}={v!r} unsupported "
            f"({node.name}); import with TFModule instead")


def _same_pad(n: Optional[int], k: int, s: int) -> int:
    """TF SAME padding for one spatial dim; None size means unknown.
    Returns the symmetric per-side pad or raises if asymmetric."""
    if s == 1:
        total = k - 1
    else:
        if n is None:
            raise ValueError(
                "SAME padding with stride>1 needs a known input size "
                "(give the Placeholder a shape)")
        total = max((-(-n // s) - 1) * s + k - n, 0)
    if total % 2:
        raise ValueError(
            f"TF SAME padding is asymmetric here (total {total}); "
            "SpatialConvolution cannot express it — repad the graph")
    return total // 2


def _out_size(n: Optional[int], k: int, s: int, pad: int,
              ceil_mode: bool = False) -> Optional[int]:
    if n is None:
        return None
    m = n + 2 * pad - k
    return (-(-m // s) if ceil_mode else m // s) + 1


class _Fuser:
    def __init__(self, nodes: List[TFNode], inputs, outputs):
        self.by_name = {n.name: n for n in nodes}
        self.nodes = nodes
        self.consts: Dict[str, np.ndarray] = {
            n.name: np.asarray(n.attrs.get("value"))
            for n in nodes if n.op == "Const"}
        self.input_names = list(inputs) if inputs else [
            n.name for n in nodes if n.op == "Placeholder"]
        if outputs:
            self.output_names = list(outputs)
        else:
            consumed = {i.split(":")[0].lstrip("^")
                        for n in nodes for i in n.inputs}
            self.output_names = [n.name for n in nodes
                                 if n.name not in consumed
                                 and n.op not in ("Const", "Placeholder",
                                                  "NoOp")]

    def const(self, ref: str) -> np.ndarray:
        nm = ref.split(":")[0].lstrip("^")
        node = self.by_name[nm]
        while node.op == "Identity":
            nm = node.inputs[0].split(":")[0].lstrip("^")
            node = self.by_name[nm]
        if nm not in self.consts:
            raise ValueError(
                f"fusion needs a constant weight at {ref}, found "
                f"{node.op} (freeze the graph first)")
        return self.consts[nm]

    def _bias_of(self, node: Optional[TFNode]) -> Optional[np.ndarray]:
        """The constant bias when ``node`` is a bias-add form: BiasAdd,
        or Add/AddV2 with a rank-1 const operand (TF2 freezing lowers
        `y + b` to AddV2)."""
        if node is None or node.op not in ("BiasAdd", "Add", "AddV2"):
            return None
        try:
            b = self.const(node.inputs[1])
        except (ValueError, KeyError):
            return None
        return b if b.ndim == 1 else None

    def fuse(self):
        """Chain walk from the single input to the single output."""
        import bigdl_tpu.nn as nn

        if len(self.input_names) != 1 or len(self.output_names) != 1:
            raise ValueError(
                "fusion covers single-input single-output chains; use "
                "TFModule for general graphs")
        # build the producer chain output <- ... <- input, following the
        # first TENSOR input of each node (weights are const operands)
        chain: List[TFNode] = []
        cur = self.by_name[self.output_names[0]]
        guard = 0
        while cur.name != self.input_names[0]:
            chain.append(cur)
            data_in = None
            for ref in cur.inputs:
                nm = ref.split(":")[0].lstrip("^")
                node = self.by_name[nm]
                while node.op == "Identity":
                    nm = node.inputs[0].split(":")[0].lstrip("^")
                    node = self.by_name[nm]
                if node.op != "Const":
                    data_in = node
                    break
            if data_in is None:
                raise ValueError(f"no tensor input at node {cur.name}")
            cur = data_in
            guard += 1
            if guard > 10000:
                raise ValueError("graph is not a chain")
        chain.reverse()

        placeholder = self.by_name[self.input_names[0]]
        shape = placeholder.attrs.get("shape")
        # spatial sizes tracked through the chain for SAME padding
        h, w = (None, None)
        if shape is not None and len(shape) == 4:
            h = None if shape[1] in (-1, None) else int(shape[1])
            w = None if shape[2] in (-1, None) else int(shape[2])

        seq = nn.Sequential()
        layout = "NHWC"  # the TF graph's native layout
        presets = []     # (module, params, state) to install after init

        def to_nchw():
            nonlocal layout
            if layout == "NHWC":
                seq.add(nn.Transpose([(2, 4), (3, 4)]))
                layout = "NCHW"

        def to_nhwc():
            nonlocal layout
            if layout == "NCHW":
                seq.add(nn.Transpose([(2, 3), (3, 4)]))
                layout = "NHWC"

        i = 0
        while i < len(chain):
            node = chain[i]
            op = node.op
            nxt = chain[i + 1] if i + 1 < len(chain) else None
            if op == "Identity":
                i += 1
            elif op == "Conv2D":
                _require(node, "data_format", ("NHWC", None))
                _require(node, "padding", ("SAME", "VALID"))
                dil = node.attrs.get("dilations")
                if dil is not None and any(d != 1 for d in dil):
                    raise ValueError(
                        f"fusion: dilated Conv2D unsupported ({node.name})"
                        "; import with TFModule instead")
                wgt = self.const(node.inputs[1])  # HWIO
                kh, kw_ = wgt.shape[0], wgt.shape[1]
                cin, cout = wgt.shape[2], wgt.shape[3]
                sh, sw = node.attrs["strides"][1:3]
                pad = node.attrs["padding"]
                ph = 0 if pad == "VALID" else _same_pad(h, kh, sh)
                pw = 0 if pad == "VALID" else _same_pad(w, kw_, sw)
                bias = self._bias_of(nxt)
                if bias is not None:
                    i += 1
                m = nn.SpatialConvolution(cin, cout, kw_, kh, sw, sh,
                                          pw, ph,
                                          with_bias=bias is not None)
                p = {"weight": np.transpose(wgt, (3, 2, 0, 1))}
                if bias is not None:
                    p["bias"] = bias
                presets.append((m, p, None))
                to_nchw()
                seq.add(m)
                h, w = _out_size(h, kh, sh, ph), _out_size(w, kw_, sw, pw)
                i += 1
            elif op == "MatMul":
                if node.attrs.get("transpose_a") or \
                        node.attrs.get("transpose_b"):
                    raise ValueError(
                        f"fusion: transposed MatMul unsupported "
                        f"({node.name}); import with TFModule instead")
                wgt = self.const(node.inputs[1])  # [in, out]
                bias = self._bias_of(nxt)
                if bias is not None:
                    i += 1
                m = nn.Linear(wgt.shape[0], wgt.shape[1],
                              with_bias=bias is not None)
                p = {"weight": wgt.T}
                if bias is not None:
                    p["bias"] = bias
                presets.append((m, p, None))
                seq.add(m)
                i += 1
            elif op in ("FusedBatchNorm", "FusedBatchNormV2",
                        "FusedBatchNormV3"):
                # is_training=True means TF ignores the mean/var const
                # inputs (batch stats instead) — fusing those consts in
                # would silently diverge from the graph; NCHW would put
                # the stats on the wrong channel axis. Fail fast to
                # TFModule like Conv2D/pooling do. NOTE: the op-def
                # DEFAULT for is_training is True, so an absent attr is
                # training mode too — only an explicit False may fuse.
                _require(node, "is_training", (False,))
                _require(node, "data_format", ("NHWC", None))
                scale = self.const(node.inputs[1])
                offset = self.const(node.inputs[2])
                mean = self.const(node.inputs[3])
                var = self.const(node.inputs[4])
                eps = float(node.attrs.get("epsilon", 1e-3))
                m = nn.SpatialBatchNormalization(len(scale), eps)
                presets.append((m, {"weight": scale, "bias": offset},
                                {"running_mean": mean,
                                 "running_var": var}))
                to_nchw()
                seq.add(m)
                i += 1
            elif op in ("MaxPool", "AvgPool"):
                _require(node, "data_format", ("NHWC", None))
                _require(node, "padding", ("SAME", "VALID"))
                kh, kw_ = node.attrs["ksize"][1:3]
                sh, sw = node.attrs["strides"][1:3]
                pad = node.attrs["padding"]
                ph = 0 if pad == "VALID" else _same_pad(h, kh, sh)
                pw = 0 if pad == "VALID" else _same_pad(w, kw_, sw)
                ceil = pad == "SAME"  # TF SAME pooling covers the tail
                if op == "MaxPool":
                    m = nn.SpatialMaxPooling(kw_, kh, sw, sh, pw, ph)
                else:
                    # TF AvgPool excludes padding from the divisor, the
                    # Torch count_include_pad=False convention
                    m = nn.SpatialAveragePooling(
                        kw_, kh, sw, sh, pw, ph, count_include_pad=False)
                if ceil:
                    m = m.ceil()
                to_nchw()
                seq.add(m)
                h = _out_size(h, kh, sh, ph, ceil)
                w = _out_size(w, kw_, sw, pw, ceil)
                i += 1
            elif op == "Relu":
                seq.add(nn.ReLU())
                i += 1
            elif op == "Softmax":
                to_nhwc()
                seq.add(nn.SoftMax())
                i += 1
            elif op == "Reshape":
                tgt = [int(v) for v in
                       np.asarray(self.const(node.inputs[1])).ravel()]
                # TF flatten reshapes in H,W,C order — return to NHWC
                # first so the following Linear's weights line up
                to_nhwc()
                if len(tgt) == 2 and tgt[0] == -1:
                    seq.add(nn.View(tgt[1]))
                else:
                    seq.add(nn.Reshape(tuple(tgt[1:])))
                i += 1
            else:
                raise ValueError(
                    f"fusion table has no pattern for op {op} (node "
                    f"{node.name}); import with TFModule instead")
        to_nhwc()  # a 4-D output leaves in the graph's own layout

        import jax.numpy as jnp
        # install weights BEFORE the container initializes: Container.init
        # adopts a child's already-materialized params (the importer
        # contract, nn/container.py adopt_or_init)
        for m, p, s in presets:
            m.set_parameters({k: jnp.asarray(v) for k, v in p.items()})
            if s is not None:
                m.set_state({k: jnp.asarray(v) for k, v in s.items()})
        seq.evaluate()
        seq.ensure_initialized()
        return seq


class _DagFuser:
    """Branch/concat-capable fuser: maps the tensor-dataflow DAG onto a
    ``Graph`` of real nn modules. Layout is tracked PER VALUE — each TF
    tensor may exist as an NHWC and/or NCHW nn node, adapters inserted
    once on demand — so every branch sees exactly the layout its ops
    need and the fused output equals the TF graph's."""

    # NHWC axis -> NCHW axis (concat remap)
    _NHWC2NCHW = {0: 0, 1: 2, 2: 3, 3: 1}

    def __init__(self, fuser: _Fuser, mixed: bool):
        self.f = fuser
        self.mixed = mixed
        self.presets = []
        self.islands: List[str] = []
        self.vals: Dict[str, Dict[str, object]] = {}  # name->layout->Node
        self.kind: Dict[str, str] = {}                # "4D" | "FLAT"
        self.hw: Dict[str, tuple] = {}

    # -------------------------------------------------------- graph walk
    def _resolve(self, ref: str) -> TFNode:
        nm = ref.split(":")[0].lstrip("^")
        node = self.f.by_name[nm]
        while node.op == "Identity":
            nm = node.inputs[0].split(":")[0].lstrip("^")
            node = self.f.by_name[nm]
        return node

    def _tensor_inputs(self, node: TFNode) -> List[TFNode]:
        out = []
        for ref in node.inputs:
            if ref.startswith("^"):
                continue  # control edge
            p = self._resolve(ref)
            if p.op != "Const":
                out.append(p)
        return out

    def _value_as(self, name: str, layout: str):
        """The nn node holding TF tensor ``name`` in ``layout``,
        inserting a Transpose adapter once if needed."""
        import bigdl_tpu.nn as nn
        d = self.vals[name]
        if layout in d:
            return d[layout]
        if layout == "NCHW":
            node = nn.Transpose([(2, 4), (3, 4)])(d["NHWC"])
        elif layout == "NHWC":
            node = nn.Transpose([(2, 3), (3, 4)])(d["NCHW"])
        else:
            raise ValueError(f"no {layout} form of {name} ({list(d)})")
        d[layout] = node
        return node

    def _natural(self, name: str) -> str:
        """A layout ``name`` already exists in (avoids adapters for
        layout-agnostic ops like ReLU)."""
        return next(iter(self.vals[name]))

    def _set(self, name: str, layout: str, node, kind: str, hw=None):
        self.vals[name] = {layout: node}
        self.kind[name] = kind
        self.hw[name] = hw if hw is not None else (None, None)

    # ------------------------------------------------------------- fuse
    def fuse(self):
        import bigdl_tpu.nn as nn
        f = self.f
        if len(f.input_names) != 1 or len(f.output_names) != 1:
            raise ValueError(
                "fusion covers single-input single-output graphs; use "
                "TFModule for general graphs")
        placeholder = f.by_name[f.input_names[0]]
        shape = placeholder.attrs.get("shape")
        hw = (None, None)
        kind = "FLAT"
        if shape is not None and len(shape) == 4:
            hw = tuple(None if s in (-1, None) else int(s)
                       for s in shape[1:3])
            kind = "4D"
        elif shape is None:
            kind = "4D"  # assume image input like the TF graphs we fuse

        # reachable tensor nodes + consumer map (tensor edges only);
        # iterative DFS — imported graphs can be thousands of nodes
        # deep and must not hit Python's recursion limit
        consumers: Dict[str, List[TFNode]] = {}
        order: List[TFNode] = []
        seen: Dict[int, int] = {}
        out_node = f.by_name[f.output_names[0]]
        root = (self._resolve(out_node.name)
                if out_node.op == "Identity" else out_node)
        stack = [(root, False)]
        while stack:
            node, done = stack.pop()
            if done:
                seen[id(node)] = 2
                if node.op not in ("Const", "Placeholder"):
                    order.append(node)
                continue
            if seen.get(id(node)) is not None:
                # duplicate push from a diamond ancestor — already
                # in progress or finished
                continue
            seen[id(node)] = 1
            stack.append((node, True))
            preds = self._tensor_inputs(node)
            for p in preds:
                consumers.setdefault(p.name, []).append(node)
            for p in reversed(preds):
                if seen.get(id(p)) is None:
                    stack.append((p, False))
        # a malformed (cyclic) graph would put a consumer before its
        # producer here; _emit then fails cleanly on the missing value
        # rather than this walk looping forever

        inp = nn.Input()()
        self._set(placeholder.name, "NHWC" if kind == "4D" else "FLAT",
                  inp, kind, hw)

        absorbed: set = set()
        for node in order:
            if node.name in absorbed or node.name in self.vals:
                continue
            try:
                self._emit(node, consumers, absorbed)
            except ValueError:
                if not self.mixed:
                    raise
                self._emit_island(node)

        out_name = (self._resolve(out_node.name).name
                    if out_node.op == "Identity" else out_node.name)
        final_kind = self.kind[out_name]
        out = self._value_as(out_name,
                             "NHWC" if final_kind == "4D" else "FLAT")

        import jax.numpy as jnp
        for m, p, s in self.presets:
            m.set_parameters({k: jnp.asarray(v) for k, v in p.items()})
            if s is not None:
                m.set_state({k: jnp.asarray(v) for k, v in s.items()})
        g = nn.Graph(inp, out)
        g.fused_islands = list(self.islands)
        g.evaluate()
        g.ensure_initialized()
        return g

    # ------------------------------------------------- per-op emission
    def _absorb_bias(self, node: TFNode, consumers, absorbed):
        """Absorb a following bias-add into a Conv2D/MatMul when it is
        the node's sole consumer. Returns (bias, out_name)."""
        cons = consumers.get(node.name, [])
        if len(cons) == 1:
            b = self.f._bias_of(cons[0])
            if b is not None and [t.name for t in
                                  self._tensor_inputs(cons[0])] \
                    == [node.name]:
                absorbed.add(cons[0].name)
                return b, cons[0].name
        return None, node.name

    def _emit(self, node: TFNode, consumers, absorbed):
        import bigdl_tpu.nn as nn
        f, op = self.f, node.op
        tin = self._tensor_inputs(node)
        for t in tin:
            if t.name not in self.vals:
                raise ValueError(
                    f"fusion: input {t.name} of {node.name} has no "
                    "emitted value (malformed or cyclic graph)")

        if op == "Conv2D":
            _require(node, "data_format", ("NHWC", None))
            _require(node, "padding", ("SAME", "VALID"))
            dil = node.attrs.get("dilations")
            if dil is not None and any(d != 1 for d in dil):
                raise ValueError(
                    f"fusion: dilated Conv2D unsupported ({node.name})")
            wgt = f.const(node.inputs[1])  # HWIO
            kh, kw_ = wgt.shape[0], wgt.shape[1]
            h, w = self.hw[tin[0].name]
            sh, sw = node.attrs["strides"][1:3]
            pad = node.attrs["padding"]
            ph = 0 if pad == "VALID" else _same_pad(h, kh, sh)
            pw = 0 if pad == "VALID" else _same_pad(w, kw_, sw)
            # resolve the input value BEFORE mutating absorbed/presets:
            # mixed mode islands this node on ValueError, and a
            # half-mutated emission would drop the bias and orphan its
            # BiasAdd node
            x_in = self._value_as(tin[0].name, "NCHW")
            bias, out_name = self._absorb_bias(node, consumers, absorbed)
            m = nn.SpatialConvolution(wgt.shape[2], wgt.shape[3], kw_,
                                      kh, sw, sh, pw, ph,
                                      with_bias=bias is not None)
            p = {"weight": np.transpose(wgt, (3, 2, 0, 1))}
            if bias is not None:
                p["bias"] = bias
            self.presets.append((m, p, None))
            gnode = m(x_in)
            self._set(out_name, "NCHW", gnode, "4D",
                      (_out_size(h, kh, sh, ph), _out_size(w, kw_, sw,
                                                           pw)))
        elif op == "MatMul":
            if node.attrs.get("transpose_a") or \
                    node.attrs.get("transpose_b"):
                raise ValueError(
                    f"fusion: transposed MatMul unsupported ({node.name})")
            wgt = f.const(node.inputs[1])
            x_in = self._value_as(tin[0].name, "FLAT")  # before mutation
            bias, out_name = self._absorb_bias(node, consumers, absorbed)
            m = nn.Linear(wgt.shape[0], wgt.shape[1],
                          with_bias=bias is not None)
            p = {"weight": wgt.T}
            if bias is not None:
                p["bias"] = bias
            self.presets.append((m, p, None))
            gnode = m(x_in)
            self._set(out_name, "FLAT", gnode, "FLAT")
        elif op in ("FusedBatchNorm", "FusedBatchNormV2",
                    "FusedBatchNormV3"):
            _require(node, "is_training", (False,))
            _require(node, "data_format", ("NHWC", None))
            scale = f.const(node.inputs[1])
            offset = f.const(node.inputs[2])
            mean = f.const(node.inputs[3])
            var = f.const(node.inputs[4])
            x_in = self._value_as(tin[0].name, "NCHW")  # before mutation
            m = nn.SpatialBatchNormalization(
                len(scale), float(node.attrs.get("epsilon", 1e-3)))
            self.presets.append(
                (m, {"weight": scale, "bias": offset},
                 {"running_mean": mean, "running_var": var}))
            gnode = m(x_in)
            self._set(node.name, "NCHW", gnode, "4D",
                      self.hw[tin[0].name])
        elif op in ("MaxPool", "AvgPool"):
            _require(node, "data_format", ("NHWC", None))
            _require(node, "padding", ("SAME", "VALID"))
            kh, kw_ = node.attrs["ksize"][1:3]
            sh, sw = node.attrs["strides"][1:3]
            h, w = self.hw[tin[0].name]
            pad = node.attrs["padding"]
            ph = 0 if pad == "VALID" else _same_pad(h, kh, sh)
            pw = 0 if pad == "VALID" else _same_pad(w, kw_, sw)
            ceil = pad == "SAME"
            if op == "MaxPool":
                m = nn.SpatialMaxPooling(kw_, kh, sw, sh, pw, ph)
            else:
                m = nn.SpatialAveragePooling(
                    kw_, kh, sw, sh, pw, ph, count_include_pad=False)
            if ceil:
                m = m.ceil()
            gnode = m(self._value_as(tin[0].name, "NCHW"))
            self._set(node.name, "NCHW", gnode, "4D",
                      (_out_size(h, kh, sh, ph, ceil),
                       _out_size(w, kw_, sw, pw, ceil)))
        elif op == "Relu":
            lay = self._natural(tin[0].name)
            gnode = nn.ReLU()(self.vals[tin[0].name][lay])
            self._set(node.name, lay, gnode, self.kind[tin[0].name],
                      self.hw[tin[0].name])
        elif op == "Softmax":
            if self.kind[tin[0].name] == "4D":
                gnode = nn.SoftMax()(self._value_as(tin[0].name, "NHWC"))
                self._set(node.name, "NHWC", gnode, "4D",
                          self.hw[tin[0].name])
            else:
                gnode = nn.SoftMax()(self._value_as(tin[0].name, "FLAT"))
                self._set(node.name, "FLAT", gnode, "FLAT")
        elif op == "Reshape":
            tgt = [int(v) for v in
                   np.asarray(f.const(node.inputs[1])).ravel()]
            # TF flatten reshapes in H,W,C order — feed from NHWC
            src = self._value_as(
                tin[0].name,
                "NHWC" if self.kind[tin[0].name] == "4D" else "FLAT")
            if len(tgt) == 2 and tgt[0] == -1:
                gnode = nn.View(tgt[1])(src)
                self._set(node.name, "FLAT", gnode, "FLAT")
            elif len(tgt) == 4:
                gnode = nn.Reshape(tuple(tgt[1:]))(src)
                self._set(node.name, "NHWC", gnode, "4D",
                          (tgt[1], tgt[2]))
            else:
                gnode = nn.Reshape(tuple(tgt[1:]))(src)
                self._set(node.name, "FLAT", gnode, "FLAT")
        elif op in ("Concat", "ConcatV2"):
            axis_ref = node.inputs[0] if op == "Concat" \
                else node.inputs[-1]
            axis = int(np.asarray(f.const(axis_ref)).ravel()[0])
            kinds = {self.kind[t.name] for t in tin}
            if len(kinds) != 1:
                raise ValueError(
                    f"fusion: concat of mixed-rank values ({node.name})")
            if kinds == {"4D"}:
                if axis < 0:
                    axis += 4
                nchw_axis = self._NHWC2NCHW[axis]
                srcs = [self._value_as(t.name, "NCHW") for t in tin]
                gnode = nn.JoinTable(nchw_axis + 1)(*srcs)
                h, w = self.hw[tin[0].name]
                if axis in (1, 2):  # spatial concat changes H or W
                    sizes = [self.hw[t.name][axis - 1] for t in tin]
                    tot = None if any(s is None for s in sizes) \
                        else sum(sizes)
                    h, w = (tot, w) if axis == 1 else (h, tot)
                self._set(node.name, "NCHW", gnode, "4D", (h, w))
            else:
                if axis < 0:
                    axis += 2
                srcs = [self._value_as(t.name, "FLAT") for t in tin]
                gnode = nn.JoinTable(axis + 1)(*srcs)
                self._set(node.name, "FLAT", gnode, "FLAT")
        elif op in ("Add", "AddV2") and len(tin) == 2:
            kinds = {self.kind[t.name] for t in tin}
            if len(kinds) != 1:
                raise ValueError(
                    f"fusion: add of mixed-rank values ({node.name})")
            lay = "NCHW" if kinds == {"4D"} else "FLAT"
            gnode = nn.CAddTable()(self._value_as(tin[0].name, lay),
                                   self._value_as(tin[1].name, lay))
            self._set(node.name, lay, gnode, self.kind[tin[0].name],
                      self.hw[tin[0].name])
        elif op in ("Add", "AddV2", "BiasAdd") and len(tin) == 1:
            # un-absorbed bias-add (producer has other consumers): a
            # real standalone module would need a broadcast-add layer;
            # fall back (mixed mode wraps it)
            raise ValueError(
                f"fusion: standalone bias-add ({node.name}) not "
                "absorbed; import with TFModule instead")
        else:
            raise ValueError(
                f"fusion table has no pattern for op {op} (node "
                f"{node.name}); import with TFModule instead")

    def _emit_island(self, node: TFNode):
        """Wrap one unsupported node as a single-op TFModule rebuilt
        from raw NodeDef bytes (stays serializable)."""
        from bigdl_tpu.utils.tf_loader import TFModule
        from bigdl_tpu.utils import proto
        tin = self._tensor_inputs(node)
        if len(tin) != 1:
            raise ValueError(
                f"fusion: cannot island multi-input op {node.op} "
                f"({node.name}); import with TFModule instead")
        if getattr(node, "raw", None) is None:
            raise ValueError(
                f"fusion: no raw NodeDef bytes for {node.name} (parse "
                "the graph from bytes to enable mixed mode)")
        # placeholder standing in for the tensor input + the const
        # (and Identity) dependencies this node references
        blob = b""
        ph_name = None
        for ref in node.inputs:
            if ref.startswith("^"):
                continue
            nm = ref.split(":")[0]
            dep = self.f.by_name[nm]
            chain = []
            while dep.op == "Identity":
                chain.append(dep)
                dep = self.f.by_name[
                    dep.inputs[0].split(":")[0].lstrip("^")]
            if dep.op == "Const":
                for c in chain + [dep]:
                    blob += c.raw
            else:
                ph_name = nm
                msg = proto.encode_field(1, nm) + \
                    proto.encode_field(2, "Placeholder")
                blob += proto.encode_message(1, msg)
        blob += node.raw
        m = TFModule(blob, inputs=[ph_name], outputs=[node.name])
        kind = self.kind[tin[0].name]
        lay = "NHWC" if kind == "4D" else "FLAT"
        gnode = m(self._value_as(tin[0].name, lay))
        # unknown op: layout assumed preserved, spatial size UNKNOWN —
        # a downstream stride>1 SAME conv/pool then fails loudly in
        # _same_pad instead of computing padding from a stale H,W
        self._set(node.name, lay, gnode, kind, (None, None))
        self.islands.append(f"{node.name}:{node.op}")


def _is_chain(nodes: List[TFNode], fuser: _Fuser) -> bool:
    """True when every reachable tensor value feeds exactly one
    consumer and no table op (Concat/two-tensor Add) appears."""
    dag = _DagFuser(fuser, mixed=False)
    counts: Dict[str, int] = {}
    for n in nodes:
        if n.op in ("Const", "Placeholder"):
            continue
        if n.op in ("Concat", "ConcatV2"):
            return False
        tin = dag._tensor_inputs(n)
        if n.op in ("Add", "AddV2") and len(tin) == 2:
            return False
        for p in tin:
            counts[p.name] = counts.get(p.name, 0) + 1
    return all(c <= 1 for c in counts.values())


def fuse_tf_graph(nodes_or_bytes,
                  inputs: Optional[Sequence[str]] = None,
                  outputs: Optional[Sequence[str]] = None,
                  mixed: bool = False):
    """GraphDef (bytes or parsed TFNode list) -> real nn modules with
    the TF weights installed (TensorflowToBigDL.scala:1): a
    ``Sequential`` for a pure chain, a ``Graph`` for a branchy DAG
    (Inception-style branch/concat, residual adds).

    The fused module is NHWC-in/NHWC-out like the TF graph, survives
    ``nn.quantized.quantize`` and the module serializer, and — unlike
    ``TFModule`` — reads as layers. With ``mixed=True`` unsupported
    single-input nodes become one-op TFModule islands (listed on
    ``fused_islands``) instead of failing the whole import."""
    if isinstance(nodes_or_bytes, (bytes, bytearray)):
        nodes = parse_graphdef(bytes(nodes_or_bytes))
    else:
        nodes = list(nodes_or_bytes)
    fuser = _Fuser(nodes, inputs, outputs)
    if not mixed and _is_chain(nodes, fuser):
        return fuser.fuse()
    return _DagFuser(fuser, mixed).fuse()
