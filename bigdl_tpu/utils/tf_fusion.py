"""TF graph pattern fusion → structured modules (the reference's
TensorflowToBigDL fusion table, utils/tf/TensorflowToBigDL.scala:1).

``TFModule`` (utils/tf_loader.py) executes an imported GraphDef
op-by-op; that runs and trains, but an op soup cannot be ``quantize()``d
(the rewrite looks for Linear/SpatialConvolution modules), re-exported
through the Caffe/module serializers, or inspected as layers. This pass
pattern-matches the node chain into REAL ``bigdl_tpu.nn`` modules:

    Conv2D [+ BiasAdd]        -> SpatialConvolution
    MatMul [+ BiasAdd]        -> Linear
    FusedBatchNorm{,V2,V3}    -> SpatialBatchNormalization (+ stats)
    MaxPool / AvgPool         -> SpatialMaxPooling / SpatialAveragePooling
    Relu / Softmax / Reshape  -> ReLU / SoftMax / View

Layout: TF graphs are NHWC, the nn modules are NCHW. The pass tracks
the live layout and inserts the minimal ``Transpose`` adapters (one
entering the conv stack, one before a TF-semantics flatten/output), so
the fused module's outputs equal the TF graph's EXACTLY — including the
H,W,C flatten order feeding a Linear.

Scope: linear chains of the ops above (the classic TF1 conv net). An
unsupported op raises with its name — the general fallback path stays
``TFModule``, which executes everything.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.utils.tf_loader import TFNode, parse_graphdef


def _require(node: TFNode, attr: str, allowed) -> None:
    """Fail fast on attrs the fusion table cannot express — a silently
    wrong module is worse than falling back to TFModule."""
    v = node.attrs.get(attr)
    if isinstance(v, bytes):
        v = v.decode()
    if v not in allowed:
        raise ValueError(
            f"fusion: {node.op} with {attr}={v!r} unsupported "
            f"({node.name}); import with TFModule instead")


def _same_pad(n: Optional[int], k: int, s: int) -> int:
    """TF SAME padding for one spatial dim; None size means unknown.
    Returns the symmetric per-side pad or raises if asymmetric."""
    if s == 1:
        total = k - 1
    else:
        if n is None:
            raise ValueError(
                "SAME padding with stride>1 needs a known input size "
                "(give the Placeholder a shape)")
        total = max((-(-n // s) - 1) * s + k - n, 0)
    if total % 2:
        raise ValueError(
            f"TF SAME padding is asymmetric here (total {total}); "
            "SpatialConvolution cannot express it — repad the graph")
    return total // 2


def _out_size(n: Optional[int], k: int, s: int, pad: int,
              ceil_mode: bool = False) -> Optional[int]:
    if n is None:
        return None
    m = n + 2 * pad - k
    return (-(-m // s) if ceil_mode else m // s) + 1


class _Fuser:
    def __init__(self, nodes: List[TFNode], inputs, outputs):
        self.by_name = {n.name: n for n in nodes}
        self.nodes = nodes
        self.consts: Dict[str, np.ndarray] = {
            n.name: np.asarray(n.attrs.get("value"))
            for n in nodes if n.op == "Const"}
        self.input_names = list(inputs) if inputs else [
            n.name for n in nodes if n.op == "Placeholder"]
        if outputs:
            self.output_names = list(outputs)
        else:
            consumed = {i.split(":")[0].lstrip("^")
                        for n in nodes for i in n.inputs}
            self.output_names = [n.name for n in nodes
                                 if n.name not in consumed
                                 and n.op not in ("Const", "Placeholder",
                                                  "NoOp")]

    def const(self, ref: str) -> np.ndarray:
        nm = ref.split(":")[0].lstrip("^")
        node = self.by_name[nm]
        while node.op == "Identity":
            nm = node.inputs[0].split(":")[0].lstrip("^")
            node = self.by_name[nm]
        if nm not in self.consts:
            raise ValueError(
                f"fusion needs a constant weight at {ref}, found "
                f"{node.op} (freeze the graph first)")
        return self.consts[nm]

    def _bias_of(self, node: Optional[TFNode]) -> Optional[np.ndarray]:
        """The constant bias when ``node`` is a bias-add form: BiasAdd,
        or Add/AddV2 with a rank-1 const operand (TF2 freezing lowers
        `y + b` to AddV2)."""
        if node is None or node.op not in ("BiasAdd", "Add", "AddV2"):
            return None
        try:
            b = self.const(node.inputs[1])
        except (ValueError, KeyError):
            return None
        return b if b.ndim == 1 else None

    def fuse(self):
        """Chain walk from the single input to the single output."""
        import bigdl_tpu.nn as nn

        if len(self.input_names) != 1 or len(self.output_names) != 1:
            raise ValueError(
                "fusion covers single-input single-output chains; use "
                "TFModule for general graphs")
        # build the producer chain output <- ... <- input, following the
        # first TENSOR input of each node (weights are const operands)
        chain: List[TFNode] = []
        cur = self.by_name[self.output_names[0]]
        guard = 0
        while cur.name != self.input_names[0]:
            chain.append(cur)
            data_in = None
            for ref in cur.inputs:
                nm = ref.split(":")[0].lstrip("^")
                node = self.by_name[nm]
                while node.op == "Identity":
                    nm = node.inputs[0].split(":")[0].lstrip("^")
                    node = self.by_name[nm]
                if node.op != "Const":
                    data_in = node
                    break
            if data_in is None:
                raise ValueError(f"no tensor input at node {cur.name}")
            cur = data_in
            guard += 1
            if guard > 10000:
                raise ValueError("graph is not a chain")
        chain.reverse()

        placeholder = self.by_name[self.input_names[0]]
        shape = placeholder.attrs.get("shape")
        # spatial sizes tracked through the chain for SAME padding
        h, w = (None, None)
        if shape is not None and len(shape) == 4:
            h = None if shape[1] in (-1, None) else int(shape[1])
            w = None if shape[2] in (-1, None) else int(shape[2])

        seq = nn.Sequential()
        layout = "NHWC"  # the TF graph's native layout
        presets = []     # (module, params, state) to install after init

        def to_nchw():
            nonlocal layout
            if layout == "NHWC":
                seq.add(nn.Transpose([(2, 4), (3, 4)]))
                layout = "NCHW"

        def to_nhwc():
            nonlocal layout
            if layout == "NCHW":
                seq.add(nn.Transpose([(2, 3), (3, 4)]))
                layout = "NHWC"

        i = 0
        while i < len(chain):
            node = chain[i]
            op = node.op
            nxt = chain[i + 1] if i + 1 < len(chain) else None
            if op == "Identity":
                i += 1
            elif op == "Conv2D":
                _require(node, "data_format", ("NHWC", None))
                _require(node, "padding", ("SAME", "VALID"))
                dil = node.attrs.get("dilations")
                if dil is not None and any(d != 1 for d in dil):
                    raise ValueError(
                        f"fusion: dilated Conv2D unsupported ({node.name})"
                        "; import with TFModule instead")
                wgt = self.const(node.inputs[1])  # HWIO
                kh, kw_ = wgt.shape[0], wgt.shape[1]
                cin, cout = wgt.shape[2], wgt.shape[3]
                sh, sw = node.attrs["strides"][1:3]
                pad = node.attrs["padding"]
                ph = 0 if pad == "VALID" else _same_pad(h, kh, sh)
                pw = 0 if pad == "VALID" else _same_pad(w, kw_, sw)
                bias = self._bias_of(nxt)
                if bias is not None:
                    i += 1
                m = nn.SpatialConvolution(cin, cout, kw_, kh, sw, sh,
                                          pw, ph,
                                          with_bias=bias is not None)
                p = {"weight": np.transpose(wgt, (3, 2, 0, 1))}
                if bias is not None:
                    p["bias"] = bias
                presets.append((m, p, None))
                to_nchw()
                seq.add(m)
                h, w = _out_size(h, kh, sh, ph), _out_size(w, kw_, sw, pw)
                i += 1
            elif op == "MatMul":
                if node.attrs.get("transpose_a") or \
                        node.attrs.get("transpose_b"):
                    raise ValueError(
                        f"fusion: transposed MatMul unsupported "
                        f"({node.name}); import with TFModule instead")
                wgt = self.const(node.inputs[1])  # [in, out]
                bias = self._bias_of(nxt)
                if bias is not None:
                    i += 1
                m = nn.Linear(wgt.shape[0], wgt.shape[1],
                              with_bias=bias is not None)
                p = {"weight": wgt.T}
                if bias is not None:
                    p["bias"] = bias
                presets.append((m, p, None))
                seq.add(m)
                i += 1
            elif op in ("FusedBatchNorm", "FusedBatchNormV2",
                        "FusedBatchNormV3"):
                # is_training=True means TF ignores the mean/var const
                # inputs (batch stats instead) — fusing those consts in
                # would silently diverge from the graph; NCHW would put
                # the stats on the wrong channel axis. Fail fast to
                # TFModule like Conv2D/pooling do. NOTE: the op-def
                # DEFAULT for is_training is True, so an absent attr is
                # training mode too — only an explicit False may fuse.
                _require(node, "is_training", (False,))
                _require(node, "data_format", ("NHWC", None))
                scale = self.const(node.inputs[1])
                offset = self.const(node.inputs[2])
                mean = self.const(node.inputs[3])
                var = self.const(node.inputs[4])
                eps = float(node.attrs.get("epsilon", 1e-3))
                m = nn.SpatialBatchNormalization(len(scale), eps)
                presets.append((m, {"weight": scale, "bias": offset},
                                {"running_mean": mean,
                                 "running_var": var}))
                to_nchw()
                seq.add(m)
                i += 1
            elif op in ("MaxPool", "AvgPool"):
                _require(node, "data_format", ("NHWC", None))
                _require(node, "padding", ("SAME", "VALID"))
                kh, kw_ = node.attrs["ksize"][1:3]
                sh, sw = node.attrs["strides"][1:3]
                pad = node.attrs["padding"]
                ph = 0 if pad == "VALID" else _same_pad(h, kh, sh)
                pw = 0 if pad == "VALID" else _same_pad(w, kw_, sw)
                ceil = pad == "SAME"  # TF SAME pooling covers the tail
                if op == "MaxPool":
                    m = nn.SpatialMaxPooling(kw_, kh, sw, sh, pw, ph)
                else:
                    # TF AvgPool excludes padding from the divisor, the
                    # Torch count_include_pad=False convention
                    m = nn.SpatialAveragePooling(
                        kw_, kh, sw, sh, pw, ph, count_include_pad=False)
                if ceil:
                    m = m.ceil()
                to_nchw()
                seq.add(m)
                h = _out_size(h, kh, sh, ph, ceil)
                w = _out_size(w, kw_, sw, pw, ceil)
                i += 1
            elif op == "Relu":
                seq.add(nn.ReLU())
                i += 1
            elif op == "Softmax":
                to_nhwc()
                seq.add(nn.SoftMax())
                i += 1
            elif op == "Reshape":
                tgt = [int(v) for v in
                       np.asarray(self.const(node.inputs[1])).ravel()]
                # TF flatten reshapes in H,W,C order — return to NHWC
                # first so the following Linear's weights line up
                to_nhwc()
                if len(tgt) == 2 and tgt[0] == -1:
                    seq.add(nn.View(tgt[1]))
                else:
                    seq.add(nn.Reshape(tuple(tgt[1:])))
                i += 1
            else:
                raise ValueError(
                    f"fusion table has no pattern for op {op} (node "
                    f"{node.name}); import with TFModule instead")
        to_nhwc()  # a 4-D output leaves in the graph's own layout

        import jax.numpy as jnp
        # install weights BEFORE the container initializes: Container.init
        # adopts a child's already-materialized params (the importer
        # contract, nn/container.py adopt_or_init)
        for m, p, s in presets:
            m.set_parameters({k: jnp.asarray(v) for k, v in p.items()})
            if s is not None:
                m.set_state({k: jnp.asarray(v) for k, v in s.items()})
        seq.evaluate()
        seq.ensure_initialized()
        return seq


def fuse_tf_graph(nodes_or_bytes,
                  inputs: Optional[Sequence[str]] = None,
                  outputs: Optional[Sequence[str]] = None):
    """GraphDef (bytes or parsed TFNode list) -> a Sequential of real
    nn modules with the TF weights installed (TensorflowToBigDL.scala:1).

    The fused module is NHWC-in/NHWC-out like the TF graph, survives
    ``nn.quantized.quantize`` and the module serializer, and — unlike
    ``TFModule`` — reads as layers."""
    if isinstance(nodes_or_bytes, (bytes, bytearray)):
        nodes = parse_graphdef(bytes(nodes_or_bytes))
    else:
        nodes = list(nodes_or_bytes)
    return _Fuser(nodes, inputs, outputs).fuse()
