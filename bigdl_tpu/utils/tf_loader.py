"""TensorFlow GraphDef import & execution (reference: utils/tf/
TensorflowLoader.scala:43 — parse GraphDef :88, build graph :160 — plus the
81 per-op importers in utils/tf/loaders/ and Session execution,
Session.scala:104).

Decodes the frozen-graph protobuf with the in-repo wire codec (no TF
dependency at runtime) and executes the node DAG with jax ops under jit —
the TPU-native analogue of the reference's nn/ops graph execution.
"""
from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils import proto

# tensorflow DataType enum -> numpy (14 = DT_BFLOAT16, 19 = DT_HALF)
import ml_dtypes as _ml_dtypes

_DTYPES = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
           5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_,
           14: _ml_dtypes.bfloat16, 19: np.float16}


def _parse_shape(buf: bytes) -> List[int]:
    f = proto.parse_message(buf)
    dims = []
    for d in f.get(2, []):
        df = proto.parse_message(d)
        dims.append(proto.as_sint(df.get(1, [0])[0]))
    return dims


def _parse_tensor(buf: bytes) -> np.ndarray:
    """TensorProto: dtype=1, tensor_shape=2, tensor_content=4,
    float_val=5, double_val=6, int_val=7, int64_val=10, bool_val=11."""
    f = proto.parse_message(buf)
    dtype = _DTYPES.get(f.get(1, [1])[0], np.float32)
    shape = _parse_shape(f[2][0]) if 2 in f else []
    if 4 in f and f[4][0]:
        arr = np.frombuffer(f[4][0], dtype=dtype)
    else:
        vals: List = []
        for field, conv in ((5, proto.as_float), (6, proto.as_double)):
            for raw in f.get(field, []):
                if isinstance(raw, bytes):
                    if field == 5 and len(raw) % 4 == 0 and len(raw) > 4:
                        vals.extend(proto.unpack_packed_floats(raw))
                    elif field == 6 and len(raw) % 8 == 0 and len(raw) > 8:
                        vals.extend(proto.unpack_packed_doubles(raw))
                    else:
                        vals.append(conv(raw))
                else:
                    vals.append(raw)
        for field in (7, 10, 11):
            for raw in f.get(field, []):
                if isinstance(raw, bytes):
                    vals.extend(proto.as_sint(v)
                                for v in proto.unpack_packed_varints(raw))
                else:
                    vals.append(proto.as_sint(raw))
        arr = np.asarray(vals, dtype=dtype)
    n = int(np.prod(shape)) if shape else arr.size
    if arr.size == 1 and n > 1:  # scalar splat
        arr = np.full(n, arr[0], dtype=dtype)
    return arr.reshape(shape) if shape else (
        arr.reshape(()) if arr.size == 1 else arr)


def _parse_attr(buf: bytes) -> Any:
    """AttrValue: list=1, s=2, i=3, f=4, b=5, type=6, shape=7, tensor=8."""
    f = proto.parse_message(buf)
    if 2 in f:
        return f[2][0].decode("utf-8", "replace")
    if 3 in f:
        return proto.as_sint(f[3][0])
    if 4 in f:
        return proto.as_float(f[4][0])
    if 5 in f:
        return bool(f[5][0])
    if 6 in f:
        return _DTYPES.get(f[6][0], np.float32)
    if 7 in f:
        return _parse_shape(f[7][0])
    if 8 in f:
        return _parse_tensor(f[8][0])
    if 1 in f:
        lf = proto.parse_message(f[1][0])
        out = []
        for raw in lf.get(3, []):  # ints (packed or not)
            if isinstance(raw, bytes):
                out.extend(proto.as_sint(v)
                           for v in proto.unpack_packed_varints(raw))
            else:
                out.append(proto.as_sint(raw))
        if out:
            return out
        floats: List[float] = []
        for r in lf.get(4, []):  # list(float): packed fixed32 or single
            if isinstance(r, bytes):
                if len(r) > 4 and len(r) % 4 == 0:
                    floats.extend(proto.unpack_packed_floats(r))
                else:
                    floats.append(proto.as_float(r))
            else:
                floats.append(r)
        return floats
    return None


class TFNode:
    def __init__(self, name: str, op: str, inputs: List[str],
                 attrs: Dict[str, Any]):
        self.name = name
        self.op = op
        self.inputs = inputs
        self.attrs = attrs

    def __repr__(self):
        return f"TFNode({self.name}:{self.op})"


def parse_graphdef(data: bytes) -> List[TFNode]:
    nodes = []
    for buf in proto.parse_message(data).get(1, []):
        f = proto.parse_message(buf)
        name = proto.as_string(f.get(1, [b""])[0])
        op = proto.as_string(f.get(2, [b""])[0])
        inputs = [proto.as_string(b) for b in f.get(3, [])]
        attrs = {}
        for ab in f.get(5, []):
            af = proto.parse_message(ab)
            key = proto.as_string(af.get(1, [b""])[0])
            attrs[key] = _parse_attr(af.get(2, [b""])[0])
        nodes.append(TFNode(name, op, inputs, attrs))
    return nodes


# ------------------------------------------------------------ op registry

def _pool(kind):
    def run(node, xs):
        x = xs[0]
        ksize = node.attrs.get("ksize", [1, 1, 1, 1])
        strides = node.attrs.get("strides", [1, 1, 1, 1])
        pad = node.attrs.get("padding", "VALID")
        fn = jax.lax.max if kind == "max" else jax.lax.add
        init = (-jnp.inf if kind == "max" else 0.0)
        out = jax.lax.reduce_window(
            x, init, fn, tuple(ksize), tuple(strides), pad)
        if kind == "avg":
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, tuple(ksize), tuple(strides), pad)
            out = out / counts
        return out
    return run


def _conv2d(node, xs):
    x, w = xs[0], xs[1]  # NHWC, HWIO
    strides = node.attrs.get("strides", [1, 1, 1, 1])
    pad = node.attrs.get("padding", "VALID")
    dil = node.attrs.get("dilations", [1, 1, 1, 1])
    return jax.lax.conv_general_dilated(
        x, w, window_strides=tuple(strides[1:3]), padding=pad,
        rhs_dilation=tuple(dil[1:3]),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _depthwise_conv2d(node, xs):
    x, w = xs[0], xs[1]  # w: [H,W,Cin,M]
    strides = node.attrs.get("strides", [1, 1, 1, 1])
    pad = node.attrs.get("padding", "VALID")
    h, ww, cin, mult = w.shape
    w2 = w.reshape(h, ww, 1, cin * mult)
    return jax.lax.conv_general_dilated(
        x, w2, window_strides=tuple(strides[1:3]), padding=pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=cin)


def _fused_bn(node, xs):
    x, scale, offset, mean, var = xs[:5]
    eps = node.attrs.get("epsilon", 1e-3)
    inv = jax.lax.rsqrt(var + eps) * scale
    return x * inv + (offset - mean * inv)


def _matmul(node, xs):
    a, b = xs[0], xs[1]
    if node.attrs.get("transpose_a"):
        a = a.T
    if node.attrs.get("transpose_b"):
        b = b.T
    return a @ b


_OPS: Dict[str, Callable] = {
    "Identity": lambda n, xs: xs[0],
    "StopGradient": lambda n, xs: jax.lax.stop_gradient(xs[0]),
    "MatMul": _matmul,
    "BatchMatMulV2": lambda n, xs: jnp.matmul(xs[0], xs[1]),
    "Add": lambda n, xs: xs[0] + xs[1],
    "AddV2": lambda n, xs: xs[0] + xs[1],
    "BiasAdd": lambda n, xs: xs[0] + xs[1],
    "Sub": lambda n, xs: xs[0] - xs[1],
    "Mul": lambda n, xs: xs[0] * xs[1],
    "RealDiv": lambda n, xs: xs[0] / xs[1],
    "Maximum": lambda n, xs: jnp.maximum(xs[0], xs[1]),
    "Minimum": lambda n, xs: jnp.minimum(xs[0], xs[1]),
    "Square": lambda n, xs: jnp.square(xs[0]),
    "Sqrt": lambda n, xs: jnp.sqrt(xs[0]),
    "Rsqrt": lambda n, xs: jax.lax.rsqrt(xs[0]),
    "Exp": lambda n, xs: jnp.exp(xs[0]),
    "Log": lambda n, xs: jnp.log(xs[0]),
    "Neg": lambda n, xs: -xs[0],
    "Abs": lambda n, xs: jnp.abs(xs[0]),
    "Relu": lambda n, xs: jax.nn.relu(xs[0]),
    "Relu6": lambda n, xs: jnp.clip(xs[0], 0, 6),
    "LeakyRelu": lambda n, xs: jax.nn.leaky_relu(
        xs[0], n.attrs.get("alpha", 0.2)),
    "Elu": lambda n, xs: jax.nn.elu(xs[0]),
    "Sigmoid": lambda n, xs: jax.nn.sigmoid(xs[0]),
    "Tanh": lambda n, xs: jnp.tanh(xs[0]),
    "Softmax": lambda n, xs: jax.nn.softmax(xs[0], axis=-1),
    "LogSoftmax": lambda n, xs: jax.nn.log_softmax(xs[0], axis=-1),
    "Softplus": lambda n, xs: jax.nn.softplus(xs[0]),
    "Reshape": lambda n, xs: jnp.reshape(
        xs[0], [int(v) for v in np.asarray(xs[1]).ravel()]),
    "Squeeze": lambda n, xs: jnp.squeeze(
        xs[0], axis=tuple(n.attrs["squeeze_dims"])
        if n.attrs.get("squeeze_dims") else None),
    "ExpandDims": lambda n, xs: jnp.expand_dims(xs[0], int(xs[1])),
    "Transpose": lambda n, xs: jnp.transpose(
        xs[0], [int(v) for v in np.asarray(xs[1]).ravel()]),
    "ConcatV2": lambda n, xs: jnp.concatenate(xs[:-1], axis=int(xs[-1])),
    "Pad": lambda n, xs: jnp.pad(
        xs[0], [(int(a), int(b)) for a, b in np.asarray(xs[1])]),
    "PadV2": lambda n, xs: jnp.pad(
        xs[0], [(int(a), int(b)) for a, b in np.asarray(xs[1])],
        constant_values=float(np.asarray(xs[2]))),
    "Mean": lambda n, xs: jnp.mean(
        xs[0], axis=tuple(int(v) for v in np.asarray(xs[1]).ravel()),
        keepdims=bool(n.attrs.get("keep_dims", False))),
    "Sum": lambda n, xs: jnp.sum(
        xs[0], axis=tuple(int(v) for v in np.asarray(xs[1]).ravel()),
        keepdims=bool(n.attrs.get("keep_dims", False))),
    "Max": lambda n, xs: jnp.max(
        xs[0], axis=tuple(int(v) for v in np.asarray(xs[1]).ravel()),
        keepdims=bool(n.attrs.get("keep_dims", False))),
    "Cast": lambda n, xs: xs[0].astype(n.attrs.get("DstT", np.float32)),
    "Shape": lambda n, xs: jnp.asarray(xs[0].shape, jnp.int32),
    "Conv2D": _conv2d,
    "DepthwiseConv2dNative": _depthwise_conv2d,
    "MaxPool": _pool("max"),
    "AvgPool": _pool("avg"),
    "FusedBatchNorm": _fused_bn,
    "FusedBatchNormV3": _fused_bn,
    "Pack": lambda n, xs: jnp.stack(xs, axis=n.attrs.get("axis", 0)),
    "StridedSlice": lambda n, xs: _strided_slice(n, xs),
    "GatherV2": lambda n, xs: jnp.take(xs[0], xs[1].astype(jnp.int32),
                                       axis=int(xs[2])),
    "Rank": lambda n, xs: jnp.asarray(xs[0].ndim, jnp.int32),
    "NoOp": lambda n, xs: None,
}


def _strided_slice(node, xs):
    x, begin, end, strides = xs[:4]
    begin = [int(v) for v in np.asarray(begin).ravel()]
    end = [int(v) for v in np.asarray(end).ravel()]
    strides = [int(v) for v in np.asarray(strides).ravel()]
    slices = []
    shrink = node.attrs.get("shrink_axis_mask", 0) or 0
    begin_mask = node.attrs.get("begin_mask", 0) or 0
    end_mask = node.attrs.get("end_mask", 0) or 0
    for i, (b, e, s) in enumerate(zip(begin, end, strides)):
        if shrink & (1 << i):
            slices.append(b)
            continue
        bb = None if (begin_mask & (1 << i)) else b
        ee = None if (end_mask & (1 << i)) else e
        slices.append(slice(bb, ee, s))
    return x[tuple(slices)]


class TFModule(Module):
    """Executes an imported frozen GraphDef as a Module.

    inputs/outputs: node names (Placeholders default as inputs). The whole
    node walk happens at trace time, so the module jits/differentiates
    like native layers (the reference's Session.run analogue).
    """

    def __init__(self, nodes,
                 inputs: Optional[Sequence[str]] = None,
                 outputs: Optional[Sequence[str]] = None):
        super().__init__()
        if isinstance(nodes, (bytes, bytearray)):
            # raw GraphDef bytes: keeps the module serializable through
            # save_module (ctor-arg capture stores the bytes, not the
            # parsed TFNode objects with numpy-dtype attrs)
            nodes = parse_graphdef(bytes(nodes))
        self.nodes = list(nodes)
        self.by_name = {n.name: n for n in self.nodes}
        self.input_names = list(inputs) if inputs else [
            n.name for n in self.nodes if n.op == "Placeholder"]
        if outputs:
            self.output_names = list(outputs)
        else:
            consumed = {inp.split(":")[0].lstrip("^")
                        for n in self.nodes for inp in n.inputs}
            # orphan Consts/Placeholders (pruning leftovers) are not
            # outputs
            self.output_names = [n.name for n in self.nodes
                                 if n.name not in consumed
                                 and n.op not in ("NoOp", "Const",
                                                  "Placeholder")]
        self.consts = {n.name: _ensure_array(n.attrs.get("value"))
                       for n in self.nodes if n.op == "Const"}

    def forward_fn(self, params, input, *, training=False, rng=None):
        from bigdl_tpu.utils.table import Table, T
        if isinstance(input, (Table, list, tuple)):
            feed = {name: x for name, x in zip(self.input_names,
                                               list(input))}
        else:
            feed = {self.input_names[0]: input}
        values: Dict[str, Any] = {}

        def evaluate(ref: str):
            name = ref.split(":")[0].lstrip("^")
            out_idx = int(ref.split(":")[1]) if ":" in ref else 0
            if name in values:
                v = values[name]
            elif name in feed:
                v = values[name] = jnp.asarray(feed[name])
            elif name in self.consts:
                v = values[name] = jnp.asarray(self.consts[name])
            else:
                node = self.by_name[name]
                xs = [evaluate(i) for i in node.inputs
                      if not i.startswith("^")]
                fn = _OPS.get(node.op)
                if fn is None:
                    raise ValueError(
                        f"unsupported TF op {node.op} (node {name})")
                v = values[name] = fn(node, xs)
            if isinstance(v, tuple):
                return v[out_idx]
            return v

        outs = [evaluate(o) for o in self.output_names]
        return outs[0] if len(outs) == 1 else T(*outs)


def _ensure_array(v):
    if v is None:
        return np.zeros((), np.float32)
    return np.asarray(v)


# saved/loaded by name through save_module/load_module
from bigdl_tpu.utils.module_serializer import register_module_class

register_module_class(TFModule)


def load_tf_graph(path: str, inputs: Optional[Sequence[str]] = None,
                  outputs: Optional[Sequence[str]] = None) -> TFModule:
    """Module.loadTF equivalent: read a frozen .pb GraphDef."""
    with open(path, "rb") as f:
        data = f.read()
    nodes = parse_graphdef(data)
    if not nodes:
        raise ValueError(f"no nodes parsed from {path}")
    m = TFModule(nodes, inputs, outputs)
    # serialize via the raw bytes, not the parsed TFNode objects
    m._init_args = (data, inputs, outputs)
    m._init_kwargs = {}
    return m
