"""TensorFlow GraphDef import & execution (reference: utils/tf/
TensorflowLoader.scala:43 — parse GraphDef :88, build graph :160 — plus the
81 per-op importers in utils/tf/loaders/ and Session execution,
Session.scala:104).

Decodes the frozen-graph protobuf with the in-repo wire codec (no TF
dependency at runtime) and executes the node DAG with jax ops under jit —
the TPU-native analogue of the reference's nn/ops graph execution.
"""
from __future__ import annotations

import struct
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils import proto

# tensorflow DataType enum -> numpy (14 = DT_BFLOAT16, 19 = DT_HALF)
import ml_dtypes as _ml_dtypes

_DTYPES = {1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
           5: np.int16, 6: np.int8, 7: np.object_, 9: np.int64,
           10: np.bool_, 14: _ml_dtypes.bfloat16, 19: np.float16}


def _parse_shape(buf: bytes) -> List[int]:
    f = proto.parse_message(buf)
    dims = []
    for d in f.get(2, []):
        df = proto.parse_message(d)
        dims.append(proto.as_sint(df.get(1, [0])[0]))
    return dims


def _parse_tensor(buf: bytes) -> np.ndarray:
    """TensorProto: dtype=1, tensor_shape=2, tensor_content=4,
    float_val=5, double_val=6, int_val=7, string_val=8, int64_val=10,
    bool_val=11."""
    f = proto.parse_message(buf)
    dtype_enum = f.get(1, [1])[0]
    dtype = _DTYPES.get(dtype_enum, np.float32)
    shape = _parse_shape(f[2][0]) if 2 in f else []
    if dtype_enum == 7:  # DT_STRING: object array of bytes
        vals = [bytes(v) for v in f.get(8, [])]
        arr = np.empty(len(vals), object)
        arr[:] = vals
        if shape:
            return arr.reshape(shape)
        return arr.reshape(()) if arr.size == 1 else arr
    if 4 in f and f[4][0]:
        arr = np.frombuffer(f[4][0], dtype=dtype)
    else:
        vals: List = []
        for field, conv in ((5, proto.as_float), (6, proto.as_double)):
            for raw in f.get(field, []):
                if isinstance(raw, bytes):
                    if field == 5 and len(raw) % 4 == 0 and len(raw) > 4:
                        vals.extend(proto.unpack_packed_floats(raw))
                    elif field == 6 and len(raw) % 8 == 0 and len(raw) > 8:
                        vals.extend(proto.unpack_packed_doubles(raw))
                    else:
                        vals.append(conv(raw))
                else:
                    vals.append(raw)
        for field in (7, 10, 11):
            for raw in f.get(field, []):
                if isinstance(raw, bytes):
                    vals.extend(proto.as_sint(v)
                                for v in proto.unpack_packed_varints(raw))
                else:
                    vals.append(proto.as_sint(raw))
        arr = np.asarray(vals, dtype=dtype)
    n = int(np.prod(shape)) if shape else max(arr.size, 1)
    if arr.size < n:
        if 4 in f and f[4][0]:
            # tensor_content is never repeat-compressed: short content
            # means a truncated/corrupt buffer, not compression
            raise ValueError(
                f"tensor_content holds {arr.size} elements, shape needs "
                f"{n}")
        # the VALUE-LIST form compresses trailing repeats: pad with the
        # LAST stored value (tensor_util.MakeNdarray semantics); an
        # entirely omitted list means all zeros (proto3 drops zeros)
        fill = arr[-1] if arr.size else np.zeros((), dtype=dtype)
        arr = np.concatenate(
            [arr, np.full(n - arr.size, fill, dtype=dtype)])
    return arr.reshape(shape) if shape else (
        arr.reshape(()) if arr.size == 1 else arr)


def _parse_attr(buf: bytes) -> Any:
    """AttrValue: list=1, s=2, i=3, f=4, b=5, type=6, shape=7, tensor=8."""
    f = proto.parse_message(buf)
    if 2 in f:
        return f[2][0].decode("utf-8", "replace")
    if 3 in f:
        return proto.as_sint(f[3][0])
    if 4 in f:
        return proto.as_float(f[4][0])
    if 5 in f:
        return bool(f[5][0])
    if 6 in f:
        return _DTYPES.get(f[6][0], np.float32)
    if 7 in f:
        return _parse_shape(f[7][0])
    if 8 in f:
        return _parse_tensor(f[8][0])
    if 1 in f:
        lf = proto.parse_message(f[1][0])
        if 2 in lf:   # list(string)
            return [proto.as_string(b) for b in lf[2]]
        if 6 in lf:   # list(type)
            types = []
            for raw in lf[6]:
                if isinstance(raw, bytes):
                    types.extend(_DTYPES.get(v, np.float32)
                                 for v in proto.unpack_packed_varints(raw))
                else:
                    types.append(_DTYPES.get(raw, np.float32))
            return types
        if 7 in lf:   # list(shape)
            return [_parse_shape(b) for b in lf[7]]
        out = []
        for raw in lf.get(3, []):  # ints (packed or not)
            if isinstance(raw, bytes):
                out.extend(proto.as_sint(v)
                           for v in proto.unpack_packed_varints(raw))
            else:
                out.append(proto.as_sint(raw))
        if out:
            return out
        floats: List[float] = []
        for r in lf.get(4, []):  # list(float): packed fixed32 or single
            if isinstance(r, bytes):
                if len(r) > 4 and len(r) % 4 == 0:
                    floats.extend(proto.unpack_packed_floats(r))
                else:
                    floats.append(proto.as_float(r))
            else:
                floats.append(r)
        return floats
    return None


class TFNode:
    """One parsed GraphDef NodeDef (name/op/inputs/attrs; ``raw``
    keeps the wire record for re-emission)."""
    def __init__(self, name: str, op: str, inputs: List[str],
                 attrs: Dict[str, Any]):
        self.name = name
        self.op = op
        self.inputs = inputs
        self.attrs = attrs

    def __repr__(self):
        return f"TFNode({self.name}:{self.op})"


def parse_graphdef(data: bytes) -> List[TFNode]:
    """Frozen GraphDef bytes -> [TFNode] via the in-repo protobuf
    codec (no tensorflow dependency)."""
    nodes = []
    for buf in proto.parse_message(data).get(1, []):
        f = proto.parse_message(buf)
        name = proto.as_string(f.get(1, [b""])[0])
        op = proto.as_string(f.get(2, [b""])[0])
        inputs = [proto.as_string(b) for b in f.get(3, [])]
        attrs = {}
        for ab in f.get(5, []):
            af = proto.parse_message(ab)
            key = proto.as_string(af.get(1, [b""])[0])
            attrs[key] = _parse_attr(af.get(2, [b""])[0])
        n = TFNode(name, op, inputs, attrs)
        # raw wire record (length-delimited field 1): lets consumers
        # re-emit this exact NodeDef into a sub-GraphDef (tf_fusion's
        # mixed-mode TFModule islands stay byte-serializable)
        n.raw = proto.encode_message(1, buf)
        nodes.append(n)
    return nodes


# ------------------------------------------------------------ op registry

def _pool(kind):
    def run(node, xs):
        x = xs[0]
        ksize = node.attrs.get("ksize", [1, 1, 1, 1])
        strides = node.attrs.get("strides", [1, 1, 1, 1])
        pad = node.attrs.get("padding", "VALID")
        fn = jax.lax.max if kind == "max" else jax.lax.add
        init = (-jnp.inf if kind == "max" else 0.0)
        out = jax.lax.reduce_window(
            x, init, fn, tuple(ksize), tuple(strides), pad)
        if kind == "avg":
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, tuple(ksize), tuple(strides), pad)
            out = out / counts
        return out
    return run


def _conv2d(node, xs):
    x, w = xs[0], xs[1]  # NHWC, HWIO
    strides = node.attrs.get("strides", [1, 1, 1, 1])
    pad = node.attrs.get("padding", "VALID")
    dil = node.attrs.get("dilations", [1, 1, 1, 1])
    return jax.lax.conv_general_dilated(
        x, w, window_strides=tuple(strides[1:3]), padding=pad,
        rhs_dilation=tuple(dil[1:3]),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _depthwise_conv2d(node, xs):
    x, w = xs[0], xs[1]  # w: [H,W,Cin,M]
    strides = node.attrs.get("strides", [1, 1, 1, 1])
    pad = node.attrs.get("padding", "VALID")
    h, ww, cin, mult = w.shape
    w2 = w.reshape(h, ww, 1, cin * mult)
    return jax.lax.conv_general_dilated(
        x, w2, window_strides=tuple(strides[1:3]), padding=pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=cin)


def _fused_bn(node, xs):
    x, scale, offset, mean, var = xs[:5]
    eps = node.attrs.get("epsilon", 1e-3)
    inv = jax.lax.rsqrt(var + eps) * scale
    return x * inv + (offset - mean * inv)


def _matmul(node, xs):
    a, b = xs[0], xs[1]
    if node.attrs.get("transpose_a"):
        a = a.T
    if node.attrs.get("transpose_b"):
        b = b.T
    return a @ b


def _reduce_op(fn):
    def run(node, xs):
        return fn(xs[0],
                  axis=tuple(int(v) for v in np.asarray(xs[1]).ravel()),
                  keepdims=bool(node.attrs.get("keep_dims", False)))
    return run


def _select_v1(node, xs):
    cond, t, e = xs
    cond = jnp.asarray(cond)
    if cond.ndim == 1 and jnp.ndim(t) > 1:
        # v1 Select broadcasts a vector cond along axis 0 (row select)
        cond = cond.reshape((cond.shape[0],) + (1,) * (jnp.ndim(t) - 1))
    return jnp.where(cond, t, e)


_OPS: Dict[str, Callable] = {
    "Identity": lambda n, xs: xs[0],
    "ReadVariableOp": lambda n, xs: xs[0],
    "StopGradient": lambda n, xs: jax.lax.stop_gradient(xs[0]),
    "MatMul": _matmul,
    "BatchMatMulV2": lambda n, xs: jnp.matmul(xs[0], xs[1]),
    "Add": lambda n, xs: xs[0] + xs[1],
    "AddV2": lambda n, xs: xs[0] + xs[1],
    "BiasAdd": lambda n, xs: xs[0] + xs[1],
    "Sub": lambda n, xs: xs[0] - xs[1],
    "Mul": lambda n, xs: xs[0] * xs[1],
    "RealDiv": lambda n, xs: xs[0] / xs[1],
    "Maximum": lambda n, xs: jnp.maximum(xs[0], xs[1]),
    "Minimum": lambda n, xs: jnp.minimum(xs[0], xs[1]),
    "Square": lambda n, xs: jnp.square(xs[0]),
    "Sqrt": lambda n, xs: jnp.sqrt(xs[0]),
    "Rsqrt": lambda n, xs: jax.lax.rsqrt(xs[0]),
    "Exp": lambda n, xs: jnp.exp(xs[0]),
    "Log": lambda n, xs: jnp.log(xs[0]),
    "Neg": lambda n, xs: -xs[0],
    "Abs": lambda n, xs: jnp.abs(xs[0]),
    "Relu": lambda n, xs: jax.nn.relu(xs[0]),
    "Relu6": lambda n, xs: jnp.clip(xs[0], 0, 6),
    "LeakyRelu": lambda n, xs: jax.nn.leaky_relu(
        xs[0], n.attrs.get("alpha", 0.2)),
    "Elu": lambda n, xs: jax.nn.elu(xs[0]),
    "Sigmoid": lambda n, xs: jax.nn.sigmoid(xs[0]),
    "Tanh": lambda n, xs: jnp.tanh(xs[0]),
    "Softmax": lambda n, xs: jax.nn.softmax(xs[0], axis=-1),
    "LogSoftmax": lambda n, xs: jax.nn.log_softmax(xs[0], axis=-1),
    "Softplus": lambda n, xs: jax.nn.softplus(xs[0]),
    # the loss heads exported training graphs carry (Session.scala
    # trains against the graph's own loss; loaders/…CrossEntropy…):
    # outputs are (per-example loss, backprop gradient)
    "SoftmaxCrossEntropyWithLogits": lambda n, xs: (
        -(jnp.asarray(xs[1])
          * jax.nn.log_softmax(xs[0], axis=-1)).sum(-1),
        jax.nn.softmax(xs[0], axis=-1) - jnp.asarray(xs[1])),
    "SparseSoftmaxCrossEntropyWithLogits": lambda n, xs: (
        -jnp.take_along_axis(
            jax.nn.log_softmax(xs[0], axis=-1),
            jnp.asarray(xs[1], jnp.int32)[:, None], axis=-1)[:, 0],
        jax.nn.softmax(xs[0], axis=-1)
        - jax.nn.one_hot(jnp.asarray(xs[1], jnp.int32),
                         xs[0].shape[-1], dtype=xs[0].dtype)),
    "Gather": lambda n, xs: jnp.take(
        xs[0], jnp.asarray(xs[1], jnp.int32), axis=0),
    "Split": lambda n, xs: tuple(jnp.split(
        xs[1], int(n.attrs.get("num_split", 1)), axis=int(xs[0]))),
    "SplitV": lambda n, xs: tuple(jnp.split(
        xs[0], np.cumsum(np.asarray(xs[1]).astype(int))[:-1].tolist(),
        axis=int(np.asarray(xs[2])))),
    "TopKV2": lambda n, xs: tuple(jax.lax.top_k(
        xs[0], int(np.asarray(xs[1])))),
    "Reshape": lambda n, xs: jnp.reshape(
        xs[0], [int(v) for v in np.asarray(xs[1]).ravel()]),
    "Squeeze": lambda n, xs: jnp.squeeze(
        xs[0], axis=tuple(n.attrs["squeeze_dims"])
        if n.attrs.get("squeeze_dims") else None),
    "ExpandDims": lambda n, xs: jnp.expand_dims(xs[0], int(xs[1])),
    "Transpose": lambda n, xs: jnp.transpose(
        xs[0], [int(v) for v in np.asarray(xs[1]).ravel()]),
    "ConcatV2": lambda n, xs: jnp.concatenate(xs[:-1], axis=int(xs[-1])),
    "Pad": lambda n, xs: jnp.pad(
        xs[0], [(int(a), int(b)) for a, b in np.asarray(xs[1])]),
    "PadV2": lambda n, xs: jnp.pad(
        xs[0], [(int(a), int(b)) for a, b in np.asarray(xs[1])],
        constant_values=float(np.asarray(xs[2]))),
    "Mean": _reduce_op(jnp.mean),
    "Sum": _reduce_op(jnp.sum),
    "Max": _reduce_op(jnp.max),
    "Cast": lambda n, xs: xs[0].astype(n.attrs.get("DstT", np.float32)),
    "Shape": lambda n, xs: jnp.asarray(xs[0].shape, jnp.int32),
    "Conv2D": _conv2d,
    "DepthwiseConv2dNative": _depthwise_conv2d,
    "MaxPool": _pool("max"),
    "AvgPool": _pool("avg"),
    "FusedBatchNorm": _fused_bn,
    "FusedBatchNormV3": _fused_bn,
    "Pack": lambda n, xs: jnp.stack(xs, axis=n.attrs.get("axis", 0)),
    "StridedSlice": lambda n, xs: _strided_slice(n, xs),
    "GatherV2": lambda n, xs: jnp.take(xs[0], xs[1].astype(jnp.int32),
                                       axis=int(xs[2])),
    "Rank": lambda n, xs: jnp.asarray(xs[0].ndim, jnp.int32),
    "NoOp": lambda n, xs: None,
    # arithmetic/rounding/comparison tail (utils/tf/loaders per-op
    # importers: Floor.scala, Pow.scala, Greater.scala, Select.scala, ...)
    "Floor": lambda n, xs: jnp.floor(xs[0]),
    "Ceil": lambda n, xs: jnp.ceil(xs[0]),
    "Round": lambda n, xs: jnp.round(xs[0]),
    "Sign": lambda n, xs: jnp.sign(xs[0]),
    "Pow": lambda n, xs: jnp.power(xs[0], xs[1]),
    "SquaredDifference": lambda n, xs: jnp.square(xs[0] - xs[1]),
    "FloorDiv": lambda n, xs: jnp.floor_divide(xs[0], xs[1]),
    "FloorMod": lambda n, xs: jnp.mod(xs[0], xs[1]),
    "Greater": lambda n, xs: xs[0] > xs[1],
    "GreaterEqual": lambda n, xs: xs[0] >= xs[1],
    "Less": lambda n, xs: xs[0] < xs[1],
    "LessEqual": lambda n, xs: xs[0] <= xs[1],
    "Equal": lambda n, xs: xs[0] == xs[1],
    "NotEqual": lambda n, xs: xs[0] != xs[1],
    "LogicalAnd": lambda n, xs: jnp.logical_and(xs[0], xs[1]),
    "LogicalOr": lambda n, xs: jnp.logical_or(xs[0], xs[1]),
    "LogicalNot": lambda n, xs: jnp.logical_not(xs[0]),
    "Select": _select_v1,
    "SelectV2": lambda n, xs: jnp.where(xs[0], xs[1], xs[2]),
    "Fill": lambda n, xs: jnp.full(
        tuple(int(v) for v in np.asarray(xs[0]).ravel()), xs[1]),
    "Range": lambda n, xs: jnp.arange(np.asarray(xs[0]).item(),
                                      np.asarray(xs[1]).item(),
                                      np.asarray(xs[2]).item()),
    "Tile": lambda n, xs: jnp.tile(
        xs[0], tuple(int(v) for v in np.asarray(xs[1]).ravel())),
    "Slice": lambda n, xs: jax.lax.dynamic_slice(
        xs[0], tuple(int(v) for v in np.asarray(xs[1]).ravel()),
        tuple(dim - int(b) if int(sz) == -1 else int(sz)  # -1 = to end
              for dim, b, sz in zip(xs[0].shape,
                                    np.asarray(xs[1]).ravel(),
                                    np.asarray(xs[2]).ravel()))),
    "OneHot": lambda n, xs: jnp.moveaxis(
        jax.nn.one_hot(jnp.asarray(xs[0]).astype(jnp.int32),
                       int(np.asarray(xs[1]))) * (xs[2] - xs[3]) + xs[3],
        -1, n.attrs.get("axis", -1)),
    "ZerosLike": lambda n, xs: jnp.zeros_like(xs[0]),
    "OnesLike": lambda n, xs: jnp.ones_like(xs[0]),
    "ArgMax": lambda n, xs: jnp.argmax(xs[0], axis=int(np.asarray(xs[1]))),
    "ArgMin": lambda n, xs: jnp.argmin(xs[0], axis=int(np.asarray(xs[1]))),
    "Min": _reduce_op(jnp.min),
    "Prod": _reduce_op(jnp.prod),
}


def _strided_slice(node, xs):
    x, begin, end, strides = xs[:4]
    begin = [int(v) for v in np.asarray(begin).ravel()]
    end = [int(v) for v in np.asarray(end).ravel()]
    strides = [int(v) for v in np.asarray(strides).ravel()]
    slices = []
    shrink = node.attrs.get("shrink_axis_mask", 0) or 0
    begin_mask = node.attrs.get("begin_mask", 0) or 0
    end_mask = node.attrs.get("end_mask", 0) or 0
    for i, (b, e, s) in enumerate(zip(begin, end, strides)):
        if shrink & (1 << i):
            slices.append(b)
            continue
        bb = None if (begin_mask & (1 << i)) else b
        ee = None if (end_mask & (1 << i)) else e
        slices.append(slice(bb, ee, s))
    return x[tuple(slices)]


class TFModule(Module):
    """Executes an imported frozen GraphDef as a Module.

    inputs/outputs: node names (Placeholders default as inputs). The whole
    node walk happens at trace time, so the module jits/differentiates
    like native layers (the reference's Session.run analogue).
    """

    def __init__(self, nodes,
                 inputs: Optional[Sequence[str]] = None,
                 outputs: Optional[Sequence[str]] = None):
        super().__init__()
        if isinstance(nodes, (bytes, bytearray)):
            # raw GraphDef bytes: keeps the module serializable through
            # save_module (ctor-arg capture stores the bytes, not the
            # parsed TFNode objects with numpy-dtype attrs)
            nodes = parse_graphdef(bytes(nodes))
        self.nodes = list(nodes)
        self.by_name = {n.name: n for n in self.nodes}
        self.input_names = list(inputs) if inputs else [
            n.name for n in self.nodes if n.op == "Placeholder"]
        self.consts = {n.name: _ensure_array(n.attrs.get("value"))
                       for n in self.nodes if n.op == "Const"}
        # Variables (unfrozen v1 graphs): VariableV2 nodes become trainable
        # parameters; their Assign initializers give the initial values
        # (the role TFTrainingHelper's weight extraction plays in the
        # reference utils/tf/Session.scala:104).
        self.variable_init: Dict[str, np.ndarray] = {}
        assign_of = {}
        for n in self.nodes:
            # ref variables use Assign; resource variables (TF2 compat.v1)
            # use AssignVariableOp
            if n.op in ("Assign", "AssignVariableOp") and \
                    len(n.inputs) >= 2:
                assign_of[n.inputs[0].split(":")[0]] = \
                    n.inputs[1].split(":")[0]
        for n in self.nodes:
            if n.op in ("VariableV2", "Variable", "VarHandleOp"):
                init_name = assign_of.get(n.name)
                if init_name is None:
                    shape = n.attrs.get("shape")
                    self.variable_init[n.name] = np.zeros(
                        tuple(shape) if shape else (), np.float32)
                else:
                    self.variable_init[n.name] = np.asarray(
                        self._eval_initializer(init_name), np.float32)
        if outputs:
            self.output_names = list(outputs)
        else:
            consumed = {inp.split(":")[0].lstrip("^")
                        for n in self.nodes for inp in n.inputs}
            # orphan Consts/Placeholders (pruning leftovers) are not
            # outputs; neither is variable-initialization machinery
            self.output_names = [n.name for n in self.nodes
                                 if n.name not in consumed
                                 and n.op not in ("NoOp", "Const",
                                                  "Placeholder", "Assign",
                                                  "AssignVariableOp",
                                                  "VarIsInitializedOp",
                                                  "VariableV2", "Variable",
                                                  "VarHandleOp")]

    def _eval_initializer(self, name: str) -> np.ndarray:
        """Evaluate a variable-initializer subgraph on host numpy —
        Const chains plus the standard random-init ops (the reference's
        Session evaluates these through the graph too). Raises on
        anything else rather than silently zero-initializing."""
        # seed per-initializer: same-shape variables must NOT share a
        # stream (identical inits would train symmetrically); hash the
        # FULL name — suffix bytes collide (layer1/kernel vs layer2/kernel)
        rng = np.random.RandomState(
            zlib.crc32(name.encode()) & 0xFFFFFFFF)

        def ev(nm: str) -> np.ndarray:
            nm = nm.split(":")[0].lstrip("^")
            if nm in self.consts:
                return self.consts[nm]
            node = self.by_name[nm]
            if node.op in ("Identity", "ReadVariableOp"):
                return ev(node.inputs[0])
            if node.op in ("TruncatedNormal", "RandomStandardNormal"):
                shape = tuple(int(v) for v in
                              np.asarray(ev(node.inputs[0])).ravel())
                vals = rng.standard_normal(shape)
                if node.op == "TruncatedNormal":
                    vals = np.clip(vals, -2.0, 2.0)
                return vals.astype(np.float32)
            if node.op == "RandomUniform":
                shape = tuple(int(v) for v in
                              np.asarray(ev(node.inputs[0])).ravel())
                return rng.uniform(size=shape).astype(np.float32)
            if node.op in ("Add", "AddV2"):
                return ev(node.inputs[0]) + ev(node.inputs[1])
            if node.op == "Sub":
                return ev(node.inputs[0]) - ev(node.inputs[1])
            if node.op == "Mul":
                return ev(node.inputs[0]) * ev(node.inputs[1])
            if node.op == "Fill":
                shape = tuple(int(v) for v in
                              np.asarray(ev(node.inputs[0])).ravel())
                return np.full(shape, np.asarray(ev(node.inputs[1])))
            raise ValueError(
                f"cannot evaluate variable initializer op {node.op} "
                f"(node {nm}); freeze the graph or initialize with "
                "constants")

        return ev(name)

    def init(self, rng):
        import jax.numpy as _jnp
        return {k: _jnp.asarray(v) for k, v in self.variable_init.items()}

    def forward_fn(self, params, input, *, training=False, rng=None):
        from bigdl_tpu.utils.table import Table, T
        if isinstance(input, (Table, list, tuple)):
            feed = {name: x for name, x in zip(self.input_names,
                                               list(input))}
        else:
            feed = {self.input_names[0]: input}
        values: Dict[str, Any] = {}
        # inputs may be tensor REFS ("parse:1") when a host input
        # pipeline feeds mid-graph boundary tensors (Session.scala:104's
        # queue-runner handoff); seed multi-output nodes as tuples
        ref_feed: Dict[str, Dict[int, Any]] = {}
        for key, x in list(feed.items()):
            if ":" in key:
                nm, idx = key.split(":")[0], int(key.split(":")[1])
                ref_feed.setdefault(nm, {})[idx] = x
                del feed[key]
        for nm, d in ref_feed.items():
            if nm in feed:
                d.setdefault(0, feed.pop(nm))
            if set(d) == {0}:
                values[nm] = d[0]
            else:
                values[nm] = tuple(d.get(i)
                                   for i in range(max(d) + 1))

        def resolve(ref: str):
            name = ref.split(":")[0].lstrip("^")
            out_idx = int(ref.split(":")[1]) if ":" in ref else 0
            v = values[name]
            return v[out_idx] if isinstance(v, tuple) else v

        def controlling_switch(ref: str):
            """Walk a Merge input back to its Switch: returns (switch_node,
            branch out_idx) — the trace-time equivalent of the reference
            Scheduler's control-flow availability (Scheduler.scala:118).
            DFS over ALL data inputs: the Switch ancestry may sit on any
            operand (e.g. Add(const, switch_out))."""
            seen = set()
            work = [ref]
            while work:
                r = work.pop()
                name = r.split(":")[0].lstrip("^")
                if name in seen:
                    continue
                seen.add(name)
                node = self.by_name.get(name)
                if node is None:
                    continue
                if node.op == "Switch":
                    out_idx = int(r.split(":")[1]) if ":" in r else 0
                    return node, out_idx
                work.extend(i for i in node.inputs
                            if not i.startswith("^"))
            return None

        def evaluate(ref: str):
            # Explicit work stack — deep sequential graphs (large
            # ResNet/Inception exports) overflow Python recursion limits.
            in_progress: Dict[str, bool] = {}
            stack = [ref.split(":")[0].lstrip("^")]
            while stack:
                name = stack[-1]
                if name in values:
                    stack.pop()
                    continue
                if name in feed:
                    values[name] = jnp.asarray(feed[name])
                    stack.pop()
                    continue
                if name in self.variable_init:
                    values[name] = jnp.asarray(
                        params[name] if params and name in params
                        else self.variable_init[name])
                    stack.pop()
                    continue
                if name in self.consts:
                    # keep consts as NUMPY: under jit, jnp.asarray would
                    # make them tracers, breaking ops that need concrete
                    # shape/axis operands (Reshape, Mean, Transpose, ...)
                    values[name] = self.consts[name]
                    stack.pop()
                    continue
                node = self.by_name[name]
                deps = [i.split(":")[0].lstrip("^") for i in node.inputs
                        if not i.startswith("^")]
                pending = [d for d in deps if d not in values]
                if pending:
                    # revisiting an in-progress node with deps still
                    # unresolved = a data cycle (v1 tf.while_loop's
                    # NextIteration); fail loudly instead of spinning
                    if in_progress.get(name):
                        raise ValueError(
                            f"graph cycle through node {name} "
                            "(v1 while_loop is not supported)")
                    in_progress[name] = True
                    stack.extend(pending)
                    continue
                xs = [resolve(i) for i in node.inputs
                      if not i.startswith("^")]
                if node.op == "Switch":
                    # outputs: (output_false, output_true); selection is
                    # deferred to the matching Merge (ControlOps.scala:69)
                    values[name] = (xs[0], xs[0])
                elif node.op == "Merge":
                    from bigdl_tpu.nn.control_ops import MergeOps
                    data_refs = [i for i in node.inputs
                                 if not i.startswith("^")]
                    def pred_ref(sw):
                        r = [i for i in sw.inputs
                             if not i.startswith("^")][1]
                        name = r.split(":")[0]
                        idx = int(r.split(":")[1]) if ":" in r else 0
                        return (name, idx)

                    # TF v1 cond makes one Switch per external tensor per
                    # branch; what must match is the PREDICATE, not the
                    # Switch node (nested conds have different predicates)
                    ctl = [controlling_switch(r) for r in data_refs]
                    if len(xs) == 2 and all(c is not None for c in ctl) \
                            and pred_ref(ctl[0][0]) == pred_ref(ctl[1][0]) \
                            and {ctl[0][1], ctl[1][1]} == {0, 1}:
                        sw = ctl[0][0]
                        pred = resolve([i for i in sw.inputs
                                        if not i.startswith("^")][1])
                        ti = 0 if ctl[0][1] == 1 else 1
                        out = MergeOps.select(pred, xs[ti], xs[1 - ti])
                        idx = jnp.where(jnp.asarray(pred).astype(bool),
                                        ti, 1 - ti)
                        values[name] = (out, idx)  # (output, value_index)
                    else:
                        raise ValueError(
                            f"Merge node {name}: could not resolve a "
                            "single two-branch Switch (nested v1 conds "
                            "are not supported)")
                else:
                    fn = _OPS.get(node.op)
                    if fn is None:
                        raise ValueError(
                            f"unsupported TF op {node.op} (node {name})")
                    values[name] = fn(node, xs)
                stack.pop()
            return resolve(ref)

        outs = [evaluate(o) for o in self.output_names]
        return outs[0] if len(outs) == 1 else T(*outs)


def _ensure_array(v):
    if v is None:
        return np.zeros((), np.float32)
    return np.asarray(v)


# saved/loaded by name through save_module/load_module
from bigdl_tpu.utils.module_serializer import register_module_class

register_module_class(TFModule)


def load_tf_graph(path: str, inputs: Optional[Sequence[str]] = None,
                  outputs: Optional[Sequence[str]] = None) -> TFModule:
    """Module.loadTF equivalent: read a frozen .pb GraphDef."""
    with open(path, "rb") as f:
        data = f.read()
    nodes = parse_graphdef(data)
    if not nodes:
        raise ValueError(f"no nodes parsed from {path}")
    m = TFModule(nodes, inputs, outputs)
    # serialize via the raw bytes, not the parsed TFNode objects
    m._init_args = (data, inputs, outputs)
    m._init_kwargs = {}
    return m


class Session:
    """Train an imported (unfrozen) TF graph — the reference's
    BigDLSessionImpl.train (utils/tf/Session.scala:53,104-110): Variables
    become trainable parameters, the graph's own loss node is minimized,
    Placeholders are fed from MiniBatches.

    ``inputs`` are the feature/label placeholder names in MiniBatch order
    (features first, then targets); ``loss`` is the scalar loss node.

    When the graph carries its own input pipeline (queue runners +
    ParseExample / Decode* nodes, Session.scala:104-110), ``inputs`` may
    be omitted: the host region is split off and executed on numpy (see
    utils/tf_input.py), and ``train`` pulls batches straight from the
    graph's own .tfrecord readers — pass ``record_files`` to point the
    baked-in reader paths at local files.
    """

    def __init__(self, nodes_or_bytes, inputs: Optional[Sequence[str]]
                 = None, loss: str = "loss", *,
                 record_files: Optional[Sequence[str]] = None,
                 seed: int = 0):
        from bigdl_tpu.utils import tf_input as _ti

        nodes = (parse_graphdef(bytes(nodes_or_bytes))
                 if isinstance(nodes_or_bytes, (bytes, bytearray))
                 else list(nodes_or_bytes))
        by_name = {n.name: n for n in nodes}
        self.pipeline = None
        if inputs is None:
            if not _ti.has_input_pipeline(nodes):
                raise ValueError(
                    "inputs not given and the graph has no in-graph "
                    "input pipeline (readers/queues/ParseExample)")
            inputs = _ti.find_boundary_refs(nodes, by_name, [loss])
            if not inputs:
                raise ValueError(
                    "input-pipeline graph: no host->device boundary "
                    f"tensors found on the ancestry of '{loss}'")
            self.pipeline = _ti.HostInputGraph(
                nodes, record_files=record_files, seed=seed)
        self.module = TFModule(nodes, inputs=inputs, outputs=[loss])
        if not self.module.variable_init:
            raise ValueError(
                "graph has no Variables to train (frozen graph?)")
        self.loss_name = loss

    def train(self, batches=None, optim_method=None, *, end_trigger=None,
              max_iterations: Optional[int] = None,
              epoch_size: Optional[int] = None):
        """batches: iterable of MiniBatch (or (x, y) tuples); omit it
        for input-pipeline graphs, which feed themselves from their own
        readers. Returns the trained TFModule (params updated in place).

        ``epoch_size`` (iterations per epoch) makes epoch-based triggers
        (max_epoch/every_epoch) meaningful on infinite batch iterables —
        without it only iteration-count triggers can fire.
        """
        import jax as _jax

        from bigdl_tpu.dataset.sample import MiniBatch
        from bigdl_tpu.optim.trigger import max_iteration as _max_iter

        if optim_method is None:
            raise ValueError("optim_method is required")
        if batches is None:
            if self.pipeline is None:
                raise ValueError(
                    "batches is required: this graph has no in-graph "
                    "input pipeline to feed itself from")
            batches = self.pipeline.batches(self.module.input_names)

        module = self.module
        module.ensure_initialized()
        params = module.get_parameters()
        opt_state = optim_method.init_state(params)
        if end_trigger is None:
            end_trigger = _max_iter(max_iterations or 100)

        @_jax.jit
        def step(p, o, lr, xs):
            def loss_fn(pp):
                out, _ = module.apply(pp, {}, xs, training=True)
                return jnp.asarray(out).reshape(())

            loss, grads = _jax.value_and_grad(loss_fn)(p)
            p2, o2 = optim_method.update(grads, o, p, lr)
            return p2, o2, loss

        state = {"epoch": 1, "neval": 1}
        loss_val = None
        for b in batches:
            if end_trigger(state):  # endWhen fires -> stop
                break
            if isinstance(b, MiniBatch):
                xs = ([b.input] if not isinstance(b.input, (list, tuple))
                      else list(b.input))
                if b.target is not None:
                    xs += ([b.target]
                           if not isinstance(b.target, (list, tuple))
                           else list(b.target))
            else:
                xs = list(b)
            lr = optim_method.update_hyper_parameter()
            params, opt_state, loss_val = step(params, opt_state, lr, xs)
            state["neval"] += 1
            optim_method.state["neval"] = state["neval"]
            if epoch_size and (state["neval"] - 1) % epoch_size == 0:
                state["epoch"] += 1
                optim_method.state["epoch"] = state["epoch"]
        module.set_parameters(params)
        self.last_loss = float(loss_val) if loss_val is not None else None
        return module
