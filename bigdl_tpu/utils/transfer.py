"""Host->device transfer sizing (the measured device_put "cliff").

On tunneled/NIC-limited hosts a single large ``jax.device_put`` falls off
a throughput cliff above a few hundred MB (BASELINE.md: a 1.23 GB put
took 14-37 s while the same bytes as 38 MB pieces moved at ~1.1 GB/s).
``probe_device_put_chunk`` measures ascending sizes once per process and
returns the largest piece size that stays near peak throughput — the
auto-tuned chunk every piecewise staging path (fed bench, shard
rotation) should use. The reference's counterpart decision is caching
decoded images to dodge its IO wall (dataset/DataSet.scala:240); here
the wall is the link, so we size around it instead.
"""
from __future__ import annotations

import os
import time
from typing import Optional

import numpy as np

_cached_chunk: Optional[int] = None


def probe_device_put_chunk(max_mb: int = 96, *, drop_ratio: float = 0.5,
                           device=None) -> int:
    """Measure device_put throughput at 4,8,...,max_mb MB and return the
    largest size (bytes) whose throughput holds >= ``drop_ratio`` x the
    best seen. Ascending order stops at the first cliff, so at most one
    slow transfer is ever issued. Result is cached per process; the
    BENCH_CHUNK_MB env var short-circuits the probe."""
    global _cached_chunk
    if _cached_chunk is not None:
        return _cached_chunk
    env = os.environ.get("BENCH_CHUNK_MB")
    if env:
        _cached_chunk = int(float(env) * (1 << 20))
        return _cached_chunk

    import jax

    dev = device or jax.devices()[0]
    best_bps = 0.0
    chosen = 4 << 20
    mb = 4
    while mb <= max_mb:
        arr = np.random.RandomState(mb).randint(0, 256, mb << 20,
                                                dtype=np.uint8)
        t0 = time.time()
        out = jax.device_put(arr, dev)
        # the probe measures completed transfers; per-piece
        # sync is the alternation rule under test
        out.block_until_ready()  # bigdl: disable=sync-in-loop
        # fetch a slice: on tunneled backends block_until_ready can
        # return before the bytes actually crossed (measured: "fast"
        # puts that were pure dispatch) — a readback is the only
        # honest completion signal. Random payload defeats relay-side
        # dedup of repeated buffers.
        np.asarray(out[:64])
        dt = max(time.time() - t0, 1e-9)
        bps = arr.nbytes / dt
        if bps >= best_bps:
            best_bps = bps
            chosen = arr.nbytes
        elif bps < drop_ratio * best_bps:
            break  # over the cliff: stop probing larger sizes
        else:
            chosen = arr.nbytes  # slower but acceptable; keep growing
        mb *= 2
    _cached_chunk = chosen
    return chosen
