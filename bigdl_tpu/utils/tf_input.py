"""In-graph TF input pipelines executed on the host (reference:
nn/ops/ParseExample.scala, nn/ops/DecodeImage.scala, and the
queue-runner input graphs BigDLSessionImpl trains from,
utils/tf/Session.scala:104-110).

The reference runs readers/queues/ParseExample as graph ops on Spark
partitions. The TPU build splits the graph instead: everything from
reader nodes down to the last string-typed op runs HERE on host numpy
(JAX cannot trace ragged string tensors), and the dense boundary
tensors feed the jitted device graph — the same host/device split the
driver's data feed uses everywhere else. Queues are stateful Python
objects whose elements are pulled lazily from their enqueue subgraphs,
so ``string_input_producer -> TFRecordReader -> batch -> ParseExample``
executes with the reference's semantics (cycling filename epochs,
streaming reads) without a queue-runner thread.
"""
from __future__ import annotations

from collections import deque
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

# ops that force host execution (everything upstream of their outputs
# runs on host; the refs their consumers read become device feeds)
HOST_OPS = frozenset({
    "TFRecordReaderV2", "TFRecordReader", "WholeFileReaderV2",
    "IdentityReaderV2", "ReaderReadV2", "ReaderRead", "ReaderReadUpToV2",
    "FIFOQueueV2", "FIFOQueue", "PaddingFIFOQueueV2",
    "RandomShuffleQueueV2", "RandomShuffleQueue",
    "QueueDequeueV2", "QueueDequeue", "QueueDequeueManyV2",
    "QueueDequeueMany", "QueueDequeueUpToV2",
    "QueueEnqueueV2", "QueueEnqueue", "QueueEnqueueManyV2",
    "QueueEnqueueMany", "QueueCloseV2", "QueueSizeV2",
    "ParseExample", "ParseExampleV2", "ParseSingleExample",
    "DecodeJpeg", "DecodePng", "DecodeImage", "DecodeBmp", "DecodeRaw",
})


def _base(ref: str) -> str:
    return ref.split(":")[0].lstrip("^")


def _out_idx(ref: str) -> int:
    return int(ref.split(":")[1]) if ":" in ref else 0


def find_boundary_refs(nodes, by_name, outputs: Sequence[str]
                       ) -> List[str]:
    """Walk the requested outputs' ancestry; stop at host nodes and
    collect the tensor refs where host data crosses into the device
    graph. Deterministic order (sorted)."""
    boundary = set()
    seen = set()
    stack = [_base(o) for o in outputs]
    while stack:
        name = stack.pop()
        if name in seen:
            continue
        seen.add(name)
        node = by_name.get(name)
        if node is None:
            continue
        for ref in node.inputs:
            if ref.startswith("^"):
                continue
            src = by_name.get(_base(ref))
            if src is not None and src.op in HOST_OPS:
                boundary.add(ref)
            else:
                stack.append(_base(ref))
    return sorted(boundary)


def has_input_pipeline(nodes) -> bool:
    return any(n.op in HOST_OPS for n in nodes)


class _Queue:
    """FIFO/shuffle queue whose elements are pulled lazily from its
    QueueEnqueue(Many) subgraphs (replaces the queue-runner thread)."""

    def __init__(self, host: "HostInputGraph", qnode):
        self.host = host
        self.name = qnode.name
        self.shuffle = "Shuffle" in qnode.op
        self.enqs = [n for n in host.nodes
                     if n.op.startswith("QueueEnqueue")
                     and _base(n.inputs[0]) == qnode.name]
        if not self.enqs:
            raise ValueError(
                f"queue {qnode.name} has no enqueue ops in the graph")
        self.buf: deque = deque()

    def dequeue(self):
        if not self.buf:
            self._fill()
        if self.shuffle and len(self.buf) > 1:
            i = int(self.host.rng.randint(0, len(self.buf)))
            self.buf.rotate(-i)
            out = self.buf.popleft()
            self.buf.rotate(i)
            return out
        return self.buf.popleft()

    def _fill(self):
        for enq in self.enqs:
            cache: Dict[str, Any] = {}  # fresh: reader state advances
            comps = [self.host.eval_ref(r, cache)
                     for r in enq.inputs[1:] if not r.startswith("^")]
            if enq.op.startswith("QueueEnqueueMany"):
                for i in range(len(comps[0])):
                    self.buf.append(tuple(c[i] for c in comps))
            else:
                self.buf.append(tuple(comps))
        if not self.buf:
            raise RuntimeError(
                f"queue {self.name}: enqueue sources produced no "
                "elements")


class _Reader:
    """TFRecord/whole-file reader state: current file iterator plus the
    filename queue it pulls from (ReaderReadV2 semantics)."""

    def __init__(self, host: "HostInputGraph", kind: str):
        self.host = host
        self.kind = kind
        self._it = None
        self._fname = None
        self._rec = 0
        self._override_pos = 0

    def _next_file(self, queue: Optional[_Queue]) -> str:
        if self.host.record_files is not None:
            files = self.host.record_files
            f = files[self._override_pos % len(files)]
            self._override_pos += 1
            return f
        if queue is None:
            raise ValueError("reader has no filename queue")
        el = queue.dequeue()
        f = el[0] if isinstance(el, tuple) else el
        if isinstance(f, np.ndarray):
            f = f.item()
        return f.decode() if isinstance(f, bytes) else str(f)

    def read(self, queue: Optional[_Queue]):
        from bigdl_tpu.utils.tfrecord import read_tfrecord
        while True:
            if self._it is None:
                self._fname = self._next_file(queue)
                self._rec = 0
                if self.kind == "whole":
                    def whole():
                        with open(self._fname, "rb") as fh:
                            yield fh.read()
                    self._it = whole()
                else:
                    self._it = read_tfrecord(self._fname)
            try:
                value = next(self._it)
                key = f"{self._fname}:{self._rec}".encode()
                self._rec += 1
                return (key, value)
            except StopIteration:
                self._it = None


class HostInputGraph:
    """Evaluates the host-side input region of an imported GraphDef.

    ``batch(boundary_refs)`` yields, per training iteration, the numpy
    values of the boundary tensors (one shared evaluation, so a
    ParseExample producing features AND labels parses each record
    once). ``record_files`` substitutes the .tfrecord paths baked into
    the exporting machine's graph.
    """

    def __init__(self, nodes, *, record_files: Optional[Sequence[str]]
                 = None, seed: int = 0):
        self.nodes = list(nodes)
        self.by_name = {n.name: n for n in self.nodes}
        self.record_files = (list(record_files)
                             if record_files is not None else None)
        self.rng = np.random.RandomState(seed)
        self._queues: Dict[str, _Queue] = {}
        self._readers: Dict[str, _Reader] = {}

    # ------------------------------------------------------- evaluation
    def eval_ref(self, ref: str, cache: Dict[str, Any]):
        name = _base(ref)
        if name not in cache:
            node = self.by_name[name]
            cache[name] = self._eval_node(node, cache)
        v = cache[name]
        idx = _out_idx(ref)
        return v[idx] if isinstance(v, tuple) else v

    def _inputs(self, node) -> List[str]:
        return [r for r in node.inputs if not r.startswith("^")]

    def _eval_node(self, node, cache):
        op = node.op
        ins = self._inputs(node)
        if op == "Const":
            return np.asarray(node.attrs.get("value"))
        if op in ("Identity", "StopGradient", "PreventGradient"):
            return self.eval_ref(ins[0], cache)
        if op == "RandomShuffle":
            arr = np.asarray(self.eval_ref(ins[0], cache))
            return self.rng.permutation(arr)
        if op in ("TFRecordReaderV2", "TFRecordReader"):
            return self._readers.setdefault(
                node.name, _Reader(self, "tfrecord"))
        if op in ("WholeFileReaderV2", "IdentityReaderV2"):
            return self._readers.setdefault(
                node.name, _Reader(self, "whole"))
        if op.startswith("FIFOQueue") or op.startswith(
                "RandomShuffleQueue") or op.startswith("PaddingFIFOQueue"):
            return self._queues.setdefault(node.name, _Queue(self, node))
        if op in ("ReaderReadV2", "ReaderRead"):
            reader = self.eval_ref(ins[0], cache)
            queue = self.eval_ref(ins[1], cache) if len(ins) > 1 else None
            return reader.read(queue)
        if op.startswith("QueueDequeueMany") or \
                op.startswith("QueueDequeueUpTo"):
            q = self.eval_ref(ins[0], cache)
            n = int(np.asarray(self.eval_ref(ins[1], cache)))
            els = [q.dequeue() for _ in range(n)]
            return self._stack_elements(els)
        if op.startswith("QueueDequeue"):
            q = self.eval_ref(ins[0], cache)
            el = q.dequeue()
            return el if len(el) > 1 else el[0]
        if op in ("ParseExample", "ParseExampleV2",
                  "ParseSingleExample"):
            return self._parse_example(node, cache)
        if op in ("DecodeJpeg", "DecodePng", "DecodeImage", "DecodeBmp"):
            from bigdl_tpu.dataset.imagenet import decode_image
            data = self.eval_ref(ins[0], cache)
            return decode_image(bytes(np.asarray(data).item()))
        if op == "DecodeRaw":
            out_t = node.attrs.get("out_type", np.float32)
            data = np.asarray(self.eval_ref(ins[0], cache))
            if data.ndim == 0:
                return np.frombuffer(data.item(), dtype=out_t)
            return np.stack([np.frombuffer(d, dtype=out_t)
                             for d in data.ravel()]).reshape(
                                 data.shape + (-1,))
        if op == "Cast":
            dst = node.attrs.get("DstT", np.float32)
            return np.asarray(self.eval_ref(ins[0], cache)).astype(dst)
        if op == "Reshape":
            x = np.asarray(self.eval_ref(ins[0], cache))
            shp = np.asarray(self.eval_ref(ins[1], cache)).astype(int)
            return x.reshape(tuple(shp))
        if op == "ExpandDims":
            x = np.asarray(self.eval_ref(ins[0], cache))
            ax = int(np.asarray(self.eval_ref(ins[1], cache)))
            return np.expand_dims(x, ax)
        if op == "Squeeze":
            x = np.asarray(self.eval_ref(ins[0], cache))
            dims = node.attrs.get("squeeze_dims") or None
            return np.squeeze(x, tuple(dims) if dims else None)
        raise ValueError(
            f"unsupported host input op {op} (node {node.name}); "
            "supported: readers, queues, ParseExample, DecodeJpeg/Png/"
            "Raw and numpy glue (Cast/Reshape/ExpandDims/Squeeze)")

    @staticmethod
    def _stack_elements(els):
        comps = []
        for i in range(len(els[0])):
            col = [e[i] for e in els]
            if isinstance(col[0], (bytes, bytearray, str)) or (
                    isinstance(col[0], np.ndarray)
                    and col[0].dtype == object) or (
                    isinstance(col[0], np.generic)
                    and col[0].dtype == object):
                arr = np.empty(len(col), object)
                arr[:] = [c.item() if isinstance(c, np.ndarray) else c
                          for c in col]
                comps.append(arr)
            else:
                comps.append(np.stack([np.asarray(c) for c in col]))
        return tuple(comps) if len(comps) > 1 else comps[0]

    # ---------------------------------------------------- ParseExample
    def _parse_example(self, node, cache):
        """Dense-feature tf.Example batch parse (ParseExample.scala:1;
        v1 layout Nsparse/Ndense attrs + per-key Const inputs, v2 layout
        vector-Const keys). Sparse outputs are not supported."""
        from bigdl_tpu.utils.tfrecord import parse_example

        ins = self._inputs(node)
        if node.op == "ParseSingleExample":
            # TF1 frozen-graph layout: keys live in ATTRS, the only
            # tensor inputs are the scalar serialized proto + defaults
            # (modern TF lowers parse_single_example to ParseExampleV2,
            # which the branch below handles via its scalar-input path)
            serialized = self.eval_ref(ins[0], cache)
            sparse_keys = [self._to_str(k) for k in
                           (node.attrs.get("sparse_keys") or [])]
            dense_keys = [self._to_str(k) for k in
                          (node.attrs.get("dense_keys") or [])]
            defaults = [np.asarray(self.eval_ref(r, cache))
                        for r in ins[1:1 + len(dense_keys)]]
        elif node.op == "ParseExampleV2":
            serialized = self.eval_ref(ins[0], cache)
            sparse_keys = [self._to_str(k) for k in
                           np.asarray(self.eval_ref(ins[2], cache)).ravel()]
            dense_keys = [self._to_str(k) for k in
                          np.asarray(self.eval_ref(ins[3], cache)).ravel()]
            defaults = [np.asarray(self.eval_ref(r, cache))
                        for r in ins[5:5 + len(dense_keys)]]
        else:
            n_sparse = int(node.attrs.get("Nsparse", 0))
            n_dense = int(node.attrs.get("Ndense", 0))
            serialized = self.eval_ref(ins[0], cache)
            sparse_keys = [self._to_str(np.asarray(
                self.eval_ref(r, cache)).item())
                for r in ins[2:2 + n_sparse]]
            dense_keys = [self._to_str(np.asarray(
                self.eval_ref(r, cache)).item())
                for r in ins[2 + n_sparse:2 + n_sparse + n_dense]]
            defaults = [np.asarray(self.eval_ref(r, cache))
                        for r in ins[2 + n_sparse + n_dense:
                                     2 + n_sparse + n_dense + n_dense]]
        if sparse_keys:
            raise ValueError(
                "ParseExample with sparse features is not supported; "
                "use dense FixedLenFeatures")
        dtypes = node.attrs.get("Tdense") or [np.float32] * len(dense_keys)
        shapes = node.attrs.get("dense_shapes") or [[]] * len(dense_keys)

        serialized = np.asarray(serialized)
        scalar_in = serialized.ndim == 0
        rows = [parse_example(bytes(s))
                for s in np.atleast_1d(serialized.ravel())]
        outs = []
        for k, dt, shp, dflt in zip(dense_keys, dtypes, shapes, defaults):
            col = []
            for row in rows:
                v = row.get(k)
                if v is None:
                    if dflt.size == 0:
                        raise ValueError(
                            f"record missing required feature '{k}'")
                    v = dflt
                col.append(np.asarray(v, dt).reshape(tuple(shp)))
            stacked = np.stack(col)
            outs.append(stacked[0] if scalar_in else stacked)
        return tuple(outs) if len(outs) > 1 else outs[0]

    @staticmethod
    def _to_str(k) -> str:
        return k.decode() if isinstance(k, bytes) else str(k)

    # ------------------------------------------------------- iteration
    def batches(self, boundary_refs: Sequence[str]):
        """Infinite generator of per-iteration boundary values (the
        Session's feed source, Session.scala:104)."""
        while True:
            cache: Dict[str, Any] = {}
            yield [np.asarray(self.eval_ref(r, cache))
                   for r in boundary_refs]
