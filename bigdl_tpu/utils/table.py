"""Table — the heterogeneous activity container (BigDL utils/Table.scala:34).

BigDL's ``Table`` is a Lua-style int/any-keyed map used wherever a module takes
or returns multiple tensors (``Activity = Tensor | Table``). In a JAX-native
design a Table is just a pytree node, so tables flow through ``jit``, ``grad``
and shardings with no special handling.

Keys follow BigDL's Lua convention: ``T(a, b, c)`` builds {1: a, 2: b, 3: c}
(1-indexed), matching utils/Table.scala:318's ``T()`` constructor. String and
other keys are allowed, as in the reference.
"""
from __future__ import annotations

import jax


class Table:
    """An int/any-keyed container registered as a JAX pytree.

    Mirrors BigDL ``utils.Table`` semantics: 1-indexed ``insert``/``apply``,
    ``length`` counts consecutive integer keys from 1.
    """

    __slots__ = ("_state",)

    def __init__(self, state=None):
        object.__setattr__(self, "_state", dict(state) if state else {})

    # -- dict-like surface -------------------------------------------------
    def __getitem__(self, key):
        return self._state[key]

    def __setitem__(self, key, value):
        self._state[key] = value

    def __contains__(self, key):
        return key in self._state

    def __delitem__(self, key):
        del self._state[key]

    def get(self, key, default=None):
        return self._state.get(key, default)

    def keys(self):
        return self._state.keys()

    def values(self):
        return self._state.values()

    def items(self):
        return self._state.items()

    def __iter__(self):
        # iterate positional entries 1..length (Lua array part)
        for i in range(1, self.length() + 1):
            yield self._state[i]

    def __len__(self):
        return self.length()

    def length(self):
        """Number of consecutive int keys starting at 1 (Table.scala:120)."""
        n = 0
        while (n + 1) in self._state:
            n += 1
        return n

    def insert(self, value):
        """Append at the end of the array part (Table.scala:151)."""
        self._state[self.length() + 1] = value
        return self

    def remove(self, index=None):
        if index is None:
            index = self.length()
        if index not in self._state:
            return None
        value = self._state.pop(index)
        # shift down the array part above `index`
        i = index
        while (i + 1) in self._state:
            self._state[i] = self._state.pop(i + 1)
            i += 1
        return value

    def update(self, other):
        if isinstance(other, Table):
            other = other._state
        self._state.update(other)
        return self

    def to_dict(self):
        return dict(self._state)

    def to_list(self):
        return [self._state[i] for i in range(1, self.length() + 1)]

    def __eq__(self, other):
        if isinstance(other, Table):
            return self._state == other._state
        return NotImplemented

    def __hash__(self):
        return object.__hash__(self)

    def __repr__(self):
        inner = ", ".join(f"{k!r}: {v!r}" for k, v in self._state.items())
        return f"T({{{inner}}})"


def T(*args, **kwargs):
    """Table constructor mirroring BigDL's ``T()`` (utils/Table.scala:318).

    ``T(a, b)`` -> {1: a, 2: b}; ``T(k=v)`` adds string keys.
    """
    t = Table()
    for i, a in enumerate(args):
        t[i + 1] = a
    for k, v in kwargs.items():
        t[k] = v
    return t


def _table_flatten(t: Table):
    keys = sorted(t._state.keys(), key=lambda k: (str(type(k)), str(k)))
    return [t._state[k] for k in keys], tuple(keys)


def _table_unflatten(keys, children):
    return Table(dict(zip(keys, children)))


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)
