"""Minimal protobuf wire-format codec (no protoc dependency).

The reference ships generated protobuf Java for its own model format, Caffe
and TensorFlow interop (SURVEY.md §2.5: serialization/Bigdl.java,
caffe/Caffe.java, 121 TF proto files). The TPU build needs the same wire
compatibility but not the codegen: messages of interest are small and
well-known, so a hand-rolled varint/length-delimited codec keeps the
framework dependency-free. Used by visualization (tfevents), the Caffe
importer and the TF GraphDef importer.

Wire types: 0 varint, 1 fixed64, 2 length-delimited, 5 fixed32.
"""
from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple, Union

# ---------------------------------------------------------------- encoding

def encode_varint(value: int) -> bytes:
    if value < 0:
        value &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_tag(field: int, wire_type: int) -> bytes:
    return encode_varint((field << 3) | wire_type)


def encode_field(field: int, value, wire_type: int = None) -> bytes:
    """Encode one field. Type inferred when wire_type is None:
    int -> varint, float -> fixed64 (double), bytes/str -> length-delim."""
    if wire_type is None:
        if isinstance(value, bool):
            wire_type = 0
        elif isinstance(value, int):
            wire_type = 0
        elif isinstance(value, float):
            wire_type = 1
        elif isinstance(value, (bytes, bytearray, str)):
            wire_type = 2
        else:
            raise TypeError(f"cannot infer wire type for {type(value)}")
    if wire_type == 0:
        return encode_tag(field, 0) + encode_varint(int(value))
    if wire_type == 1:
        return encode_tag(field, 1) + struct.pack("<d", float(value))
    if wire_type == 5:
        return encode_tag(field, 5) + struct.pack("<f", float(value))
    if wire_type == 2:
        if isinstance(value, str):
            value = value.encode("utf-8")
        return (encode_tag(field, 2) + encode_varint(len(value)) +
                bytes(value))
    raise ValueError(f"bad wire type {wire_type}")


def encode_float32(field: int, value: float) -> bytes:
    return encode_field(field, value, wire_type=5)


def encode_double(field: int, value: float) -> bytes:
    return encode_field(field, value, wire_type=1)


def encode_packed_doubles(field: int, values) -> bytes:
    payload = b"".join(struct.pack("<d", float(v)) for v in values)
    return encode_field(field, payload, wire_type=2)


def encode_message(field: int, payload: bytes) -> bytes:
    return encode_field(field, payload, wire_type=2)


# ---------------------------------------------------------------- decoding

def decode_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not (b & 0x80):
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def iter_fields(buf: bytes) -> Iterator[Tuple[int, int, Union[int, bytes]]]:
    """Yield (field_number, wire_type, raw_value) over a message buffer.

    Varints come back as ints; fixed32/64 as raw 4/8 bytes; length-delimited
    as bytes.
    """
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = decode_varint(buf, pos)
        field, wire_type = key >> 3, key & 7
        if wire_type == 0:
            value, pos = decode_varint(buf, pos)
        elif wire_type == 1:
            value = buf[pos:pos + 8]
            pos += 8
        elif wire_type == 5:
            value = buf[pos:pos + 4]
            pos += 4
        elif wire_type == 2:
            length, pos = decode_varint(buf, pos)
            value = buf[pos:pos + length]
            pos += length
        elif wire_type in (3, 4):  # groups: skip (deprecated)
            continue
        else:
            raise ValueError(f"bad wire type {wire_type} at {pos}")
        yield field, wire_type, value


def parse_message(buf: bytes) -> Dict[int, List]:
    """Collect fields into {field_number: [raw values...]}."""
    out: Dict[int, List] = {}
    for field, _, value in iter_fields(buf):
        out.setdefault(field, []).append(value)
    return out


def as_double(raw) -> float:
    return struct.unpack("<d", raw)[0]


def as_float(raw) -> float:
    return struct.unpack("<f", raw)[0]


def as_string(raw: bytes) -> str:
    return raw.decode("utf-8")


def as_sint(raw: int) -> int:
    """Reinterpret a decoded varint as a signed 64-bit int (non-zigzag)."""
    if raw >= 1 << 63:
        return raw - (1 << 64)
    return raw


def unpack_packed_doubles(raw: bytes) -> List[float]:
    return [struct.unpack_from("<d", raw, i)[0]
            for i in range(0, len(raw), 8)]


def unpack_packed_floats(raw: bytes) -> List[float]:
    return [struct.unpack_from("<f", raw, i)[0]
            for i in range(0, len(raw), 4)]


def unpack_packed_varints(raw: bytes) -> List[int]:
    out = []
    pos = 0
    while pos < len(raw):
        v, pos = decode_varint(raw, pos)
        out.append(v)
    return out
