"""TensorFlow GraphDef export (reference: utils/tf/TensorflowSaver.scala +
BigDLToTensorflow.scala — save a trained model as a frozen graph other
frameworks can run).

Encodes NodeDefs with the in-repo wire codec. Covers the feed-forward
subset (Linear, SpatialConvolution NCHW→NHWC, pooling, activations,
Reshape, BatchNorm folded to scale/offset, Dropout→Identity, SoftMax,
LogSoftMax, containers).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.utils import proto

_TF_FLOAT = 1
_TF_INT32 = 3


def _attr(key: str, payload: bytes) -> bytes:
    return proto.encode_message(
        5, proto.encode_field(1, key) + proto.encode_message(2, payload))


def _attr_type(key: str, dtype: int = _TF_FLOAT) -> bytes:
    return _attr(key, proto.encode_field(6, dtype, wire_type=0))


def _attr_s(key: str, s: str) -> bytes:
    return _attr(key, proto.encode_field(2, s.encode()))


def _attr_ints(key: str, vals) -> bytes:
    lst = b"".join(proto.encode_field(3, int(v), wire_type=0) for v in vals)
    return _attr(key, proto.encode_message(1, lst))


def _tensor_proto(arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    if arr.dtype in (np.float64, np.float32):
        arr = arr.astype(np.float32)
        dtype = _TF_FLOAT
    else:
        arr = arr.astype(np.int32)
        dtype = _TF_INT32
    shape = b"".join(
        proto.encode_message(2, proto.encode_field(1, int(d), wire_type=0))
        for d in arr.shape)
    return (proto.encode_field(1, dtype, wire_type=0) +
            proto.encode_message(2, shape) +
            proto.encode_field(4, arr.tobytes(), wire_type=2))


def _node(name: str, op: str, inputs: List[str], *attrs: bytes) -> bytes:
    msg = proto.encode_field(1, name) + proto.encode_field(2, op)
    for i in inputs:
        msg += proto.encode_field(3, i)
    for a in attrs:
        msg += a
    return proto.encode_message(1, msg)


class GraphDefBuilder:
    def __init__(self):
        self.buf = b""
        self.names: Dict[str, int] = {}

    def unique(self, base: str) -> str:
        n = self.names.get(base, 0)
        self.names[base] = n + 1
        return base if n == 0 else f"{base}_{n}"

    def const(self, base: str, arr: np.ndarray) -> str:
        name = self.unique(base)
        dtype = _TF_FLOAT if np.asarray(arr).dtype.kind == "f" else _TF_INT32
        self.buf += _node(name, "Const", [],
                          _attr_type("dtype", dtype),
                          _attr("value",
                                proto.encode_message(8, _tensor_proto(arr))))
        return name

    def op(self, base: str, op: str, inputs: List[str],
           *attrs: bytes) -> str:
        name = self.unique(base)
        self.buf += _node(name, op, inputs, *attrs)
        return name

    def placeholder(self, name: str) -> str:
        self.buf += _node(name, "Placeholder", [], _attr_type("dtype"))
        return name


def _emit(module, params, g: GraphDefBuilder, inp: str, *,
          data_format: str) -> Tuple[str, str]:
    """Returns (output_ref, data_format). data_format tracks NCHW inputs
    converted to NHWC for TF ops."""
    import bigdl_tpu.nn as nn
    name = type(module).__name__

    if isinstance(module, nn.Sequential):
        cur, fmt = inp, data_format
        for i, child in enumerate(module.modules):
            cur, fmt = _emit(child, params[str(i)], g, cur,
                             data_format=fmt)
        return cur, fmt
    if isinstance(module, nn.Linear):
        if data_format == "NHWC_from_NCHW":
            # a conv ran before in converted layout; restore NCHW order
            inp = g.op("to_nchw", "Transpose",
                       [inp, g.const("perm", np.array([0, 3, 1, 2]))],
                       _attr_type("T"), _attr_type("Tperm", _TF_INT32))
            data_format = "NCHW"
        flat = g.op("flatten", "Reshape",
                    [inp, g.const("shape", np.array([-1, module.input_size],
                                                    np.int32))],
                    _attr_type("T"), _attr_type("Tshape", _TF_INT32))
        w = g.const("weight", np.asarray(params["weight"]).T)
        mm = g.op("dense", "MatMul", [flat, w], _attr_type("T"))
        if module.with_bias:
            b = g.const("bias", np.asarray(params["bias"]))
            mm = g.op("bias_add", "BiasAdd", [mm, b], _attr_type("T"))
        return mm, data_format
    if isinstance(module, nn.SpatialConvolution):
        if module.n_group != 1:
            raise ValueError(
                "TF export: grouped convolution (n_group > 1) is not "
                "supported — plain Conv2D would scramble channels")
        if data_format == "NCHW":
            inp = g.op("to_nhwc", "Transpose",
                       [inp, g.const("perm", np.array([0, 2, 3, 1]))],
                       _attr_type("T"), _attr_type("Tperm", _TF_INT32))
            data_format = "NHWC_from_NCHW"
        w = np.asarray(params["weight"])  # OIHW -> HWIO
        w = np.transpose(w, (2, 3, 1, 0))
        wn = g.const("kernel", w)
        if module.pad_h or module.pad_w:
            pads = np.array([[0, 0], [module.pad_h, module.pad_h],
                             [module.pad_w, module.pad_w], [0, 0]],
                            np.int32)
            inp = g.op("pad", "Pad",
                       [inp, g.const("paddings", pads)],
                       _attr_type("T"), _attr_type("Tpaddings", _TF_INT32))
        conv = g.op("conv", "Conv2D", [inp, wn], _attr_type("T"),
                    _attr_ints("strides",
                               [1, module.stride_h, module.stride_w, 1]),
                    _attr_s("padding", "VALID"))
        if module.with_bias:
            b = g.const("bias", np.asarray(params["bias"]))
            conv = g.op("bias_add", "BiasAdd", [conv, b], _attr_type("T"))
        return conv, data_format
    if isinstance(module, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
        is_max = isinstance(module, nn.SpatialMaxPooling)
        op = "MaxPool" if is_max else "AvgPool"
        if getattr(module, "ceil_mode", False):
            raise ValueError(
                "TF export: ceil-mode pooling has no MaxPool/AvgPool "
                "equivalent (SAME/VALID only); re-build the model with "
                "floor-mode pooling to export")
        if data_format == "NCHW":
            inp = g.op("to_nhwc", "Transpose",
                       [inp, g.const("perm", np.array([0, 2, 3, 1]))],
                       _attr_type("T"), _attr_type("Tperm", _TF_INT32))
            data_format = "NHWC_from_NCHW"
        if getattr(module, "pad_h", 0) or getattr(module, "pad_w", 0):
            pads = np.array([[0, 0], [module.pad_h, module.pad_h],
                             [module.pad_w, module.pad_w], [0, 0]],
                            np.int32)
            if is_max:
                # pad with -max so padding never wins the max
                out = g.op("pad", "PadV2",
                           [inp, g.const("paddings", pads),
                            g.const("pad_value",
                                    np.float32(np.finfo(np.float32).min))],
                           _attr_type("T"),
                           _attr_type("Tpaddings", _TF_INT32))
                inp = out
            else:
                inp = g.op("pad", "Pad",
                           [inp, g.const("paddings", pads)],
                           _attr_type("T"),
                           _attr_type("Tpaddings", _TF_INT32))
        out = g.op("pool", op, [inp], _attr_type("T"),
                   _attr_ints("ksize", [1, module.kh, module.kw, 1]),
                   _attr_ints("strides", [1, module.dh, module.dw, 1]),
                   _attr_s("padding", "VALID"))
        return out, data_format
    simple = {"ReLU": "Relu", "Tanh": "Tanh", "Sigmoid": "Sigmoid",
              "SoftMax": "Softmax", "LogSoftMax": "LogSoftmax",
              "Identity": "Identity", "Dropout": "Identity"}
    if name in simple:
        return g.op(name.lower(), simple[name], [inp],
                    _attr_type("T")), data_format
    if isinstance(module, nn.Reshape):
        if data_format == "NHWC_from_NCHW":
            # our Reshape semantics are NCHW-ordered; restore before
            # flattening
            inp = g.op("to_nchw", "Transpose",
                       [inp, g.const("perm", np.array([0, 3, 1, 2]))],
                       _attr_type("T"), _attr_type("Tperm", _TF_INT32))
            data_format = "NCHW"
        dims = [int(d) for d in module.size]
        return g.op("reshape", "Reshape",
                    [inp, g.const("shape",
                                  np.array([-1] + dims, np.int32))],
                    _attr_type("T"),
                    _attr_type("Tshape", _TF_INT32)), data_format
    raise ValueError(f"TF export: unsupported module {name}")


def save_tf_graph(path: str, module, input_name: str = "input",
                  data_format: str = "NCHW") -> Dict[str, str]:
    """Export a module tree to a frozen GraphDef .pb. Returns
    {"input": ..., "output": ...} node names."""
    module.ensure_initialized()
    g = GraphDefBuilder()
    inp = g.placeholder(input_name)
    out, fmt = _emit(module, module.get_parameters(), g, inp,
                     data_format=data_format)
    if fmt == "NHWC_from_NCHW":
        # restore the caller's NCHW layout at the graph output
        out = g.op("output_nchw", "Transpose",
                   [out, g.const("perm", np.array([0, 3, 1, 2]))],
                   _attr_type("T"), _attr_type("Tperm", _TF_INT32))
    with open(path, "wb") as f:
        f.write(g.buf)
    return {"input": input_name, "output": out}
