"""Scheme-aware file IO (reference: utils/File.scala — checkpoint and
model files on local disk, HDFS or S3).

Local paths use the standard library; any path with a ``scheme://``
(hdfs://, s3://, gs://, ...) routes through fsspec, whose installed
filesystem implementations provide the transport. All checkpoint and
module save/load paths in bigdl_tpu funnel through these helpers, so
remote storage works everywhere the reference's File.saveToHdfs did.
"""
from __future__ import annotations

import os
import re
from typing import List

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")


def is_remote(path: str) -> bool:
    return bool(_SCHEME_RE.match(path)) and not path.startswith("file://")


def _fs(path: str):
    import fsspec
    return fsspec.core.url_to_fs(path)[0]


def open_file(path: str, mode: str = "r"):
    if is_remote(path):
        if "w" in mode or "a" in mode or "x" in mode:
            # remote writes are where network blips become torn
            # checkpoints; the faultpoint lets the chaos harness script
            # exactly that (lazy import: local IO pays nothing)
            from bigdl_tpu import faults
            faults.point("file_io/remote_write", path=path)
        import fsspec
        return fsspec.open(path, mode).open()
    return open(path, mode)


def makedirs(path: str) -> None:
    if is_remote(path):
        _fs(path).makedirs(path, exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)


def exists(path: str) -> bool:
    if is_remote(path):
        return _fs(path).exists(path)
    return os.path.exists(path)


def isdir(path: str) -> bool:
    if is_remote(path):
        return _fs(path).isdir(path)
    return os.path.isdir(path)


def listdir(path: str) -> List[str]:
    """Base names of entries in a directory (local or remote)."""
    if is_remote(path):
        return [p.rstrip("/").rsplit("/", 1)[-1]
                for p in _fs(path).ls(path, detail=False)]
    return os.listdir(path)


def join(path: str, *parts: str) -> str:
    if is_remote(path):
        return "/".join([path.rstrip("/")] + [p.strip("/") for p in parts])
    return os.path.join(path, *parts)


def rename(src: str, dst: str) -> bool:
    """Rename ``src`` to ``dst`` (local or remote); returns False when
    the backing filesystem cannot rename (callers must then handle the
    original path remaining in place)."""
    if is_remote(src):
        try:
            _fs(src).mv(src, dst, recursive=True)
            return True
        except Exception:
            return False
    try:
        os.rename(src, dst)
        return True
    except OSError:  # read-only parent, cross-device link, ...
        return False


def file_sha256(path: str) -> str:
    """Streaming sha256 hex digest of one (local or remote) file — the
    checkpoint-integrity primitive MANIFEST digests are computed and
    verified with."""
    import hashlib
    h = hashlib.sha256()
    with open_file(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()
