"""Scheme-aware file IO (reference: utils/File.scala — checkpoint and
model files on local disk, HDFS or S3).

Local paths use the standard library; any path with a ``scheme://``
(hdfs://, s3://, gs://, ...) routes through fsspec, whose installed
filesystem implementations provide the transport. All checkpoint and
module save/load paths in bigdl_tpu funnel through these helpers, so
remote storage works everywhere the reference's File.saveToHdfs did.
"""
from __future__ import annotations

import os
import re
from typing import List

_SCHEME_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9+.-]*://")


def is_remote(path: str) -> bool:
    return bool(_SCHEME_RE.match(path)) and not path.startswith("file://")


def _fs(path: str):
    import fsspec
    return fsspec.core.url_to_fs(path)[0]


def open_file(path: str, mode: str = "r"):
    if is_remote(path):
        import fsspec
        return fsspec.open(path, mode).open()
    return open(path, mode)


def makedirs(path: str) -> None:
    if is_remote(path):
        _fs(path).makedirs(path, exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)


def exists(path: str) -> bool:
    if is_remote(path):
        return _fs(path).exists(path)
    return os.path.exists(path)


def isdir(path: str) -> bool:
    if is_remote(path):
        return _fs(path).isdir(path)
    return os.path.isdir(path)


def listdir(path: str) -> List[str]:
    """Base names of entries in a directory (local or remote)."""
    if is_remote(path):
        return [p.rstrip("/").rsplit("/", 1)[-1]
                for p in _fs(path).ls(path, detail=False)]
    return os.listdir(path)


def join(path: str, *parts: str) -> str:
    if is_remote(path):
        return "/".join([path.rstrip("/")] + [p.strip("/") for p in parts])
    return os.path.join(path, *parts)
