"""Caffe model export (reference: utils/caffe/CaffePersister.scala:47 —
writes .prototxt topology + .caffemodel binary weights so a bigdl model
can round-trip into Caffe tooling).

Inverse of utils/caffe.py: the prototxt carries the full topology+params
(the importer gives it priority), the caffemodel carries V2 ``layer``
messages with name/type/bottom/top + weight blobs encoded through the
in-repo protobuf wire codec (utils/proto.py) with public caffe.proto
field numbers. Round-trip contract: ``load_caffe(prototxt, caffemodel)``
rebuilds a Graph computing the same function.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.utils import proto


# ------------------------------------------------------------ blob encode

def encode_blob(arr: np.ndarray) -> bytes:
    """BlobProto: shape=7 (BlobShape packed dims field 1), data=5 packed
    float32 — the layout parse_blob reads back."""
    arr = np.asarray(arr, np.float32)
    dims = b"".join(proto.encode_varint(int(d)) for d in arr.shape)
    shape_msg = proto.encode_message(1, dims)
    payload = proto.encode_message(7, shape_msg)
    payload += proto.encode_message(5, arr.reshape(-1).tobytes())
    return payload


# -------------------------------------------------------- prototxt encode

# prototxt keys whose string values are protobuf enums (written bare);
# everything else — name/bottom/top/type… — must be quoted, or an
# all-caps layer name would emit invalid prototxt
_ENUM_KEYS = frozenset({"pool", "operation", "norm_region", "phase",
                        "backend", "db", "variance_norm", "eltwise_op"})


def _fmt_value(v, key: str = "") -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        s = repr(v)
        return s
    if isinstance(v, str):
        return v if (key in _ENUM_KEYS and v.isupper()) else f'"{v}"'
    return str(v)


def _emit(lines: List[str], indent: int, key: str, value):
    pad = "  " * indent
    if isinstance(value, dict):
        lines.append(f"{pad}{key} {{")
        for k, v in value.items():
            if isinstance(v, list):
                for el in v:
                    _emit(lines, indent + 1, k, el)
            else:
                _emit(lines, indent + 1, k, v)
        lines.append(f"{pad}}}")
    else:
        lines.append(f"{pad}{key}: {_fmt_value(value, key)}")


class _Spec:
    """One exported Caffe layer: prototxt message + weight blobs."""

    def __init__(self, name: str, type_: str, bottoms: Sequence[str],
                 top: str, params: Optional[Dict] = None,
                 blobs: Sequence[np.ndarray] = ()):
        self.name, self.type = name, type_
        self.bottoms, self.top = list(bottoms), top
        self.params = params or {}
        self.blobs = list(blobs)

    def prototxt(self) -> str:
        msg: Dict = {"name": self.name, "type": self.type}
        lines: List[str] = ["layer {"]
        _emit(lines, 1, "name", self.name)
        _emit(lines, 1, "type", self.type)
        for b in self.bottoms:
            _emit(lines, 1, "bottom", b)
        _emit(lines, 1, "top", self.top)
        for k, v in self.params.items():
            _emit(lines, 1, k, v)
        lines.append("}")
        return "\n".join(lines)

    def binary(self) -> bytes:
        out = proto.encode_message(1, self.name.encode())
        out += proto.encode_message(2, self.type.encode())
        for b in self.bottoms:
            out += proto.encode_message(3, b.encode())
        out += proto.encode_message(4, self.top.encode())
        for blob in self.blobs:
            out += proto.encode_message(7, encode_blob(blob))
        return out


# -------------------------------------------------------- module -> layer

def _convert_module(module, name: str, bottoms: List[str],
                    params: Dict, state: Dict) -> List[_Spec]:
    """bigdl_tpu module -> one (or two, for BN+Scale) Caffe layers.
    Mirrors the importer's CaffeLoader._convert table in reverse.

    ``params``/``state`` are the CONTAINER's subtrees for this module —
    asking the child for its own get_parameters() would lazily
    self-initialize it with fresh weights, silently exporting different
    numbers than the container computes with.
    """
    import bigdl_tpu.nn as nn

    t = type(module).__name__
    p = {k: np.asarray(v) for k, v in (params or {}).items()
         if not isinstance(v, dict)}

    if isinstance(module, nn.SpatialFullConvolution):
        w = p["weight"]  # stored (in, out/g, kh, kw)
        group = module.n_group
        cp = {"num_output": module.n_output_plane,
              "kernel_h": module.kh, "kernel_w": module.kw,
              "stride_h": module.dh, "stride_w": module.dw,
              "pad_h": module.pad_h, "pad_w": module.pad_w,
              "group": group, "bias_term": "bias" in p}
        blobs = [w] + ([p["bias"]] if "bias" in p else [])
        return [_Spec(name, "Deconvolution", bottoms, name,
                      {"convolution_param": cp}, blobs)]
    if isinstance(module, nn.SpatialConvolution):
        w = p["weight"]  # (out, in/g, kh, kw)
        cp = {"num_output": module.n_output_plane,
              "kernel_h": module.kernel_h, "kernel_w": module.kernel_w,
              "stride_h": module.stride_h, "stride_w": module.stride_w,
              "pad_h": module.pad_h, "pad_w": module.pad_w,
              "group": module.n_group, "bias_term": "bias" in p}
        blobs = [w] + ([p["bias"]] if "bias" in p else [])
        return [_Spec(name, "Convolution", bottoms, name,
                      {"convolution_param": cp}, blobs)]
    if isinstance(module, nn.Linear):
        w = p["weight"]  # (out, in)
        ip = {"num_output": w.shape[0], "bias_term": "bias" in p}
        blobs = [w] + ([p["bias"]] if "bias" in p else [])
        return [_Spec(name, "InnerProduct", bottoms, name,
                      {"inner_product_param": ip}, blobs)]
    if isinstance(module, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
        pool = "MAX" if isinstance(module, nn.SpatialMaxPooling) else "AVE"
        pp = {"pool": pool, "kernel_h": module.kh, "kernel_w": module.kw,
              "stride_h": module.dh, "stride_w": module.dw,
              "pad_h": module.pad_h, "pad_w": module.pad_w}
        return [_Spec(name, "Pooling", bottoms, name, {"pooling_param": pp})]
    if isinstance(module, nn.SpatialCrossMapLRN):
        lp = {"local_size": module.size, "alpha": module.alpha,
              "beta": module.beta, "k": module.k,
              "norm_region": "ACROSS_CHANNELS"}
        return [_Spec(name, "LRN", bottoms, name, {"lrn_param": lp})]
    if isinstance(module, nn.SpatialWithinChannelLRN):
        lp = {"local_size": module.size, "alpha": module.alpha,
              "beta": module.beta, "norm_region": "WITHIN_CHANNEL"}
        return [_Spec(name, "LRN", bottoms, name, {"lrn_param": lp})]
    if isinstance(module, (nn.SpatialBatchNormalization,
                           nn.BatchNormalization)):
        st = state or {}
        mean = np.asarray(st["running_mean"], np.float32)
        var = np.asarray(st["running_var"], np.float32)
        specs = [_Spec(name, "BatchNorm", bottoms, name,
                       {"batch_norm_param": {"use_global_stats": True,
                                             "eps": module.eps}},
                       [mean, var, np.ones((1,), np.float32)])]
        if module.affine:
            specs.append(_Spec(f"{name}_scale", "Scale", [name], name,
                               {"scale_param": {"bias_term": True}},
                               [p["weight"], p["bias"]]))
        return specs
    if isinstance(module, nn.Power):
        return [_Spec(name, "Power", bottoms, name,
                      {"power_param": {"power": module.power,
                                       "scale": module.scale,
                                       "shift": module.shift}})]
    if isinstance(module, nn.Dropout):
        return [_Spec(name, "Dropout", bottoms, name,
                      {"dropout_param": {"dropout_ratio": module.p}})]
    if isinstance(module, nn.JoinTable):
        return [_Spec(name, "Concat", bottoms, name,
                      {"concat_param": {"axis": module.dimension - 1}})]
    if isinstance(module, nn.CAddTable):
        return [_Spec(name, "Eltwise", bottoms, name,
                      {"eltwise_param": {"operation": "SUM"}})]
    if isinstance(module, nn.CMulTable):
        return [_Spec(name, "Eltwise", bottoms, name,
                      {"eltwise_param": {"operation": "PROD"}})]
    if isinstance(module, nn.CMaxTable):
        return [_Spec(name, "Eltwise", bottoms, name,
                      {"eltwise_param": {"operation": "MAX"}})]
    if isinstance(module, nn.InferReshape):
        if tuple(module.size) == (0, -1):
            return [_Spec(name, "Flatten", bottoms, name)]
        return [_Spec(name, "Reshape", bottoms, name,
                      {"reshape_param":
                       {"shape": {"dim": list(module.size)}}})]
    if isinstance(module, nn.View):
        # View(n) before InnerProduct is a flatten; Caffe's Flatten
        # collapses axes 1..end, the same function
        if len(module.sizes) == 1:
            return [_Spec(name, "Flatten", bottoms, name)]
        return [_Spec(name, "Reshape", bottoms, name,
                      {"reshape_param":
                       {"shape": {"dim": [0] + list(module.sizes)}}})]
    simple = {"ReLU": "ReLU", "Sigmoid": "Sigmoid", "Tanh": "TanH",
              "SoftMax": "Softmax", "Abs": "AbsVal"}
    if t in simple:
        return [_Spec(name, simple[t], bottoms, name)]
    raise ValueError(
        f"cannot export {t} to Caffe (CaffePersister supports the layer "
        "types CaffeLoader can read back)")


# ---------------------------------------------------------------- persist

class CaffePersister:
    """Export a Graph/Sequential to prototxt + caffemodel
    (CaffePersister.scala:47 saveToCaffe)."""

    def __init__(self, model, *, input_shapes: Optional[List] = None,
                 net_name: str = "bigdl_tpu"):
        self.model = model
        self.input_shapes = input_shapes
        self.net_name = net_name

    def _specs(self) -> Tuple[List[_Spec], List[str]]:
        import bigdl_tpu.nn as nn

        specs: List[_Spec] = []
        input_names: List[str] = []
        self.model.ensure_initialized()
        tree = dict(self.model.get_parameters())
        stree = dict(self.model.get_state())

        if isinstance(self.model, nn.Graph):
            g = self.model
            blob_of: Dict[int, str] = {}
            for i, n in enumerate(g.input_nodes):
                blob = "data" if len(g.input_nodes) == 1 else f"data{i}"
                blob_of[id(n)] = blob
                input_names.append(blob)
            for n in g.exec_order:
                if id(n) in blob_of:
                    continue
                name = g.node_names[id(n)]
                bottoms = [blob_of[id(p)] for p, _ in n.prevs]
                out = _convert_module(n.element, name, bottoms,
                                      tree.get(name, {}),
                                      stree.get(name, {}))
                specs.extend(out)
                blob_of[id(n)] = out[-1].top
        elif isinstance(self.model, nn.Sequential):
            input_names.append("data")
            self._walk_seq(self.model, tree, stree, "data", specs, "")
        else:
            raise ValueError("CaffePersister exports Graph or Sequential")
        return specs, input_names

    @staticmethod
    def _walk_seq(seq, tree, stree, prev: str, specs: List[_Spec],
                  prefix: str) -> str:
        """Flatten nested Sequential/Concat containers into the linear
        Caffe layer list (CaffePersister.scala walks containers the same
        way: branches fan out from one bottom, a Concat layer joins the
        branch tops)."""
        import bigdl_tpu.nn as nn

        for i, m in enumerate(seq.modules):
            name = m.get_name() or f"{prefix}{type(m).__name__.lower()}{i}"
            p = (tree or {}).get(str(i), {})
            s = (stree or {}).get(str(i), {})
            if isinstance(m, nn.Sequential):
                prev = CaffePersister._walk_seq(m, p, s, prev, specs,
                                                f"{name}_")
            elif isinstance(m, nn.Concat):
                tops = []
                for j, br in enumerate(m.modules):
                    bp = (p or {}).get(str(j), {})
                    bs = (s or {}).get(str(j), {})
                    bname = br.get_name() or f"{name}_b{j}"
                    if isinstance(br, nn.Sequential):
                        tops.append(CaffePersister._walk_seq(
                            br, bp, bs, prev, specs, f"{bname}_"))
                    else:
                        out = _convert_module(br, bname, [prev], bp, bs)
                        specs.extend(out)
                        tops.append(out[-1].top)
                specs.append(_Spec(name, "Concat", tops, name,
                                   {"concat_param":
                                    {"axis": m.dimension - 1}}))
                prev = name
            else:
                out = _convert_module(m, name, [prev], p, s)
                specs.extend(out)
                prev = out[-1].top
        return prev

    def save(self, def_path: str, model_path: str):
        specs, input_names = self._specs()
        # prototxt: Input layers first, then the net
        lines = [f'name: "{self.net_name}"']
        for i, blob in enumerate(input_names):
            shape = None
            if self.input_shapes is not None:
                shape = list(self.input_shapes[i])
            msg: Dict = {}
            if shape is not None:
                msg["input_param"] = {"shape": {"dim": shape}}
            spec = _Spec(blob, "Input", [], blob, msg)
            lines.append(spec.prototxt())
        lines += [s.prototxt() for s in specs]
        with open(def_path, "w") as f:
            f.write("\n".join(lines) + "\n")
        # caffemodel: NetParameter {1: name, 100: layer...}
        blob_bin = proto.encode_message(1, self.net_name.encode())
        for s in specs:
            blob_bin += proto.encode_message(100, s.binary())
        with open(model_path, "wb") as f:
            f.write(blob_bin)


def save_caffe(model, def_path: str, model_path: str, *,
               input_shapes: Optional[List] = None):
    """Module.saveCaffe equivalent (AbstractModule.saveCaffe)."""
    CaffePersister(model, input_shapes=input_shapes).save(def_path,
                                                          model_path)
