"""TFRecord reading/writing + tf.Example parsing (reference:
utils/tf/TFRecordIterator.scala, the ParseExample op in nn/ops/, and
FixedLengthRecordReader — the input side of executing TF data pipelines).

TFRecord wire format per record:
    [u64 length][u32 masked_crc32c(length)][data][u32 masked_crc32c(data)]

tf.Example is a protobuf: Example{features: Features{feature:
map<string, Feature>}} where Feature is one of bytes_list/float_list/
int64_list — decoded here with the in-repo wire codec (utils/proto.py),
no TF dependency.
"""
from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np

from bigdl_tpu.utils import proto
from bigdl_tpu.visualization.crc32c import masked_crc32c


def read_tfrecord(path: str, *, verify: bool = True) -> Iterator[bytes]:
    """Yield raw record payloads (TFRecordIterator.scala)."""
    with open(path, "rb") as f:
        while True:
            hdr = f.read(12)
            if not hdr:
                return
            if len(hdr) < 12:
                raise ValueError(f"{path}: truncated record header")
            (length,), crc = struct.unpack("<Q", hdr[:8]), \
                struct.unpack("<I", hdr[8:])[0]
            if verify and masked_crc32c(hdr[:8]) != crc:
                raise ValueError(f"{path}: length crc mismatch")
            data = f.read(length)
            if len(data) < length:
                raise ValueError(f"{path}: truncated record")
            dcrc = struct.unpack("<I", f.read(4))[0]
            if verify and masked_crc32c(data) != dcrc:
                raise ValueError(f"{path}: data crc mismatch")
            yield data


def write_tfrecord(path: str, records: Sequence[bytes]) -> None:
    """Write records in TFRecord framing (round-trip/test support)."""
    with open(path, "wb") as f:
        for data in records:
            hdr = struct.pack("<Q", len(data))
            f.write(hdr)
            f.write(struct.pack("<I", masked_crc32c(hdr)))
            f.write(data)
            f.write(struct.pack("<I", masked_crc32c(data)))


# --------------------------------------------------------------- Example

def parse_example(data: bytes) -> Dict[str, Any]:
    """tf.Example bytes -> {feature name: list/bytes/ndarray}.

    Example proto: features=1 -> Features{feature=1 (map entry:
    key=1 string, value=2 Feature)}; Feature: bytes_list=1, float_list=2,
    int64_list=3, each with repeated value=1 (the schema the reference's
    ParseExample op consumed, nn/ops/ParseExample).
    """
    out: Dict[str, Any] = {}
    ex = proto.parse_message(data)
    if 1 not in ex:
        return out
    features = proto.parse_message(ex[1][0])
    for entry_raw in features.get(1, []):
        entry = proto.parse_message(entry_raw)
        name = proto.as_string(entry[1][0])
        feat = proto.parse_message(entry[2][0])
        if 1 in feat:  # bytes_list
            bl = proto.parse_message(feat[1][0])
            vals = list(bl.get(1, []))
            out[name] = vals[0] if len(vals) == 1 else vals
        elif 2 in feat:  # float_list (packed or unpacked floats)
            fl = proto.parse_message(feat[2][0])
            vals: List[float] = []
            for raw in fl.get(1, []):
                if isinstance(raw, bytes):
                    if len(raw) % 4 == 0 and len(raw) > 4:
                        vals.extend(proto.unpack_packed_floats(raw))
                    else:
                        vals.append(proto.as_float(raw))
                else:
                    vals.append(float(raw))
            out[name] = np.asarray(vals, np.float32)
        elif 3 in feat:  # int64_list (packed or unpacked varints)
            il = proto.parse_message(feat[3][0])
            vals = []
            for raw in il.get(1, []):
                if isinstance(raw, bytes):
                    vals.extend(proto.as_sint(v)
                                for v in proto.unpack_packed_varints(raw))
                else:
                    vals.append(proto.as_sint(raw))
            out[name] = np.asarray(vals, np.int64)
        else:
            out[name] = None
    return out


def encode_example(features: Dict[str, Any]) -> bytes:
    """Inverse of parse_example (for tests and export pipelines)."""
    entries = b""
    for name, value in features.items():
        if isinstance(value, (bytes, bytearray)):
            inner = proto.encode_message(1, bytes(value))
            feat = proto.encode_message(1, inner)
        else:
            arr = np.asarray(value)
            if np.issubdtype(arr.dtype, np.floating):
                inner = b"".join(
                    proto.encode_float32(1, float(v)) for v in arr.ravel())
                feat = proto.encode_message(2, inner)
            else:
                inner = b"".join(
                    proto.encode_field(1, int(v)) for v in arr.ravel())
                feat = proto.encode_message(3, inner)
        entry = proto.encode_message(1, name.encode()) \
            + proto.encode_message(2, feat)
        entries += proto.encode_message(1, entry)
    return proto.encode_message(1, entries)


def example_dataset(path: str, *, feature: str = "image/raw",
                    label: str = "label",
                    shape: Optional[Sequence[int]] = None):
    """Read a TFRecord of Examples into (features, labels) arrays — the
    TFRecord input path of the reference's Session pipelines."""
    feats, labels = [], []
    for rec in read_tfrecord(path):
        ex = parse_example(rec)
        v = ex[feature]
        if isinstance(v, (bytes, bytearray)):
            v = np.frombuffer(v, np.uint8).astype(np.float32)
        feats.append(np.asarray(v, np.float32))
        lv = ex[label]
        labels.append(float(np.asarray(lv).ravel()[0]))
    X = np.stack(feats)
    if shape is not None:
        X = X.reshape((len(X),) + tuple(shape))
    return X, np.asarray(labels, np.float32)
