"""Generic DAG used by the Graph container and model importers.

Mirrors BigDL ``utils/DirectedGraph.scala:36`` / ``Node``:183 — nodes hold an
``element`` payload, edges are directed; supports topological sort, BFS, DFS
and reverse-graph construction. Pure host-side metadata: the actual compute
graph is traced by JAX, this structure only orders module execution.
"""
from __future__ import annotations

from typing import Any, Iterator, List, Optional


class Edge:
    __slots__ = ("from_index",)

    def __init__(self, from_index: Optional[int] = None):
        # 1-based index selecting a slot of the source node's output Table,
        # None = whole output (DirectedGraph.scala Edge semantics).
        self.from_index = from_index


class Node:
    """Graph node wrapping an element (usually a Module)."""

    def __init__(self, element: Any):
        self.element = element
        self.prevs: List[tuple] = []  # (node, edge)
        self.nexts: List[tuple] = []  # (node, edge)

    def add(self, other: "Node", edge: Optional[Edge] = None) -> "Node":
        """self -> other (DirectedGraph.scala:205)."""
        e = edge or Edge()
        if (other, e) not in self.nexts:
            self.nexts.append((other, e))
            other.prevs.append((self, e))
        return other

    def __call__(self, *prev_nodes):
        """Functional-API sugar: node(inputs...) wires inputs -> node."""
        for p in prev_nodes:
            if isinstance(p, tuple):  # (node, from_index)
                p[0].add(self, Edge(p[1]))
            else:
                p.add(self)
        return self

    def remove_prev_edges(self):
        for p, e in self.prevs:
            p.nexts = [(n, ee) for (n, ee) in p.nexts if n is not self]
        self.prevs = []

    def __repr__(self):
        return f"Node({self.element!r})"


class DirectedGraph:
    """DAG rooted at ``source``; ``reverse=True`` flips edge direction."""

    def __init__(self, source: Node, reverse: bool = False):
        self.source = source
        self.reverse = reverse

    def _next(self, node: Node):
        pairs = node.prevs if self.reverse else node.nexts
        return [n for n, _ in pairs]

    def _prev_count(self, node: Node) -> int:
        pairs = node.nexts if self.reverse else node.prevs
        return len(pairs)

    def bfs(self) -> Iterator[Node]:
        """Breadth-first traversal (DirectedGraph.scala:114)."""
        seen = set()
        queue = [self.source]
        while queue:
            node = queue.pop(0)
            if id(node) in seen:
                continue
            seen.add(id(node))
            yield node
            queue.extend(self._next(node))

    def dfs(self) -> Iterator[Node]:
        """Depth-first traversal (DirectedGraph.scala:87)."""
        seen = set()
        stack = [self.source]
        while stack:
            node = stack.pop()
            if id(node) in seen:
                continue
            seen.add(id(node))
            yield node
            stack.extend(reversed(self._next(node)))

    def topology_sort(self) -> List[Node]:
        """Kahn's algorithm from source (DirectedGraph.scala:54)."""
        nodes = list(self.bfs())
        indegree = {id(n): 0 for n in nodes}
        for n in nodes:
            for m in self._next(n):
                if id(m) in indegree:
                    indegree[id(m)] += 1
        ready = [n for n in nodes if indegree[id(n)] == 0]
        out: List[Node] = []
        while ready:
            n = ready.pop(0)
            out.append(n)
            for m in self._next(n):
                indegree[id(m)] -= 1
                if indegree[id(m)] == 0:
                    ready.append(m)
        if len(out) != len(nodes):
            raise ValueError("Graph contains a cycle")
        return out

    def size(self) -> int:
        return sum(1 for _ in self.bfs())
