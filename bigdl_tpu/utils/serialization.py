"""Checkpoint & model persistence (BigDL utils/serializer + utils/File.scala).

Native format: a directory with ``spec.json`` (pytree structure + host state)
and ``arrays.npz`` (flattened leaves). Readable without the framework; stable
across processes. The reference's protobuf module format (ModuleSerializer)
maps to ``save_module``/``load_module`` which additionally record the module
class and constructor args for zoo models that register themselves.
"""
from __future__ import annotations

import json
import logging
import os
import re
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.utils import file_io

logger = logging.getLogger("bigdl_tpu")

_CKPT_SAVE_S = telemetry.histogram(
    "train/checkpoint/save_s", "wall-clock seconds per checkpoint save")
_CKPT_LOAD_S = telemetry.histogram(
    "train/checkpoint/load_s", "wall-clock seconds per checkpoint load")


def _flatten_with_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        out[key] = np.asarray(leaf)
    return out, treedef


def _tree_to_template(tree):
    """JSON-able structure with leaf placeholders."""
    if isinstance(tree, dict):
        return {k: _tree_to_template(v) for k, v in sorted(tree.items())}
    from bigdl_tpu.utils.table import Table
    if isinstance(tree, Table):
        return {"__table__": {str(k): _tree_to_template(v)
                              for k, v in tree.items()}}
    return "__leaf__"


def _rebuild(template, arrays, prefix=""):
    from bigdl_tpu.utils.table import Table
    if template == "__leaf__":
        return arrays[prefix.rstrip("/")]
    if isinstance(template, dict) and "__table__" in template:
        t = Table()
        for k, v in template["__table__"].items():
            key = int(k) if k.lstrip("-").isdigit() else k
            t[key] = _rebuild(v, arrays, f"{prefix}{k}/")
        return t
    out = {}
    for k, v in template.items():
        out[k] = _rebuild(v, arrays, f"{prefix}{k}/")
    return out


def _host_leaf(a) -> np.ndarray:
    """Leaf -> host numpy, including multi-host global arrays that span
    non-addressable devices (e.g. ZeRO-1 shards): reshard to replicated
    on device (an all-gather over the mesh), then read — every host
    checkpoints the same full value (DistriOptimizer saves the
    assembled weights the same way, :433-463)."""
    try:
        return np.asarray(a)
    except Exception:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        repl = NamedSharding(a.sharding.mesh, PartitionSpec())
        return np.asarray(jax.jit(lambda x: x, out_shardings=repl)(a))


host_value = _host_leaf  # public alias: leaf -> host numpy, multi-host safe


def _flatten_leaves(tree, prefix=""):
    from bigdl_tpu.utils.table import Table
    out = {}
    if isinstance(tree, Table):
        for k, v in tree.items():
            out.update(_flatten_leaves(v, f"{prefix}{k}/"))
    elif isinstance(tree, dict):
        for k, v in sorted(tree.items()):
            out.update(_flatten_leaves(v, f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = _host_leaf(tree)
    return out


def save_tree(path_prefix: str, tree) -> None:
    """Save a pytree as <prefix>.json + <prefix>.npz (local or remote —
    utils/File.scala's HDFS/S3 role via file_io)."""
    arrays = _flatten_leaves(tree)
    template = _tree_to_template(tree)
    with file_io.open_file(path_prefix + ".json", "w") as f:
        json.dump(template, f)
    with file_io.open_file(path_prefix + ".npz", "wb") as f:
        np.savez(f, **arrays)


def load_tree(path_prefix: str):
    """Read a pytree saved by :func:`save_tree`."""
    with file_io.open_file(path_prefix + ".json") as f:
        template = json.load(f)
    with file_io.open_file(path_prefix + ".npz", "rb") as f:
        with np.load(f) as z:
            arrays = {k: z[k] for k in z.files}
    return _rebuild(template, arrays)


MANIFEST = "MANIFEST.json"
_CKPT_FILES = ("params", "opt_state", "model_state")


class CheckpointCorrupt(RuntimeError):
    """A checkpoint directory failed integrity verification: a file
    named by its MANIFEST is missing or its content no longer matches
    the sha256 recorded at write time. Raised by
    :func:`verify_checkpoint` / :func:`load_checkpoint`; the
    optimizer's resume path quarantines the directory and walks back
    to the previous intact checkpoint."""

    # the only way this ESCAPES _try_resume is the quarantine-
    # impossible path (unrenamable filesystem) — retrying re-hashes
    # the same corrupt dir forever, so the retry classifier must fail
    # fast despite the RuntimeError base
    bigdl_fatal = True


def _fsync(f) -> None:
    try:
        f.flush()
        os.fsync(f.fileno())
    except (OSError, AttributeError):
        pass  # remote file objects / fs without fsync


def _write_json(path: str, obj) -> None:
    with open(path, "w") as f:
        json.dump(obj, f)
        _fsync(f)


def _fsync_dir(d: str) -> None:
    try:
        fd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def _part_blobs(flats, host):
    """Yield each checkpoint file as (filename, bytes, sha256hex), one
    part at a time — digests hash the exact serialized bytes, so both
    the local and remote writers get MANIFEST integrity in a single
    pass (no write-then-re-read). Peak extra memory is one part's
    serialization, never the whole checkpoint twice."""
    import hashlib
    import io

    def blob(fname, data):
        return fname, data, hashlib.sha256(data).hexdigest()

    for name, (arrays, template) in flats.items():
        yield blob(name + ".json", json.dumps(template).encode())
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        yield blob(name + ".npz", buf.getvalue())
    yield blob("host_state.json", json.dumps(host).encode())


def publish_checkpoint_dir(staged: str, path: str,
                           debris_prefixes=(".tmp-", ".old-")) -> None:
    """Atomically publish a fully-written (MANIFEST-complete) staged
    checkpoint dir — the ONE crash-safety-critical commit dance shared
    by the sync format-2 writer and the elastic format-3 committer:
    rename any existing destination aside (complete->complete only),
    rename the staged dir into place, fsync the parent, and only THEN
    sweep superseded ``<base><prefix>*`` debris. A crash at any point
    leaves either the previous or the new complete checkpoint
    reachable (a stray complete dir is still found by
    ``find_latest_checkpoint`` via its MANIFEST)."""
    import shutil
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    base = os.path.basename(path)
    old = f"{path}.old-{os.getpid()}"
    if os.path.exists(path):
        os.rename(path, old)
    os.rename(staged, path)
    _fsync_dir(parent)
    doomed = tuple(base + p for p in debris_prefixes)
    for name in os.listdir(parent):
        if name.startswith(doomed):
            shutil.rmtree(os.path.join(parent, name), ignore_errors=True)


def _crash_env_matches(ctx) -> bool:
    """BIGDL_TEST_CRASH_IN_CHECKPOINT names this save's neval (read at
    fire time, like the pre-faults hook did — a harness may set the
    variable after arming)."""
    at = os.environ.get("BIGDL_TEST_CRASH_IN_CHECKPOINT")
    return bool(at) and int(at) == ctx.get("neval", -1)


def arm_scripted_crash() -> None:
    """Explicit opt-in for the mid-checkpoint-write SIGKILL (the
    reference scripted worker deaths the same way, ExceptionTest /
    TestUtils.scala:103-131). A test harness must call this IN
    ADDITION to setting BIGDL_TEST_CRASH_IN_CHECKPOINT — so a stray
    env var inherited from a test environment can never SIGKILL a real
    training run (ADVICE r5). Implemented as a ``ckpt/write_manifest``
    SIGKILL schedule on the :mod:`bigdl_tpu.faults` framework: the
    process dies after the tree files, before the MANIFEST."""
    from bigdl_tpu import faults
    rule = faults.FaultRule("ckpt/write_manifest", action="sigkill",
                            predicate=_crash_env_matches)
    sched = faults.active_schedule() if faults.is_armed() else None
    if sched is None:
        sched = faults.FaultSchedule()
    faults.arm(sched.add(rule))


def save_checkpoint(path: str, *, params, opt_state, model_state,
                    optim_host_state: Dict[str, Any],
                    driver_state: Dict[str, Any],
                    writer: bool = True) -> None:
    """Checkpoint a training run crash-safely (see
    :func:`_save_checkpoint_impl` for the atomicity contract); the
    wall-clock cost lands in the ``train/checkpoint/save_s`` telemetry
    histogram and a ``checkpoint/save`` span."""
    t0 = time.perf_counter()
    try:
        with telemetry.span("checkpoint/save", path=path):
            _save_checkpoint_impl(
                path, params=params, opt_state=opt_state,
                model_state=model_state,
                optim_host_state=optim_host_state,
                driver_state=driver_state, writer=writer)
    finally:
        _CKPT_SAVE_S.observe(time.perf_counter() - t0)


def _save_checkpoint_impl(path: str, *, params, opt_state, model_state,
                          optim_host_state: Dict[str, Any],
                          driver_state: Dict[str, Any],
                          writer: bool = True) -> None:
    """Checkpoint a training run (DistriOptimizer.checkpoint :433-463),
    crash-safely:

    - everything is staged in ``<path>.tmp-*``, fsynced, and the
      directory atomically renamed into place — a process killed at ANY
      point leaves either the previous complete checkpoint or a stray
      tmp/old dir that ``find_latest_checkpoint`` never selects, never
      a torn ``<path>``;
    - a ``MANIFEST.json`` is written LAST (after a dir fsync), so even
      on remote filesystems without atomic rename its presence certifies
      completeness;
    - in multi-host runs pass ``writer=jax.process_index() == 0``: every
      process participates in the all-gather that materializes sharded
      leaves (``_host_leaf`` resharding is collective), but only the
      single writer touches storage — the reference wrote once from the
      driver, not N× from executors (DistriOptimizer.scala:433-463).
    """
    # host materialization runs on EVERY process (collective resharding
    # of ZeRO-1/TP-sharded leaves) and in deterministic order
    parts = {"params": params, "opt_state": opt_state,
             "model_state": model_state}
    flats = {k: (_flatten_leaves(t), _tree_to_template(t))
             for k, t in parts.items()}
    if not writer:
        return
    from bigdl_tpu import faults
    host = {"optim_host_state": optim_host_state,
            "driver_state": driver_state}
    files = [f"{n}.{ext}" for n in _CKPT_FILES
             for ext in ("json", "npz")] + ["host_state.json"]
    # format 2: the MANIFEST records each file's sha256 — load verifies
    # them, so a corrupt-at-rest checkpoint (bit rot, truncation AFTER
    # the manifest landed) is detected and quarantined instead of
    # resumed from
    manifest = {"format": 2,
                "neval": driver_state.get("neval"),
                "files": files,
                "sha256": {}}
    if file_io.is_remote(path):
        # no atomic rename on object stores: MANIFEST-last ordering is
        # the completeness certificate; each digest hashes the exact
        # bytes shipped
        file_io.makedirs(path)
        for fname, data, digest in _part_blobs(flats, host):
            manifest["sha256"][fname] = digest
            with file_io.open_file(file_io.join(path, fname), "wb") as f:
                f.write(data)
        faults.point("ckpt/write_manifest",
                     neval=driver_state.get("neval", -1), path=path)
        with file_io.open_file(file_io.join(path, MANIFEST), "w") as f:
            json.dump(manifest, f)
        return

    import shutil
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.exists(tmp):  # our own earlier failed attempt
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    for fname, data, digest in _part_blobs(flats, host):
        manifest["sha256"][fname] = digest
        with open(os.path.join(tmp, fname), "wb") as f:
            f.write(data)
            _fsync(f)
    faults.point("ckpt/write_manifest",
                 neval=driver_state.get("neval", -1), path=path)
    _write_json(os.path.join(tmp, MANIFEST), manifest)
    _fsync_dir(tmp)
    publish_checkpoint_dir(tmp, path)


def verify_checkpoint(path: str) -> None:
    """Integrity-check one checkpoint dir against its MANIFEST: every
    listed file must exist and (format >= 2) hash to its recorded
    sha256. Raises :class:`CheckpointCorrupt` naming the first bad
    file; a format-0/1 dir (no MANIFEST / no digests) passes — its
    completeness certificate is presence-only, the pre-integrity
    contract."""
    mpath = file_io.join(path, MANIFEST)
    if not file_io.exists(mpath):
        from bigdl_tpu.elastic.checkpoint import is_torn_commit
        if is_torn_commit(path):
            # phase-1 part files with no MANIFEST: a death between the
            # last part write and the manifest fsync (the elastic
            # two-phase commit's torn state) — quarantinable, never a
            # format-0 pass
            raise CheckpointCorrupt(
                f"{path}: torn elastic commit (PART files present, no "
                "MANIFEST)")
        return  # format-0 back-compat: nothing recorded to verify
    try:
        with file_io.open_file(mpath) as f:
            manifest = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorrupt(f"{path}: unreadable MANIFEST ({e})")
    digests = manifest.get("sha256") or {}
    for fname in manifest.get("files", []):
        fpath = file_io.join(path, fname)
        if not file_io.exists(fpath):
            raise CheckpointCorrupt(
                f"{path}: MANIFEST names {fname} but it is missing")
        want = digests.get(fname)
        if want is None:
            continue  # format-1: files listed, no digests recorded
        got = file_io.file_sha256(fpath)
        if got != want:
            raise CheckpointCorrupt(
                f"{path}: {fname} fails its recorded sha256 "
                f"(got {got[:12]}…, want {want[:12]}…)")


def quarantine_checkpoint(path: str) -> Optional[str]:
    """Move a corrupt checkpoint dir aside to ``<path>.corrupt-<pid>``
    (kept for post-mortem, never selected by
    :func:`find_latest_checkpoint`) so resume walks back to the
    previous intact checkpoint instead of re-raising on the same bad
    dir every retry. Returns the quarantine path, or None when the
    backing filesystem cannot rename."""
    dst = f"{path}.corrupt-{os.getpid()}"
    if file_io.rename(path, dst):
        logger.warning("quarantined corrupt checkpoint %s -> %s",
                       path, dst)
        return dst
    return None


def load_checkpoint(path: str, verify: bool = True) -> Dict[str, Any]:
    """Read one complete checkpoint dir written by
    :func:`save_checkpoint`, integrity-verifying it first (every
    MANIFEST-listed file present and matching its recorded sha256 —
    :class:`CheckpointCorrupt` otherwise; ``verify=False`` skips the
    hash pass). The wall-clock cost lands in the
    ``train/checkpoint/load_s`` telemetry histogram and a
    ``checkpoint/load`` span."""
    t0 = time.perf_counter()
    try:
        mpath = file_io.join(path, MANIFEST)
        if file_io.exists(mpath):
            try:
                with file_io.open_file(mpath) as f:
                    fmt = int(json.load(f).get("format", 0))
            except (OSError, ValueError) as e:
                raise CheckpointCorrupt(
                    f"{path}: unreadable MANIFEST ({e})")
            if fmt >= 3:
                # per-shard elastic layout: reassemble the global
                # arrays from the parts via the manifest's sharding
                # metadata (the same dict shape comes back, plus the
                # "sharding"/"cursors" elastic extras)
                from bigdl_tpu.elastic.resume import load_parts
                return load_parts(path, verify=verify)
        with telemetry.span("checkpoint/load", path=path):
            if verify:
                verify_checkpoint(path)
            with file_io.open_file(
                    file_io.join(path, "host_state.json")) as f:
                host = json.load(f)
            return {
                "params": load_tree(file_io.join(path, "params")),
                "opt_state": load_tree(file_io.join(path, "opt_state")),
                "model_state": load_tree(
                    file_io.join(path, "model_state")),
                "optim_host_state": host["optim_host_state"],
                "driver_state": host["driver_state"],
            }
    finally:
        _CKPT_LOAD_S.observe(time.perf_counter() - t0)


def list_complete_checkpoints(directory: str) -> list:
    """Every COMPLETE checkpoint dir under ``directory`` as a sorted
    ``[(recency_key, path), ...]`` (oldest first) — the ONE place the
    completeness + recency rules live, consumed by both
    :func:`find_latest_checkpoint` and the elastic retention GC
    (``elastic.prune_checkpoints``), so the two can never drift on
    which dirs count. Completeness is certified by the MANIFEST
    written last by the savers (stray-but-complete ``*.tmp-*`` /
    ``*.old-*`` / ``*.staging-*`` dirs — a crash between the MANIFEST
    write and the final rename — still count), with the format-0
    back-compat exception: properly-named pre-MANIFEST dirs, neval
    from the name suffix. ``*.corrupt-*`` quarantines never count.
    The recency key is ``(neval, proper)`` — a properly-named dir
    wins over a same-neval stray."""
    out = []
    if not file_io.isdir(directory):
        return out
    for name in sorted(file_io.listdir(directory)):
        full = file_io.join(directory, name)
        if not name.startswith("checkpoint") or not file_io.isdir(full):
            continue
        if ".corrupt-" in name:
            continue  # quarantined by a failed verify: never re-selected
        if not file_io.exists(file_io.join(full, "host_state.json")):
            continue
        proper = re.match(r"checkpoint(\.\d+)?$", name) is not None
        has_manifest = file_io.exists(file_io.join(full, MANIFEST))
        if has_manifest:
            try:
                with file_io.open_file(file_io.join(full, MANIFEST)) as f:
                    neval = json.load(f).get("neval") or 0
            except (OSError, ValueError):
                continue
        elif proper:
            # format-0 back-compat: checkpoints written before the
            # MANIFEST existed carry no completeness certificate —
            # accept properly-named ones (the pre-change behavior;
            # strays without a manifest stay torn-write debris) with
            # neval from the dir suffix
            m = re.match(r"checkpoint\.(\d+)$", name)
            neval = int(m.group(1)) if m else 0
        else:
            continue
        out.append(((neval, proper), full))
    out.sort(key=lambda e: e[0])
    return out


def find_latest_checkpoint(directory: str) -> Optional[str]:
    """Latest COMPLETE checkpoint dir
    (DistriOptimizer.getLatestFile :867-880), per the
    :func:`list_complete_checkpoints` completeness/recency rules — a
    torn dir from a mid-write crash is never selected, so a resume
    after a checkpoint-time death lands on the previous intact
    checkpoint, and no crash point makes the newest complete state
    unreachable."""
    entries = list_complete_checkpoints(directory)
    return entries[-1][1] if entries else None


# -- module-level save/load (ModuleSerializer analogue) ---------------------

def save_module(path: str, module) -> None:
    """Persist a module: topology spec + params + state.

    The saved directory is self-contained — ``load_module`` reconstructs the
    module tree (class, constructor args, children, graph wiring) and its
    weights without any user code, like the reference's
    ``Module.loadModule`` (utils/serializer/ModuleLoader.scala).
    """
    from bigdl_tpu.utils.module_serializer import to_spec
    file_io.makedirs(path)
    module.ensure_initialized()
    save_tree(file_io.join(path, "params"), module.get_parameters())
    save_tree(file_io.join(path, "state"), module.get_state())
    meta = {"class": type(module).__name__, "name": module.get_name(),
            "spec": to_spec(module), "format_version": 1}
    with file_io.open_file(file_io.join(path, "module.json"), "w") as f:
        json.dump(meta, f)


def load_module(path: str):
    """Rebuild a module (topology + weights) saved by ``save_module``."""
    from bigdl_tpu.utils.module_serializer import from_spec
    with file_io.open_file(file_io.join(path, "module.json")) as f:
        meta = json.load(f)
    module = from_spec(meta["spec"])
    return load_module_weights(path, module)


def load_module_weights(path: str, module):
    """Load params/state saved by save_module into a compatible module."""
    module.set_parameters(load_tree(file_io.join(path, "params")))
    module.set_state(load_tree(file_io.join(path, "state")))
    return module
