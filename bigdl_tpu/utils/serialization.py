"""Checkpoint & model persistence (BigDL utils/serializer + utils/File.scala).

Native format: a directory with ``spec.json`` (pytree structure + host state)
and ``arrays.npz`` (flattened leaves). Readable without the framework; stable
across processes. The reference's protobuf module format (ModuleSerializer)
maps to ``save_module``/``load_module`` which additionally record the module
class and constructor args for zoo models that register themselves.
"""
from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

import jax
import numpy as np

from bigdl_tpu.utils import file_io


def _flatten_with_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        out[key] = np.asarray(leaf)
    return out, treedef


def _tree_to_template(tree):
    """JSON-able structure with leaf placeholders."""
    if isinstance(tree, dict):
        return {k: _tree_to_template(v) for k, v in sorted(tree.items())}
    from bigdl_tpu.utils.table import Table
    if isinstance(tree, Table):
        return {"__table__": {str(k): _tree_to_template(v)
                              for k, v in tree.items()}}
    return "__leaf__"


def _rebuild(template, arrays, prefix=""):
    from bigdl_tpu.utils.table import Table
    if template == "__leaf__":
        return arrays[prefix.rstrip("/")]
    if isinstance(template, dict) and "__table__" in template:
        t = Table()
        for k, v in template["__table__"].items():
            key = int(k) if k.lstrip("-").isdigit() else k
            t[key] = _rebuild(v, arrays, f"{prefix}{k}/")
        return t
    out = {}
    for k, v in template.items():
        out[k] = _rebuild(v, arrays, f"{prefix}{k}/")
    return out


def _host_leaf(a) -> np.ndarray:
    """Leaf -> host numpy, including multi-host global arrays that span
    non-addressable devices (e.g. ZeRO-1 shards): reshard to replicated
    on device (an all-gather over the mesh), then read — every host
    checkpoints the same full value (DistriOptimizer saves the
    assembled weights the same way, :433-463)."""
    try:
        return np.asarray(a)
    except Exception:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        repl = NamedSharding(a.sharding.mesh, PartitionSpec())
        return np.asarray(jax.jit(lambda x: x, out_shardings=repl)(a))


host_value = _host_leaf  # public alias: leaf -> host numpy, multi-host safe


def _flatten_leaves(tree, prefix=""):
    from bigdl_tpu.utils.table import Table
    out = {}
    if isinstance(tree, Table):
        for k, v in tree.items():
            out.update(_flatten_leaves(v, f"{prefix}{k}/"))
    elif isinstance(tree, dict):
        for k, v in sorted(tree.items()):
            out.update(_flatten_leaves(v, f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = _host_leaf(tree)
    return out


def save_tree(path_prefix: str, tree) -> None:
    """Save a pytree as <prefix>.json + <prefix>.npz (local or remote —
    utils/File.scala's HDFS/S3 role via file_io)."""
    arrays = _flatten_leaves(tree)
    template = _tree_to_template(tree)
    with file_io.open_file(path_prefix + ".json", "w") as f:
        json.dump(template, f)
    with file_io.open_file(path_prefix + ".npz", "wb") as f:
        np.savez(f, **arrays)


def load_tree(path_prefix: str):
    with file_io.open_file(path_prefix + ".json") as f:
        template = json.load(f)
    with file_io.open_file(path_prefix + ".npz", "rb") as f:
        with np.load(f) as z:
            arrays = {k: z[k] for k in z.files}
    return _rebuild(template, arrays)


def save_checkpoint(path: str, *, params, opt_state, model_state,
                    optim_host_state: Dict[str, Any],
                    driver_state: Dict[str, Any]) -> None:
    """Checkpoint a training run (DistriOptimizer.checkpoint :433-463)."""
    file_io.makedirs(path)
    save_tree(file_io.join(path, "params"), params)
    save_tree(file_io.join(path, "opt_state"), opt_state)
    save_tree(file_io.join(path, "model_state"), model_state)
    host = {"optim_host_state": optim_host_state,
            "driver_state": driver_state}
    with file_io.open_file(file_io.join(path, "host_state.json"), "w") as f:
        json.dump(host, f)


def load_checkpoint(path: str) -> Dict[str, Any]:
    with file_io.open_file(file_io.join(path, "host_state.json")) as f:
        host = json.load(f)
    return {
        "params": load_tree(file_io.join(path, "params")),
        "opt_state": load_tree(file_io.join(path, "opt_state")),
        "model_state": load_tree(file_io.join(path, "model_state")),
        "optim_host_state": host["optim_host_state"],
        "driver_state": host["driver_state"],
    }


def find_latest_checkpoint(directory: str) -> Optional[str]:
    """Latest ``checkpoint.N`` dir (DistriOptimizer.getLatestFile :867-880)."""
    if not file_io.isdir(directory):
        return None
    best, best_n = None, -1
    for name in file_io.listdir(directory):
        full = file_io.join(directory, name)
        if not file_io.isdir(full):
            continue
        if name == "checkpoint":
            n = 0
        else:
            m = re.match(r"checkpoint\.(\d+)$", name)
            if not m:
                continue
            n = int(m.group(1))
        if n >= best_n and file_io.exists(
                file_io.join(full, "host_state.json")):
            best, best_n = full, n
    return best


# -- module-level save/load (ModuleSerializer analogue) ---------------------

def save_module(path: str, module) -> None:
    """Persist a module: topology spec + params + state.

    The saved directory is self-contained — ``load_module`` reconstructs the
    module tree (class, constructor args, children, graph wiring) and its
    weights without any user code, like the reference's
    ``Module.loadModule`` (utils/serializer/ModuleLoader.scala).
    """
    from bigdl_tpu.utils.module_serializer import to_spec
    file_io.makedirs(path)
    module.ensure_initialized()
    save_tree(file_io.join(path, "params"), module.get_parameters())
    save_tree(file_io.join(path, "state"), module.get_state())
    meta = {"class": type(module).__name__, "name": module.get_name(),
            "spec": to_spec(module), "format_version": 1}
    with file_io.open_file(file_io.join(path, "module.json"), "w") as f:
        json.dump(meta, f)


def load_module(path: str):
    """Rebuild a module (topology + weights) saved by ``save_module``."""
    from bigdl_tpu.utils.module_serializer import from_spec
    with file_io.open_file(file_io.join(path, "module.json")) as f:
        meta = json.load(f)
    module = from_spec(meta["spec"])
    return load_module_weights(path, module)


def load_module_weights(path: str, module):
    """Load params/state saved by save_module into a compatible module."""
    module.set_parameters(load_tree(file_io.join(path, "params")))
    module.set_state(load_tree(file_io.join(path, "state")))
    return module
