"""Checkpoint & model persistence (BigDL utils/serializer + utils/File.scala).

Native format: a directory with ``spec.json`` (pytree structure + host state)
and ``arrays.npz`` (flattened leaves). Readable without the framework; stable
across processes. The reference's protobuf module format (ModuleSerializer)
maps to ``save_module``/``load_module`` which additionally record the module
class and constructor args for zoo models that register themselves.
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.utils import file_io

_CKPT_SAVE_S = telemetry.histogram(
    "train/checkpoint/save_s", "wall-clock seconds per checkpoint save")
_CKPT_LOAD_S = telemetry.histogram(
    "train/checkpoint/load_s", "wall-clock seconds per checkpoint load")


def _flatten_with_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        out[key] = np.asarray(leaf)
    return out, treedef


def _tree_to_template(tree):
    """JSON-able structure with leaf placeholders."""
    if isinstance(tree, dict):
        return {k: _tree_to_template(v) for k, v in sorted(tree.items())}
    from bigdl_tpu.utils.table import Table
    if isinstance(tree, Table):
        return {"__table__": {str(k): _tree_to_template(v)
                              for k, v in tree.items()}}
    return "__leaf__"


def _rebuild(template, arrays, prefix=""):
    from bigdl_tpu.utils.table import Table
    if template == "__leaf__":
        return arrays[prefix.rstrip("/")]
    if isinstance(template, dict) and "__table__" in template:
        t = Table()
        for k, v in template["__table__"].items():
            key = int(k) if k.lstrip("-").isdigit() else k
            t[key] = _rebuild(v, arrays, f"{prefix}{k}/")
        return t
    out = {}
    for k, v in template.items():
        out[k] = _rebuild(v, arrays, f"{prefix}{k}/")
    return out


def _host_leaf(a) -> np.ndarray:
    """Leaf -> host numpy, including multi-host global arrays that span
    non-addressable devices (e.g. ZeRO-1 shards): reshard to replicated
    on device (an all-gather over the mesh), then read — every host
    checkpoints the same full value (DistriOptimizer saves the
    assembled weights the same way, :433-463)."""
    try:
        return np.asarray(a)
    except Exception:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        repl = NamedSharding(a.sharding.mesh, PartitionSpec())
        return np.asarray(jax.jit(lambda x: x, out_shardings=repl)(a))


host_value = _host_leaf  # public alias: leaf -> host numpy, multi-host safe


def _flatten_leaves(tree, prefix=""):
    from bigdl_tpu.utils.table import Table
    out = {}
    if isinstance(tree, Table):
        for k, v in tree.items():
            out.update(_flatten_leaves(v, f"{prefix}{k}/"))
    elif isinstance(tree, dict):
        for k, v in sorted(tree.items()):
            out.update(_flatten_leaves(v, f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = _host_leaf(tree)
    return out


def save_tree(path_prefix: str, tree) -> None:
    """Save a pytree as <prefix>.json + <prefix>.npz (local or remote —
    utils/File.scala's HDFS/S3 role via file_io)."""
    arrays = _flatten_leaves(tree)
    template = _tree_to_template(tree)
    with file_io.open_file(path_prefix + ".json", "w") as f:
        json.dump(template, f)
    with file_io.open_file(path_prefix + ".npz", "wb") as f:
        np.savez(f, **arrays)


def load_tree(path_prefix: str):
    """Read a pytree saved by :func:`save_tree`."""
    with file_io.open_file(path_prefix + ".json") as f:
        template = json.load(f)
    with file_io.open_file(path_prefix + ".npz", "rb") as f:
        with np.load(f) as z:
            arrays = {k: z[k] for k in z.files}
    return _rebuild(template, arrays)


MANIFEST = "MANIFEST.json"
_CKPT_FILES = ("params", "opt_state", "model_state")


def _fsync(f) -> None:
    try:
        f.flush()
        os.fsync(f.fileno())
    except (OSError, AttributeError):
        pass  # remote file objects / fs without fsync


def _write_json(path: str, obj) -> None:
    with open(path, "w") as f:
        json.dump(obj, f)
        _fsync(f)


def _fsync_dir(d: str) -> None:
    try:
        fd = os.open(d, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass


def _write_ckpt_files(d: str, flats) -> None:
    """Write the three tree parts (pre-materialized host arrays) into
    ``d``, fsyncing each file."""
    for name, (arrays, template) in flats.items():
        _write_json(os.path.join(d, name + ".json"), template)
        with open(os.path.join(d, name + ".npz"), "wb") as f:
            np.savez(f, **arrays)
            _fsync(f)


_SCRIPTED_CRASH_ARMED = False


def arm_scripted_crash() -> None:
    """Explicit opt-in for the fault-injection hook below. A test
    harness must call this IN ADDITION to setting the env var — so a
    stray BIGDL_TEST_CRASH_IN_CHECKPOINT inherited from a test
    environment can never SIGKILL a real training run (ADVICE r5)."""
    global _SCRIPTED_CRASH_ARMED
    _SCRIPTED_CRASH_ARMED = True


def _maybe_scripted_crash(driver_state) -> None:
    """Test-only fault injection (the reference scripted worker deaths
    the same way, ExceptionTest / TestUtils.scala:103-131): SIGKILL this
    process MID-checkpoint-write — after the tree files, before the
    MANIFEST — when BIGDL_TEST_CRASH_IN_CHECKPOINT names this neval AND
    the process called :func:`arm_scripted_crash`."""
    if not _SCRIPTED_CRASH_ARMED:
        return
    at = os.environ.get("BIGDL_TEST_CRASH_IN_CHECKPOINT")
    if at and int(at) == driver_state.get("neval", -1):
        import signal
        os.kill(os.getpid(), signal.SIGKILL)


def save_checkpoint(path: str, *, params, opt_state, model_state,
                    optim_host_state: Dict[str, Any],
                    driver_state: Dict[str, Any],
                    writer: bool = True) -> None:
    """Checkpoint a training run crash-safely (see
    :func:`_save_checkpoint_impl` for the atomicity contract); the
    wall-clock cost lands in the ``train/checkpoint/save_s`` telemetry
    histogram and a ``checkpoint/save`` span."""
    t0 = time.perf_counter()
    try:
        with telemetry.span("checkpoint/save", path=path):
            _save_checkpoint_impl(
                path, params=params, opt_state=opt_state,
                model_state=model_state,
                optim_host_state=optim_host_state,
                driver_state=driver_state, writer=writer)
    finally:
        _CKPT_SAVE_S.observe(time.perf_counter() - t0)


def _save_checkpoint_impl(path: str, *, params, opt_state, model_state,
                          optim_host_state: Dict[str, Any],
                          driver_state: Dict[str, Any],
                          writer: bool = True) -> None:
    """Checkpoint a training run (DistriOptimizer.checkpoint :433-463),
    crash-safely:

    - everything is staged in ``<path>.tmp-*``, fsynced, and the
      directory atomically renamed into place — a process killed at ANY
      point leaves either the previous complete checkpoint or a stray
      tmp/old dir that ``find_latest_checkpoint`` never selects, never
      a torn ``<path>``;
    - a ``MANIFEST.json`` is written LAST (after a dir fsync), so even
      on remote filesystems without atomic rename its presence certifies
      completeness;
    - in multi-host runs pass ``writer=jax.process_index() == 0``: every
      process participates in the all-gather that materializes sharded
      leaves (``_host_leaf`` resharding is collective), but only the
      single writer touches storage — the reference wrote once from the
      driver, not N× from executors (DistriOptimizer.scala:433-463).
    """
    # host materialization runs on EVERY process (collective resharding
    # of ZeRO-1/TP-sharded leaves) and in deterministic order
    parts = {"params": params, "opt_state": opt_state,
             "model_state": model_state}
    flats = {k: (_flatten_leaves(t), _tree_to_template(t))
             for k, t in parts.items()}
    if not writer:
        return
    host = {"optim_host_state": optim_host_state,
            "driver_state": driver_state}
    manifest = {"format": 1,
                "neval": driver_state.get("neval"),
                "files": [f"{n}.{ext}" for n in _CKPT_FILES
                          for ext in ("json", "npz")] +
                         ["host_state.json"]}
    if file_io.is_remote(path):
        # no atomic rename on object stores: MANIFEST-last ordering is
        # the completeness certificate
        file_io.makedirs(path)
        for name, (arrays, template) in flats.items():
            with file_io.open_file(
                    file_io.join(path, name + ".json"), "w") as f:
                json.dump(template, f)
            with file_io.open_file(
                    file_io.join(path, name + ".npz"), "wb") as f:
                np.savez(f, **arrays)
        with file_io.open_file(
                file_io.join(path, "host_state.json"), "w") as f:
            json.dump(host, f)
        _maybe_scripted_crash(driver_state)
        with file_io.open_file(file_io.join(path, MANIFEST), "w") as f:
            json.dump(manifest, f)
        return

    import shutil
    path = os.path.abspath(path)
    parent = os.path.dirname(path)
    base = os.path.basename(path)
    os.makedirs(parent, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    if os.path.exists(tmp):  # our own earlier failed attempt
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    _write_ckpt_files(tmp, flats)
    _write_json(os.path.join(tmp, "host_state.json"), host)
    _maybe_scripted_crash(driver_state)
    _write_json(os.path.join(tmp, MANIFEST), manifest)
    _fsync_dir(tmp)
    # commit: the destination only ever transitions complete->complete
    # (a stray complete tmp/old dir is still found by
    # find_latest_checkpoint via its MANIFEST, so no crash point leaves
    # the latest state unreachable)
    old = f"{path}.old-{os.getpid()}"
    if os.path.exists(path):
        os.rename(path, old)
    os.rename(tmp, path)
    _fsync_dir(parent)
    # only AFTER the new checkpoint is committed: drop superseded debris
    for name in os.listdir(parent):
        if name.startswith(base + ".tmp-") or name.startswith(
                base + ".old-"):
            shutil.rmtree(os.path.join(parent, name), ignore_errors=True)


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Read one complete checkpoint dir written by
    :func:`save_checkpoint`; the wall-clock cost lands in the
    ``train/checkpoint/load_s`` telemetry histogram and a
    ``checkpoint/load`` span."""
    t0 = time.perf_counter()
    try:
        with telemetry.span("checkpoint/load", path=path):
            with file_io.open_file(
                    file_io.join(path, "host_state.json")) as f:
                host = json.load(f)
            return {
                "params": load_tree(file_io.join(path, "params")),
                "opt_state": load_tree(file_io.join(path, "opt_state")),
                "model_state": load_tree(
                    file_io.join(path, "model_state")),
                "optim_host_state": host["optim_host_state"],
                "driver_state": host["driver_state"],
            }
    finally:
        _CKPT_LOAD_S.observe(time.perf_counter() - t0)


def find_latest_checkpoint(directory: str) -> Optional[str]:
    """Latest COMPLETE checkpoint dir
    (DistriOptimizer.getLatestFile :867-880). Completeness is certified
    by the MANIFEST written last by ``save_checkpoint`` — a torn dir
    from a mid-write crash is never selected, so a resume after a
    checkpoint-time death lands on the previous intact checkpoint.
    Recency comes from the MANIFEST's recorded neval, and stray-but-
    complete ``*.tmp-*``/``*.old-*`` dirs (a crash between the MANIFEST
    write and the final rename) still count — no crash point makes the
    newest complete state unreachable."""
    if not file_io.isdir(directory):
        return None
    best, best_key = None, None
    for name in file_io.listdir(directory):
        full = file_io.join(directory, name)
        if not name.startswith("checkpoint") or not file_io.isdir(full):
            continue
        if not file_io.exists(file_io.join(full, "host_state.json")):
            continue
        proper = re.match(r"checkpoint(\.\d+)?$", name) is not None
        has_manifest = file_io.exists(file_io.join(full, MANIFEST))
        if has_manifest:
            try:
                with file_io.open_file(file_io.join(full, MANIFEST)) as f:
                    neval = json.load(f).get("neval") or 0
            except (OSError, ValueError):
                continue
        elif proper:
            # format-0 back-compat: checkpoints written before the
            # MANIFEST existed carry no completeness certificate —
            # accept properly-named ones (the pre-change behavior;
            # strays without a manifest stay torn-write debris) with
            # neval from the dir suffix
            m = re.match(r"checkpoint\.(\d+)$", name)
            neval = int(m.group(1)) if m else 0
        else:
            continue
        # a properly-named dir wins over a same-neval stray
        key = (neval, proper)
        if best_key is None or key > best_key:
            best, best_key = full, key
    return best


# -- module-level save/load (ModuleSerializer analogue) ---------------------

def save_module(path: str, module) -> None:
    """Persist a module: topology spec + params + state.

    The saved directory is self-contained — ``load_module`` reconstructs the
    module tree (class, constructor args, children, graph wiring) and its
    weights without any user code, like the reference's
    ``Module.loadModule`` (utils/serializer/ModuleLoader.scala).
    """
    from bigdl_tpu.utils.module_serializer import to_spec
    file_io.makedirs(path)
    module.ensure_initialized()
    save_tree(file_io.join(path, "params"), module.get_parameters())
    save_tree(file_io.join(path, "state"), module.get_state())
    meta = {"class": type(module).__name__, "name": module.get_name(),
            "spec": to_spec(module), "format_version": 1}
    with file_io.open_file(file_io.join(path, "module.json"), "w") as f:
        json.dump(meta, f)


def load_module(path: str):
    """Rebuild a module (topology + weights) saved by ``save_module``."""
    from bigdl_tpu.utils.module_serializer import from_spec
    with file_io.open_file(file_io.join(path, "module.json")) as f:
        meta = json.load(f)
    module = from_spec(meta["spec"])
    return load_module_weights(path, module)


def load_module_weights(path: str, module):
    """Load params/state saved by save_module into a compatible module."""
    module.set_parameters(load_tree(file_io.join(path, "params")))
    module.set_state(load_tree(file_io.join(path, "state")))
    return module
