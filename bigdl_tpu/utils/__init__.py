from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.random import RandomGenerator
from bigdl_tpu.utils.directed_graph import Node, DirectedGraph
from bigdl_tpu.utils.engine import Engine

__all__ = ["Table", "T", "RandomGenerator", "Node", "DirectedGraph", "Engine"]
