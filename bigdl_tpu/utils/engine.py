"""Engine — runtime/topology configuration (BigDL utils/Engine.scala:36).

BigDL's ``Engine`` discovers node/core counts from the Spark conf and owns two
thread pools. On TPU those roles collapse into: device discovery via
``jax.devices()``, a ``jax.sharding.Mesh`` describing the pod slice, and dtype
policy. XLA owns all threading; there is no ThreadPool equivalent
(utils/ThreadPool.scala is intentionally absent — stragglers don't exist on a
synchronous TPU pod, so ``invokeAndWait2``'s timeout machinery is moot).
"""
from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class Engine:
    """Process-global runtime config: devices, mesh, dtype policy.

    ``Engine.init()`` must run before training, like the reference's
    ``Engine.init`` (Engine.scala:93) — but here it only snapshots device
    topology and builds the default data-parallel mesh.
    """

    _initialized = False
    _distributed_started = False
    _mesh: Optional[jax.sharding.Mesh] = None
    _node_number = 1
    _core_number = 1
    _default_dtype = jnp.float32
    _compute_dtype = jnp.float32

    # -- lifecycle ---------------------------------------------------------
    @classmethod
    def init(cls, node_number: Optional[int] = None,
             core_number: Optional[int] = None,
             mesh_axes: Sequence[str] = ("data",),
             mesh_shape: Optional[Sequence[int]] = None) -> "Engine":
        """Discover devices and build the default mesh.

        node_number/core_number are accepted for reference API parity
        (Engine.scala:93 signature) but topology truly comes from
        ``jax.devices()``: nodes = process count, cores = local device count.
        """
        devices = jax.devices()
        cls._node_number = jax.process_count()
        cls._core_number = max(1, len(devices) // max(1, jax.process_count()))
        if mesh_shape is None:
            mesh_shape = [len(devices)] + [1] * (len(mesh_axes) - 1)
        mesh_devices = np.array(devices).reshape(tuple(mesh_shape))
        cls._mesh = jax.sharding.Mesh(mesh_devices, tuple(mesh_axes))
        cls._initialized = True
        return cls

    @classmethod
    def init_distributed(cls, coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None,
                         initialization_timeout: Optional[int] = None,
                         **init_kwargs) -> "Engine":
        """Multi-host bring-up: ``jax.distributed.initialize`` then
        ``init()`` — the role the reference's Engine.init played on Spark
        (executor discovery, Engine.scala:100-103). Parameters default to
        the standard JAX env vars (JAX_COORDINATOR_ADDRESS etc.), so a
        pod launcher only needs to set the environment.
        """
        if not cls._distributed_started:
            # honor the documented env contract ourselves —
            # jax.distributed.initialize only auto-detects managed
            # clusters (Slurm etc.), not raw JAX_* variables (which is
            # what tools/launch provides, the spark-submit role)
            if coordinator_address is None:
                coordinator_address = os.environ.get(
                    "JAX_COORDINATOR_ADDRESS")
            if num_processes is None and "JAX_NUM_PROCESSES" in os.environ:
                num_processes = int(os.environ["JAX_NUM_PROCESSES"])
            if process_id is None and "JAX_PROCESS_ID" in os.environ:
                process_id = int(os.environ["JAX_PROCESS_ID"])
            # jax.distributed.initialize is once-per-process and cannot
            # be undone by Engine.reset()
            kw = {}
            if initialization_timeout is not None:
                kw["initialization_timeout"] = initialization_timeout
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id, **kw)
            cls._distributed_started = True
        return cls.init(**init_kwargs)

    @classmethod
    def is_initialized(cls) -> bool:
        return cls._initialized

    @classmethod
    def reset(cls):
        cls._initialized = False
        cls._mesh = None

    # -- topology ----------------------------------------------------------
    @classmethod
    def mesh(cls) -> jax.sharding.Mesh:
        if not cls._initialized:
            cls.init()
        return cls._mesh

    @classmethod
    def set_mesh(cls, mesh: jax.sharding.Mesh):
        cls._mesh = mesh
        cls._initialized = True
        return cls

    @classmethod
    def node_number(cls) -> int:
        """Host count (Engine.nodeNumber, Engine.scala:147)."""
        return cls._node_number

    @classmethod
    def core_number(cls) -> int:
        """Per-host device count (Engine.coreNumber, Engine.scala:152)."""
        return cls._core_number

    @classmethod
    def device_count(cls) -> int:
        return len(jax.devices())

    # -- dtype policy ------------------------------------------------------
    @classmethod
    def set_default_dtype(cls, dtype):
        """Parameter dtype (BigDL's Float/Double TensorNumeric choice)."""
        cls._default_dtype = jnp.dtype(dtype)
        return cls

    @classmethod
    def default_dtype(cls):
        return cls._default_dtype

    @classmethod
    def set_compute_dtype(cls, dtype):
        """Activation/matmul dtype; bf16 is the TPU analogue of the
        reference's fp16 gradient compression (FP16CompressedTensor.scala)."""
        cls._compute_dtype = jnp.dtype(dtype)
        return cls

    @classmethod
    def compute_dtype(cls):
        return cls._compute_dtype
