"""Deterministic RNG for parameter init (BigDL utils/RandomGenerator.scala:56).

BigDL uses a per-JVM Mersenne-Twister singleton seeded by the user; layer
``reset()`` draws from it. Here the same role is played by a process-global
seed that derives ``jax.random`` keys: functional code paths take explicit
keys, while the stateful convenience API (``module.forward`` with lazy init)
draws from this generator.
"""
from __future__ import annotations

import threading

import jax
import numpy as np


class RandomGenerator:
    """Process-global seed registry + numpy MT19937 for host-side sampling."""

    _lock = threading.Lock()
    _seed = 1
    _numpy = np.random.RandomState(1)
    _counter = 0

    @classmethod
    def set_seed(cls, seed: int):
        with cls._lock:
            cls._seed = int(seed)
            cls._numpy = np.random.RandomState(cls._seed & 0x7FFFFFFF)
            cls._counter = 0
        return cls

    @classmethod
    def get_seed(cls) -> int:
        return cls._seed

    @classmethod
    def numpy(cls) -> np.random.RandomState:
        """Host-side RNG (shuffles, data augmentation)."""
        return cls._numpy

    @classmethod
    def next_key(cls) -> jax.Array:
        """A fresh jax PRNG key; successive calls never repeat."""
        with cls._lock:
            cls._counter += 1
            n = cls._counter
        return jax.random.fold_in(jax.random.PRNGKey(cls._seed), n)
