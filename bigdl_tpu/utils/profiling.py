"""Profiling utilities (reference: per-module wall-clock accumulation in
AbstractModule.forward/backward — getTimes :205, resetTimes :209 — and the
driver-side Metrics dump, SURVEY.md §5).

Under XLA the per-layer forward isn't observable at runtime (the whole
step is one fused program), so the timing surface splits in two:

- :func:`module_times` — the getTimes analogue: times each child of a
  Sequential/Graph with an EAGER forward, layer by layer, for quick
  "where is this model slow" answers. Numbers are eager-mode costs, not
  fused-step costs.
- :func:`trace` — the real thing for compiled steps: a context manager
  around ``jax.profiler`` writing a TensorBoard-loadable trace of the
  actual fused XLA execution.
"""
from __future__ import annotations

import contextlib
import time
from typing import List, Tuple


def module_times(model, x, *, repeats: int = 3) -> List[Tuple[str, float]]:
    """Eager per-child forward times, best-of-``repeats`` seconds.

    Walks one level of a Sequential (or Graph exec order), feeding each
    child the previous child's output — the reference's getTimes view.
    """
    import jax

    import bigdl_tpu.nn as nn

    def best_time(fn):
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            out = fn()
            jax.block_until_ready(out)
            best = min(best, time.perf_counter() - t0)
        return best, out

    results: List[Tuple[str, float]] = []
    if isinstance(model, nn.Sequential):
        children = [(m.get_name() or f"{type(m).__name__}#{i}", m)
                    for i, m in enumerate(model.modules)]
        cur = x
        for name, m in children:
            m.ensure_initialized()
            dt, cur = best_time(lambda m=m, cur=cur: m.forward(cur))
            results.append((name, dt))
    elif isinstance(model, nn.Graph):
        # whole-graph time only: per-node inputs are graph-internal
        model.ensure_initialized()
        dt, _ = best_time(lambda: model.forward(x))
        results.append((model.get_name() or "Graph", dt))
    else:
        model.ensure_initialized()
        dt, _ = best_time(lambda: model.forward(x))
        results.append((model.get_name() or type(model).__name__, dt))
    return results


@contextlib.contextmanager
def trace(log_dir: str):
    """Profile the enclosed (compiled) computation with jax.profiler;
    the trace loads in TensorBoard/Perfetto. This is the fused-step
    truth the eager getTimes view cannot give."""
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
