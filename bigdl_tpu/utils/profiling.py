"""Profiling utilities (reference: per-module wall-clock accumulation in
AbstractModule.forward/backward — getTimes :205, resetTimes :209 — and the
driver-side Metrics dump, SURVEY.md §5).

Under XLA the per-layer forward isn't observable at runtime (the whole
step is one fused program), so the timing surface splits in two:

- :func:`module_times` — the getTimes analogue: times each child of a
  Sequential/Graph with an EAGER forward, layer by layer, for quick
  "where is this model slow" answers. Numbers are eager-mode costs, not
  fused-step costs.
- :func:`trace` — the real thing for compiled steps: a context manager
  around ``jax.profiler`` writing a TensorBoard-loadable trace of the
  actual fused XLA execution.
"""
from __future__ import annotations

import contextlib
import time
from typing import List, Tuple


def module_times(model, x, *, repeats: int = 3) -> List[Tuple[str, float]]:
    """Eager per-child forward times, best-of-``repeats`` seconds.

    Walks one level of a Sequential (or Graph exec order), feeding each
    child the previous child's output — the reference's getTimes view.
    """
    import jax

    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.random import RandomGenerator

    if repeats < 1:
        raise ValueError("repeats must be >= 1")

    def best_time(m, feed):
        # read-only contract: the repeats must not advance BatchNorm
        # running stats or drain the global RNG stream
        saved_state = m._state
        saved_counter = RandomGenerator._counter
        best = float("inf")
        out = None
        try:
            for _ in range(repeats):
                t0 = time.perf_counter()
                out = m.forward(feed)
                # a timing harness MUST sync per repeat — the
                # measurement is the point
                jax.block_until_ready(out)  # bigdl: disable=sync-in-loop
                best = min(best, time.perf_counter() - t0)
        finally:
            m._state = saved_state
            RandomGenerator._counter = saved_counter
        return best, out

    results: List[Tuple[str, float]] = []
    if isinstance(model, nn.Sequential):
        cur = x
        for m in model.modules:
            m.ensure_initialized()
            dt, cur = best_time(m, cur)
            results.append((m.get_name(), dt))
    else:
        # Graph/leaf: whole-model time (per-node inputs are internal)
        model.ensure_initialized()
        dt, _ = best_time(model, x)
        results.append((model.get_name(), dt))
    return results


def percentile_summary(samples, qs=(50, 90, 99)):
    """Latency-style percentile digest: ``{"p50": ..., "p99": ...}``.

    The one percentile implementation shared by the serving metrics
    (`bigdl_tpu.serving`) and ad-hoc perf tooling; empty input returns
    ``{}`` so callers can export whatever exists without guards.
    """
    import numpy as np

    samples = np.asarray(list(samples), np.float64)
    if samples.size == 0:
        return {}
    return {f"p{int(q)}": float(np.percentile(samples, q)) for q in qs}


@contextlib.contextmanager
def trace(log_dir: str):
    """Profile the enclosed (compiled) computation with jax.profiler;
    the trace loads in TensorBoard/Perfetto. This is the fused-step
    truth the eager getTimes view cannot give."""
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
