"""Caffe model import (reference: utils/caffe/CaffeLoader.scala:56 with
Converter/LayerConverter/V1LayerConverter — reads .prototxt (text) +
.caffemodel (binary protobuf), builds the layer graph, copies weights).

No protobuf codegen: the binary side decodes through the in-repo wire codec
(utils/proto.py) with the public caffe.proto field numbers; the text side
uses a small recursive prototxt parser. Supports both V2 ``layer`` and V1
``layers`` nets (the Inception-v1 zoo path, SURVEY.md §2.4 config 4).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.utils import proto

# ---------------------------------------------------------------- prototxt

_TOKEN = re.compile(r"""
    \s*(?:
      (?P<comment>\#[^\n]*) |
      (?P<brace>[{}]) |
      (?P<colon>:) |
      (?P<string>"(?:[^"\\]|\\.)*") |
      (?P<ident>[A-Za-z0-9_.+\-eE]+)
    )""", re.VERBOSE)


def _tokenize(text: str):
    pos = 0
    while pos < len(text):
        while pos < len(text) and text[pos].isspace():
            pos += 1
        if pos >= len(text):
            break
        m = _TOKEN.match(text, pos)
        if not m:
            raise ValueError(f"prototxt parse error at {text[pos:pos+40]!r}")
        pos = m.end()
        if m.lastgroup == "comment":
            continue
        yield m.lastgroup, m.group(m.lastgroup)
    yield "eof", ""


def parse_prototxt(text: str) -> Dict[str, List[Any]]:
    """Parse protobuf text format into {field: [values...]} (repeated-safe).
    Nested messages are dicts; scalars are str/float/int/bool."""
    tokens = list(_tokenize(text))
    idx = 0

    def parse_value(v: str):
        if v.startswith('"'):
            return v[1:-1]
        if v in ("true", "false"):
            return v == "true"
        try:
            return int(v)
        except ValueError:
            pass
        try:
            return float(v)
        except ValueError:
            return v  # enum identifier

    def parse_msg(stop_at_brace: bool):
        nonlocal idx
        out: Dict[str, List[Any]] = {}
        while True:
            kind, val = tokens[idx]
            if kind == "eof":
                if stop_at_brace:
                    raise ValueError("unexpected EOF in prototxt message")
                return out
            if kind == "brace" and val == "}":
                idx += 1
                return out
            if kind != "ident":
                raise ValueError(f"expected field name, got {val!r}")
            key = val
            idx += 1
            kind, val = tokens[idx]
            if kind == "colon":
                idx += 1
                kind, val = tokens[idx]
                idx += 1
                out.setdefault(key, []).append(parse_value(val))
            elif kind == "brace" and val == "{":
                idx += 1
                out.setdefault(key, []).append(parse_msg(True))
            else:
                raise ValueError(f"expected ':' or '{{' after {key}")

    return parse_msg(False)


# --------------------------------------------------- caffemodel (binary)

def _msgs(fields, n):
    return fields.get(n, [])


def _scalar(fields, n, default=None, conv=lambda x: x):
    vals = fields.get(n, [])
    return conv(vals[0]) if vals else default


def _floatval(raw):
    return proto.as_float(raw) if isinstance(raw, bytes) else float(raw)


def parse_blob(buf: bytes) -> np.ndarray:
    """BlobProto: shape=7(BlobShape dim=1), data=5 packed float,
    double_data=8; legacy dims num=1,channels=2,height=3,width=4."""
    f = proto.parse_message(buf)
    if 5 in f:
        data = np.concatenate([
            np.frombuffer(raw, dtype="<f4") if isinstance(raw, bytes)
            else np.array([proto.as_float(raw)], "<f4") for raw in f[5]])
        data = data.astype(np.float32)
    elif 8 in f:
        data = np.concatenate([np.frombuffer(raw, dtype="<f8")
                               for raw in f[8]]).astype(np.float32)
    else:
        data = np.zeros((0,), np.float32)
    shape = None
    if 7 in f:
        sh = proto.parse_message(f[7][0])
        dims = []
        for raw in sh.get(1, []):
            if isinstance(raw, bytes):
                dims.extend(proto.unpack_packed_varints(raw))
            else:
                dims.append(raw)
        shape = [proto.as_sint(d) for d in dims]
    else:
        legacy = [_scalar(f, i) for i in (1, 2, 3, 4)]
        if any(v is not None for v in legacy):
            shape = [v if v is not None else 1 for v in legacy]
            # strip leading 1s of legacy 4-d layout
            while len(shape) > 1 and shape[0] == 1:
                shape = shape[1:]
    if shape:
        data = data.reshape(shape)
    return data


# V1 layer type enum -> canonical V2-style type string (caffe.proto)
_V1_TYPES = {
    3: "Concat", 4: "Convolution", 5: "Data", 6: "Dropout", 8: "Flatten",
    14: "InnerProduct", 15: "LRN", 17: "Pooling", 18: "ReLU", 19: "Sigmoid",
    20: "Softmax", 21: "SoftmaxWithLoss", 22: "Split", 23: "TanH",
    25: "Eltwise", 26: "Power", 30: "ArgMax", 33: "Slice", 35: "AbsVal",
    39: "Deconvolution", 1: "Accuracy",
}


class CaffeLayer:
    """Normalized layer record from either text or binary, V1 or V2."""

    def __init__(self, name, type_, bottoms, tops, params, blobs):
        self.name = name
        self.type = type_
        self.bottoms = bottoms
        self.tops = tops
        self.params = params  # dict: param-group name -> dict
        self.blobs = blobs    # list of np arrays

    def __repr__(self):
        return f"CaffeLayer({self.name}:{self.type})"


# V2 LayerParameter param-group field numbers
_V2_PARAM_FIELDS = {
    104: "concat_param", 106: "convolution_param", 108: "dropout_param",
    110: "eltwise_param", 117: "inner_product_param", 118: "lrn_param",
    121: "pooling_param", 122: "power_param", 123: "relu_param",
    125: "softmax_param", 133: "reshape_param", 135: "flatten_param",
    139: "batch_norm_param", 142: "scale_param", 143: "input_param",
}
# V1 equivalents
_V1_PARAM_FIELDS = {
    9: "concat_param", 10: "convolution_param", 12: "dropout_param",
    24: "eltwise_param", 17: "inner_product_param", 18: "lrn_param",
    19: "pooling_param", 21: "power_param", 39: "softmax_param",
}

# param-group sub-message field numbers → named dicts
_PARAM_SCHEMAS = {
    "convolution_param": {1: "num_output", 2: "bias_term", 3: "pad",
                          4: "kernel_size", 5: "group", 6: "stride",
                          9: "pad_h", 10: "pad_w", 11: "kernel_h",
                          12: "kernel_w", 13: "stride_h", 14: "stride_w",
                          18: "dilation"},
    "pooling_param": {1: "pool", 2: "kernel_size", 3: "stride", 4: "pad",
                      5: "kernel_h", 6: "kernel_w", 7: "stride_h",
                      8: "stride_w", 9: "pad_h", 10: "pad_w",
                      12: "global_pooling"},
    "inner_product_param": {1: "num_output", 2: "bias_term", 5: "axis"},
    "lrn_param": {1: "local_size", 2: "alpha", 3: "beta", 4: "norm_region",
                  5: "k"},
    "batch_norm_param": {1: "use_global_stats",
                         2: "moving_average_fraction", 3: "eps"},
    "scale_param": {1: "axis", 2: "num_axes", 4: "bias_term"},
    "concat_param": {1: "concat_dim", 2: "axis"},
    "dropout_param": {1: "dropout_ratio"},
    "eltwise_param": {1: "operation", 2: "coeff"},
    "softmax_param": {2: "axis"},
    "power_param": {1: "power", 2: "scale", 3: "shift"},
    "input_param": {1: "shape"},
    "reshape_param": {1: "shape"},
    "flatten_param": {1: "axis"},
}
_FLOAT_FIELDS = {"alpha", "beta", "k", "eps", "moving_average_fraction",
                 "dropout_ratio", "coeff", "power", "scale", "shift"}


def _decode_param_group(name: str, buf: bytes) -> Dict[str, Any]:
    schema = _PARAM_SCHEMAS.get(name, {})
    out: Dict[str, Any] = {}
    for field, wire, raw in proto.iter_fields(buf):
        key = schema.get(field)
        if key is None:
            continue
        if key == "shape":
            sh = proto.parse_message(raw)
            dims = []
            for r in sh.get(1, []):
                if isinstance(r, bytes):
                    dims.extend(proto.unpack_packed_varints(r))
                else:
                    dims.append(r)
            out.setdefault("shape", []).append(
                [proto.as_sint(d) for d in dims])
            continue
        if key in _FLOAT_FIELDS:
            val = _floatval(raw) if wire == 5 else (
                proto.as_double(raw) if isinstance(raw, bytes) else raw)
        elif isinstance(raw, bytes) and wire == 5:
            val = proto.as_float(raw)
        else:
            val = raw
        out.setdefault(key, []).append(val)
    return {k: (v if len(v) > 1 else v[0]) for k, v in out.items()}


def _decode_layer_v2(buf: bytes) -> CaffeLayer:
    f = proto.parse_message(buf)
    name = proto.as_string(f.get(1, [b""])[0])
    type_ = proto.as_string(f.get(2, [b""])[0])
    bottoms = [proto.as_string(b) for b in f.get(3, [])]
    tops = [proto.as_string(t) for t in f.get(4, [])]
    blobs = [parse_blob(b) for b in f.get(7, [])]
    params = {pname: _decode_param_group(pname, f[num][0])
              for num, pname in _V2_PARAM_FIELDS.items() if num in f}
    return CaffeLayer(name, type_, bottoms, tops, params, blobs)


def _decode_layer_v1(buf: bytes) -> CaffeLayer:
    f = proto.parse_message(buf)
    bottoms = [proto.as_string(b) for b in f.get(2, [])]
    tops = [proto.as_string(t) for t in f.get(3, [])]
    name = proto.as_string(f.get(4, [b""])[0])
    type_num = f.get(5, [0])[0]
    type_ = _V1_TYPES.get(type_num, f"V1Type{type_num}")
    blobs = [parse_blob(b) for b in f.get(6, [])]
    params = {pname: _decode_param_group(pname, f[num][0])
              for num, pname in _V1_PARAM_FIELDS.items() if num in f}
    return CaffeLayer(name, type_, bottoms, tops, params, blobs)


def parse_caffemodel(data: bytes) -> Tuple[str, List[CaffeLayer], Dict]:
    """NetParameter: name=1, layers(V1)=2, input=3, input_dim=4,
    input_shape=8, layer(V2)=100."""
    f = proto.parse_message(data)
    name = proto.as_string(f.get(1, [b""])[0])
    layers = [_decode_layer_v2(b) for b in f.get(100, [])]
    layers += [_decode_layer_v1(b) for b in f.get(2, [])]
    net_inputs = {"input": [proto.as_string(b) for b in f.get(3, [])],
                  "input_dim": [proto.as_sint(v) for v in f.get(4, [])]}
    return name, layers, net_inputs


def _layers_from_prototxt(net: Dict[str, List]) -> List[CaffeLayer]:
    out = []
    for key in ("layer", "layers"):
        for msg in net.get(key, []):
            name = msg.get("name", [""])[0]
            type_ = msg.get("type", [""])[0]
            if isinstance(type_, int):
                type_ = _V1_TYPES.get(type_, str(type_))
            type_ = str(type_)
            bottoms = [str(b) for b in msg.get("bottom", [])]
            tops = [str(t) for t in msg.get("top", [])]
            params = {k: v[0] for k, v in msg.items()
                      if k.endswith("_param") and isinstance(v[0], dict)}
            # prototxt param groups: unwrap single-element lists
            params = {k: {kk: (vv if len(vv) > 1 else vv[0])
                          for kk, vv in v.items()}
                      for k, v in params.items()}
            out.append(CaffeLayer(name, type_, bottoms, tops, params, []))
    return out


# ----------------------------------------------------------- model build

_SKIP_TYPES = {"Data", "Accuracy", "Silence", "HDF5Data", "ImageData",
               "DummyData", "MemoryData", "WindowData", "Python"}


def _make_global_pooling():
    """Defined lazily so utils.caffe imports without jax side effects."""
    from bigdl_tpu.nn.module import Module
    import jax.numpy as jnp

    class GlobalPooling(Module):
        """Caffe global_pooling: reduce all spatial dims, keepdims (NCHW)."""

        def __init__(self, mode: str = "ave"):
            super().__init__()
            self.mode = mode

        def forward_fn(self, params, input, *, training=False, rng=None):
            axes = tuple(range(2, input.ndim))
            if self.mode == "ave":
                return jnp.mean(input, axis=axes, keepdims=True)
            return jnp.max(input, axis=axes, keepdims=True)

    return GlobalPooling


GlobalPooling = None


def _global_pooling(mode: str):
    global GlobalPooling
    if GlobalPooling is None:
        GlobalPooling = _make_global_pooling()
        from bigdl_tpu.utils.module_serializer import register_module_class
        register_module_class(GlobalPooling)
    return GlobalPooling(mode)


def _conv_geometry(p):
    def pick(generic, h_key, w_key, default):
        h = p.get(h_key)
        w = p.get(w_key)
        g = p.get(generic, default)
        if isinstance(g, list):
            # repeated field = per-spatial-dim (h, w) — Inception-v3 style
            # 1x7 convs use 'kernel_size: 1 kernel_size: 7'
            gh, gw = (g[0], g[1]) if len(g) >= 2 else (g[0], g[0])
        else:
            gh = gw = g
        return (h if h is not None else gh, w if w is not None else gw)
    kh, kw = pick("kernel_size", "kernel_h", "kernel_w", 1)
    sh, sw = pick("stride", "stride_h", "stride_w", 1)
    ph, pw = pick("pad", "pad_h", "pad_w", 0)
    return (int(kh), int(kw), int(sh), int(sw), int(ph), int(pw))


class CaffeLoader:
    """Load prototxt+caffemodel into a bigdl_tpu Graph
    (CaffeLoader.scala:56). Either path may be None:
    - def_path only  -> random-weight model from the text net
    - model_path only -> topology+weights from the binary net
    """

    def __init__(self, def_path: Optional[str] = None,
                 model_path: Optional[str] = None):
        self.def_path = def_path
        self.model_path = model_path

    def load(self):
        layers: List[CaffeLayer] = []
        weight_layers: Dict[str, CaffeLayer] = {}
        if self.model_path:
            with open(self.model_path, "rb") as f:
                _, bin_layers, _ = parse_caffemodel(f.read())
            weight_layers = {l.name: l for l in bin_layers}
            layers = bin_layers
        if self.def_path:
            with open(self.def_path) as f:
                net = parse_prototxt(f.read())
            layers = _layers_from_prototxt(net)
        if not layers:
            raise ValueError("no layers found")
        return self._build(layers, weight_layers)

    # -- shape inference (for weight-less prototxt loading) -----------------
    @staticmethod
    def _infer_shape(layer: CaffeLayer, in_shapes: List):
        """Output shape per top, given bottom shapes (None = unknown)."""
        t = layer.type
        p = layer.params
        s = in_shapes[0] if in_shapes else None
        import math as _math
        if t == "Input":
            sh = p.get("input_param", {}).get("shape")
            if isinstance(sh, dict):
                dims = sh.get("dim", [])
                return [list(dims) if isinstance(dims, list) else [dims]]
            if isinstance(sh, list):
                return [list(sh[0]) if sh else None]
            return [None]
        if s is None:
            return [None for _ in layer.tops]
        if t == "Convolution":
            cp = p.get("convolution_param", {})
            kh, kw, sh_, sw, ph, pw = _conv_geometry(cp)
            n_out = int(cp.get("num_output", 1))
            oh = (s[2] + 2 * ph - kh) // sh_ + 1
            ow = (s[3] + 2 * pw - kw) // sw + 1
            return [[s[0], n_out, oh, ow]]
        if t == "Deconvolution":
            cp = p.get("convolution_param", {})
            kh, kw, sh_, sw, ph, pw = _conv_geometry(cp)
            n_out = int(cp.get("num_output", 1))
            oh = (s[2] - 1) * sh_ - 2 * ph + kh
            ow = (s[3] - 1) * sw - 2 * pw + kw
            return [[s[0], n_out, oh, ow]]
        if t == "Pooling":
            pp = p.get("pooling_param", {})
            if pp.get("global_pooling"):
                return [[s[0], s[1], 1, 1]]
            kh, kw, sh_, sw, ph, pw = _conv_geometry(pp)
            oh = _math.ceil((s[2] + 2 * ph - kh) / sh_) + 1
            ow = _math.ceil((s[3] + 2 * pw - kw) / sw) + 1
            if ph > 0 and (oh - 1) * sh_ >= s[2] + ph:
                oh -= 1
            if pw > 0 and (ow - 1) * sw >= s[3] + pw:
                ow -= 1
            return [[s[0], s[1], oh, ow]]
        if t == "InnerProduct":
            n_out = int(p.get("inner_product_param", {}).get("num_output", 1))
            return [[s[0], n_out]]
        if t == "Concat":
            cp = p.get("concat_param", {})
            axis = int(cp.get("axis", cp.get("concat_dim", 1)))
            out = list(s)
            out[axis] = sum(sh[axis] for sh in in_shapes)
            return [out]
        if t == "Flatten":
            return [[s[0], int(np.prod(s[1:]))]]
        # shape-preserving (activations, LRN, BN, Scale, Dropout, Eltwise,
        # Split, Softmax)
        return [list(s) for _ in (layer.tops or [1])]

    # -- layer conversion ---------------------------------------------------
    def _convert(self, layer: CaffeLayer, blobs: List[np.ndarray],
                 in_shapes: Optional[List] = None):
        import bigdl_tpu.nn as nn
        t = layer.type
        p = layer.params

        def set_wb(m, weight, bias=None):
            m.ensure_initialized()
            pp = dict(m.get_parameters())
            pp["weight"] = np.asarray(weight, np.float32)
            if bias is not None and "bias" in pp:
                pp["bias"] = np.asarray(bias, np.float32)
            m.set_parameters(pp)
            return m

        if t == "Convolution":
            cp = p.get("convolution_param", {})
            kh, kw, sh, sw, ph, pw = _conv_geometry(cp)
            n_out = int(cp.get("num_output", 1))
            group = int(cp.get("group", 1))
            bias_term = bool(cp.get("bias_term", True))
            if blobs and blobs[0].ndim == 4:
                n_in = blobs[0].shape[1] * group
            elif in_shapes and in_shapes[0] is not None:
                n_in = int(in_shapes[0][1])
            else:
                n_in = 3  # unknowable without weights or input shape
            m = nn.SpatialConvolution(n_in, n_out, kw, kh, sw, sh, pw, ph,
                                      n_group=group, with_bias=bias_term)
            if blobs:
                w = blobs[0].reshape(n_out, n_in // group, kh, kw)
                b = blobs[1] if bias_term and len(blobs) > 1 else None
                set_wb(m, w, b)
            return m
        if t == "Deconvolution":
            cp = p.get("convolution_param", {})
            kh, kw, sh, sw, ph, pw = _conv_geometry(cp)
            n_out = int(cp.get("num_output", 1))
            group = int(cp.get("group", 1))
            bias_term = bool(cp.get("bias_term", True))
            # caffe deconv blob layout is [in, out/g, kh, kw] — identical
            # to SpatialFullConvolution's weight layout
            if blobs and blobs[0].ndim == 4:
                n_in = blobs[0].shape[0]
            elif in_shapes and in_shapes[0] is not None:
                n_in = int(in_shapes[0][1])
            else:
                n_in = 3
            m = nn.SpatialFullConvolution(n_in, n_out, kw, kh, sw, sh,
                                          pw, ph, n_group=group,
                                          no_bias=not bias_term)
            if blobs:
                w = blobs[0].reshape(n_in, n_out // group, kh, kw)
                set_wb(m, w, blobs[1] if bias_term and len(blobs) > 1
                       else None)
            return m
        if t == "Pooling":
            pp = p.get("pooling_param", {})
            kh, kw, sh, sw, ph, pw = _conv_geometry(
                {**pp, "kernel_h": pp.get("kernel_h"),
                 "kernel_w": pp.get("kernel_w")})
            pool = pp.get("pool", 0)
            if isinstance(pool, str):
                pool = {"MAX": 0, "AVE": 1}.get(pool, 0)
            if pp.get("global_pooling"):
                return _global_pooling("ave" if pool == 1 else "max")
            # caffe pools use CEIL output shapes by default
            if pool == 1:
                m = nn.SpatialAveragePooling(kw, kh, sw, sh, pw, ph)
            else:
                m = nn.SpatialMaxPooling(kw, kh, sw, sh, pw, ph)
            if hasattr(m, "ceil"):
                m.ceil()
            return m
        if t == "InnerProduct":
            ip = p.get("inner_product_param", {})
            n_out = int(ip.get("num_output", 1))
            bias_term = bool(ip.get("bias_term", True))
            if blobs:
                w = blobs[0].reshape(n_out, -1)
                n_in = w.shape[1]
                lin = nn.Linear(n_in, n_out, with_bias=bias_term)
                set_wb(lin, w, blobs[1] if bias_term and len(blobs) > 1
                       else None)
            else:
                if in_shapes and in_shapes[0] is not None:
                    n_in = int(np.prod(in_shapes[0][1:]))
                else:
                    raise ValueError(
                        f"InnerProduct {layer.name}: input size unknown "
                        "(no weights and no inferable input shape)")
                lin = nn.Linear(n_in, n_out, with_bias=bias_term)
            # caffe IP implicitly flattens trailing dims
            seq = nn.Sequential().add(nn.InferReshape((0, -1))).add(lin)
            return seq
        if t == "ReLU":
            return nn.ReLU()
        if t == "TanH":
            return nn.Tanh()
        if t == "Sigmoid":
            return nn.Sigmoid()
        if t in ("Softmax", "SoftmaxWithLoss"):
            return nn.SoftMax()
        if t == "Dropout":
            ratio = float(p.get("dropout_param", {}).get("dropout_ratio",
                                                         0.5))
            return nn.Dropout(ratio)
        if t == "LRN":
            lp = p.get("lrn_param", {})
            size = int(lp.get("local_size", 5))
            alpha = float(lp.get("alpha", 1.0))
            beta = float(lp.get("beta", 0.75))
            k = float(lp.get("k", 1.0))
            region = lp.get("norm_region", 0)
            if isinstance(region, str):
                region = {"ACROSS_CHANNELS": 0, "WITHIN_CHANNEL": 1}.get(
                    region, 0)
            if region == 1:
                return nn.SpatialWithinChannelLRN(size, alpha, beta)
            return nn.SpatialCrossMapLRN(size, alpha, beta, k)
        if t == "Concat":
            cp = p.get("concat_param", {})
            axis = int(cp.get("axis", cp.get("concat_dim", 1)))
            return nn.JoinTable(axis + 1, 0)
        if t == "Eltwise":
            ep = p.get("eltwise_param", {})
            op = ep.get("operation", 1)
            if isinstance(op, str):
                op = {"PROD": 0, "SUM": 1, "MAX": 2}.get(op, 1)
            coeff = ep.get("coeff", [])
            if not isinstance(coeff, (list, tuple)):
                coeff = [coeff]
            coeff = [float(c) for c in coeff]
            if coeff and any(c != 1.0 for c in coeff):
                if int(op) != 1:
                    raise ValueError(
                        "Eltwise coeff is only defined for SUM "
                        "(caffe.proto EltwiseParameter)")
                # SUM with coefficients: scale each input, then add
                # (CaffeLoader Converter Eltwise; coeff otherwise silently
                # changes the math).
                scaled = nn.ParallelTable()
                for c in coeff:
                    scaled.add(nn.MulConstant(c))
                return nn.Sequential().add(scaled).add(nn.CAddTable())
            return {0: nn.CMulTable(), 1: nn.CAddTable(),
                    2: nn.CMaxTable()}[int(op)]
        if t == "Flatten":
            return nn.InferReshape((0, -1))
        if t == "Power":
            pw = p.get("power_param", {})
            return nn.Power(float(pw.get("power", 1.0)),
                            float(pw.get("scale", 1.0)),
                            float(pw.get("shift", 0.0)))
        if t == "AbsVal":
            return nn.Abs()
        if t in ("BatchNorm",):
            bn_blobs = blobs
            n = bn_blobs[0].shape[0] if bn_blobs else 1
            m = nn.SpatialBatchNormalization(n, affine=False)
            if bn_blobs and len(bn_blobs) >= 3:
                scale = float(bn_blobs[2].reshape(-1)[0]) or 1.0
                st = dict(m.ensure_initialized().get_state())
                st["running_mean"] = (bn_blobs[0] / scale).astype(np.float32)
                st["running_var"] = (bn_blobs[1] / scale).astype(np.float32)
                m.set_state(st)
            return m
        if t == "Scale":
            sp = p.get("scale_param", {})
            n = blobs[0].shape[0] if blobs else 1
            m = nn.CMul((1, n, 1, 1)) if not sp.get("bias_term") else None
            if m is None:
                # scale + shift: emulate with CMul then CAdd in a Sequential
                seq = nn.Sequential()
                cm = nn.CMul((1, n, 1, 1))
                ca = nn.CAdd((1, n, 1, 1))
                if blobs:
                    set_wb(cm, blobs[0].reshape(1, n, 1, 1))
                    if len(blobs) > 1:
                        # CAdd's parameter is named "bias" (nn/CAdd.scala)
                        ca.ensure_initialized()
                        ca.set_parameters(
                            {"bias": blobs[1].reshape(1, n, 1, 1)
                             .astype(np.float32)})
                return seq.add(cm).add(ca)
            if blobs:
                set_wb(m, blobs[0].reshape(1, n, 1, 1))
            return m
        if t in ("Input", "Split"):
            return nn.Identity()
        raise ValueError(f"unsupported caffe layer type {t} "
                         f"({layer.name})")

    # -- graph assembly -----------------------------------------------------
    def _build(self, layers: List[CaffeLayer],
               weight_layers: Dict[str, CaffeLayer]):
        import bigdl_tpu.nn as nn
        blob_node: Dict[str, Any] = {}
        blob_shape: Dict[str, Any] = {}
        input_nodes = []
        consumed = set()
        produced_order: List[str] = []

        def input_node():
            node = nn.Input()()
            input_nodes.append(node)
            return node

        for layer in layers:
            in_shapes = [blob_shape.get(b) for b in layer.bottoms]
            out_shapes = self._infer_shape(layer, in_shapes)
            if layer.type in _SKIP_TYPES or layer.type == "Input":
                for i, top in enumerate(layer.tops):
                    if top not in blob_node:
                        blob_node[top] = input_node()
                    if i < len(out_shapes):
                        blob_shape[top] = out_shapes[i]
                continue
            blobs = layer.blobs or (
                weight_layers[layer.name].blobs
                if layer.name in weight_layers else [])
            module = self._convert(layer, blobs, in_shapes)
            module.set_name(layer.name)
            ins = []
            for b in layer.bottoms:
                if b not in blob_node:
                    blob_node[b] = input_node()
                ins.append(blob_node[b])
                consumed.add(b)
            node = module(*ins) if ins else module(input_node())
            for i, top in enumerate(layer.tops):
                blob_node[top] = node
                produced_order.append(top)
                if i < len(out_shapes):
                    blob_shape[top] = out_shapes[i]
        # outputs = blobs produced but never consumed (graph sinks)
        sinks = [t for t in dict.fromkeys(produced_order)
                 if t not in consumed]
        outputs = [blob_node[t] for t in sinks] or \
            [blob_node[produced_order[-1]]]
        return nn.Graph(input_nodes, outputs)


def load_caffe(def_path: Optional[str] = None,
               model_path: Optional[str] = None):
    """Module.loadCaffeModel equivalent."""
    return CaffeLoader(def_path, model_path).load()
