"""Declarative SLOs over merged fleet snapshots: SloSpec -> SloReport.

The fleet needs ONE answer to "is the service meeting its objectives",
computed from the merged cross-process snapshot (``telemetry.agg``)
instead of ad-hoc per-leg budget asserts. An :class:`SloSpec` declares
objectives ("p99 TTFT <= 250 ms", "goodput >= 40 tok/s", "evictions
<= 0"); :func:`evaluate` resolves each objective's metric selector
against a merged snapshot (plus optional out-of-band observations) and
returns a typed :class:`SloReport` — ``fleet/soak.py`` asserts on it,
``tools/chaos --fleet``/``--hostkill`` fail typed
(:class:`SloBreach`) on it, and the future control plane consumes it.

Spec grammar (one clause per objective, ``;``/newline separated)::

    name: metric <= bound [default D]
    p99_ttft: serving/generation/ttft_ms.p99 <= 250
    goodput:  goodput_tokens_per_sec >= 40 default 0

Metric selectors are ``scalarize`` tags (histograms via ``.p99``/
``.count``/``.sum`` suffixes). A selector that matches several label
series reduces deterministically: counters and ``.count``/``.sum``
sum, everything else takes the WORST series (max) — a p99 objective
holds only if every series holds. ``default D`` substitutes when the
metric is absent (a clean run with zero evictions has no eviction
series to read); without a default, missing data is itself a breach.

:class:`SloEngine` adds multi-window burn-rate state across repeated
evaluations; ``telemetry.agg.detect_stragglers`` flags the gang host
whose step-time/data-wait lags the fleet median beyond a bound
(surfaced by ``tools/diagnose --fleet``).
"""
from __future__ import annotations

import math
import re
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.telemetry.export import scalarize
from bigdl_tpu.telemetry.metrics import MetricsRegistry

__all__ = ["SloObjective", "SloSpec", "SloVerdict", "SloReport",
           "SloBreach", "SloEngine", "evaluate",
           "register_slo_instruments"]


def register_slo_instruments(r: MetricsRegistry) -> dict:
    """Get-or-create the ``fleet/slo/*`` instruments in ``r``
    (covered by ``check --telemetry-audit``)."""
    return {
        "evaluations": r.counter(
            "fleet/slo/evaluations", "SloSpec evaluations"),
        "breaches": r.counter(
            "fleet/slo/breaches", "objectives found in breach"),
        "burn_rate": r.gauge(
            "fleet/slo/burn_rate",
            "error-budget burn rate per window (labelled window=<s>)"),
    }


_INST = register_slo_instruments(telemetry.registry())

_CLAUSE_RE = re.compile(
    r"^\s*([a-z0-9_]+)\s*:\s*(\S+)\s*(<=|>=)\s*([-+0-9.eE]+)"
    r"(?:\s+default\s+([-+0-9.eE]+))?\s*$")


class SloObjective:
    """One declarative objective: ``value(metric) op bound``.

    ``metric`` is a ``scalarize`` tag (or an observation key passed to
    :func:`evaluate`); ``op`` is ``"<="`` or ``">="``; ``default``
    substitutes when the metric is absent (None = absence breaches)."""

    def __init__(self, name: str, metric: str, op: str, bound: float,
                 default: Optional[float] = None):
        if op not in ("<=", ">="):
            raise ValueError(f"{name}: op must be <= or >=, got {op!r}")
        self.name = name
        self.metric = metric
        self.op = op
        self.bound = float(bound)
        self.default = default if default is None else float(default)

    def holds(self, value: float) -> bool:
        """Whether ``value`` satisfies this objective."""
        return (value <= self.bound if self.op == "<="
                else value >= self.bound)

    def to_dict(self) -> dict:
        """JSON-friendly form (round-trips through ``SloSpec.parse``'s
        clause grammar)."""
        return {"name": self.name, "metric": self.metric,
                "op": self.op, "bound": self.bound,
                "default": self.default}

    def __repr__(self) -> str:
        return (f"SloObjective({self.name}: {self.metric} "
                f"{self.op} {self.bound})")


class SloSpec:
    """An ordered set of :class:`SloObjective`\\ s — the declarative
    contract one :func:`evaluate` call checks against a merged
    snapshot."""

    def __init__(self, objectives: Sequence[SloObjective]):
        self.objectives = list(objectives)
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names in {names}")

    @classmethod
    def parse(cls, text: str) -> "SloSpec":
        """Parse the spec grammar: ``name: metric <= bound
        [default D]`` clauses separated by ``;`` or newlines."""
        objectives = []
        for clause in re.split(r"[;\n]", text):
            if not clause.strip():
                continue
            m = _CLAUSE_RE.match(clause)
            if not m:
                raise ValueError(f"unparseable SLO clause: {clause!r}")
            name, metric, op, bound, default = m.groups()
            objectives.append(SloObjective(
                name, metric, op, float(bound),
                None if default is None else float(default)))
        if not objectives:
            raise ValueError("empty SloSpec")
        return cls(objectives)

    def to_dict(self) -> dict:
        """JSON-friendly form."""
        return {"objectives": [o.to_dict() for o in self.objectives]}

    def __repr__(self) -> str:
        return f"SloSpec({[o.name for o in self.objectives]})"


class SloVerdict:
    """One objective's outcome: the resolved value (None = no data),
    where it came from (observation/snapshot/default) and whether the
    objective holds."""

    def __init__(self, objective: SloObjective, value: Optional[float],
                 ok: bool, source: str):
        self.objective = objective
        self.value = value
        self.ok = ok
        self.source = source

    def to_dict(self) -> dict:
        """JSON-friendly form."""
        return {"objective": self.objective.to_dict(),
                "value": self.value, "ok": self.ok,
                "source": self.source}

    def describe(self) -> str:
        """One human line: ``name: value op bound -> ok|BREACH``."""
        o = self.objective
        val = "no data" if self.value is None else f"{self.value:g}"
        state = "ok" if self.ok else "BREACH"
        return (f"{o.name}: {o.metric} = {val} "
                f"(want {o.op} {o.bound:g}) -> {state}")


class SloBreach(RuntimeError):
    """Typed breach error carrying the full :class:`SloReport` —
    what chaos legs raise so callers can branch on ``.report``."""

    def __init__(self, report: "SloReport"):
        self.report = report
        super().__init__(
            "SLO breach: " + ", ".join(report.breached))


class SloReport:
    """Typed result of one spec evaluation: per-objective verdicts,
    the breached-objective names, and a pass flag. ``check()`` raises
    :class:`SloBreach` on breach; ``to_dict()`` embeds in leg
    reports."""

    def __init__(self, spec: SloSpec, verdicts: Sequence[SloVerdict],
                 wall_time: Optional[float] = None):
        self.spec = spec
        self.verdicts = list(verdicts)
        self.wall_time = time.time() if wall_time is None else wall_time
        self.breached = [v.objective.name for v in self.verdicts
                         if not v.ok]
        self.passed = not self.breached

    def check(self) -> "SloReport":
        """Raise :class:`SloBreach` if any objective breached; returns
        self so call sites can chain."""
        if not self.passed:
            raise SloBreach(self)
        return self

    def to_dict(self) -> dict:
        """JSON-friendly form (what chaos/soak reports embed)."""
        return {"passed": self.passed, "breached": list(self.breached),
                "wall_time": self.wall_time,
                "verdicts": [v.to_dict() for v in self.verdicts]}

    def describe(self) -> List[str]:
        """Human lines, one per objective."""
        return [v.describe() for v in self.verdicts]

    def __repr__(self) -> str:
        state = "passed" if self.passed else f"breached={self.breached}"
        return f"SloReport({state})"


def _kind_map(snapshot: Sequence[dict]) -> Dict[str, str]:
    return {row["name"]: row["kind"] for row in snapshot}


def _resolve(metric: str, scalars: Dict[str, float],
             kinds: Dict[str, str]) -> Optional[Tuple[float, str]]:
    if metric in scalars:
        return scalars[metric], "snapshot"
    # label-set reduction: name[labels].suffix tags matching the
    # selector's name + suffix
    m = re.search(r"\.(count|sum|p\d+)$", metric)
    base = metric[:m.start()] if m else metric
    suffix = m.group(0) if m else ""
    tag_re = re.compile(
        re.escape(base) + r"\[[^]]*\]" + re.escape(suffix) + r"$")
    hits = [v for t, v in sorted(scalars.items()) if tag_re.match(t)]
    if not hits:
        return None
    if suffix in (".count", ".sum") or kinds.get(base) == "counter":
        return math.fsum(sorted(hits)), "snapshot-sum"
    return max(hits), "snapshot-max"


def evaluate(spec: SloSpec, snapshot: Optional[Sequence[dict]] = None,
             observations: Optional[Dict[str, float]] = None
             ) -> SloReport:
    """Evaluate ``spec`` over a (merged) snapshot and/or a dict of
    out-of-band observations (observation keys win over snapshot
    tags). Returns the typed :class:`SloReport`; never raises — call
    ``report.check()`` to get the typed :class:`SloBreach`."""
    scalars = scalarize(list(snapshot)) if snapshot else {}
    kinds = _kind_map(snapshot or [])
    verdicts = []
    for obj in spec.objectives:
        if observations and obj.metric in observations:
            value, source = float(observations[obj.metric]), \
                "observation"
        else:
            hit = _resolve(obj.metric, scalars, kinds)
            if hit is not None:
                value, source = hit
            elif obj.default is not None:
                value, source = obj.default, "default"
            else:
                verdicts.append(SloVerdict(obj, None, False, "missing"))
                continue
        verdicts.append(SloVerdict(obj, value, obj.holds(value),
                                   source))
    report = SloReport(spec, verdicts)
    _INST["evaluations"].inc()
    if report.breached:
        _INST["breaches"].inc(len(report.breached))
    return report


class SloEngine:
    """Multi-window burn-rate state over repeated evaluations.

    Each :meth:`evaluate` records a (timestamp, breached?) event; a
    window's **burn rate** is its breach fraction divided by the
    error budget (1.0 = spending budget exactly at the sustainable
    rate). :meth:`burning` is the classic multi-window alert — true
    only when EVERY window burns past ``burn_threshold``, so a single
    bad scrape (short window only) or stale history (long window
    only) does not page. Timestamps are injectable (``now=``) so
    tests are deterministic."""

    def __init__(self, spec: SloSpec, error_budget: float = 0.01,
                 windows: Tuple[float, ...] = (60.0, 600.0),
                 burn_threshold: float = 1.0):
        if error_budget <= 0:
            raise ValueError("error_budget must be > 0")
        self.spec = spec
        self.error_budget = float(error_budget)
        self.windows = tuple(sorted(float(w) for w in windows))
        self.burn_threshold = float(burn_threshold)
        self._events: deque = deque()

    def evaluate(self, snapshot: Optional[Sequence[dict]] = None,
                 observations: Optional[Dict[str, float]] = None,
                 now: Optional[float] = None) -> SloReport:
        """One spec evaluation, recorded into the burn-rate windows."""
        report = evaluate(self.spec, snapshot, observations)
        t = time.time() if now is None else now
        self._events.append((t, not report.passed))
        horizon = t - max(self.windows)
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()
        for w, rate in self.burn_rates(now=t).items():
            _INST["burn_rate"].set(rate, window=f"{w:g}s")
        return report

    def burn_rates(self, now: Optional[float] = None
                   ) -> Dict[float, float]:
        """``{window_s: burn_rate}`` over the recorded evaluations
        (an empty window burns 0.0)."""
        t = time.time() if now is None else now
        out: Dict[float, float] = {}
        for w in self.windows:
            hits = [bad for ts, bad in self._events if ts > t - w]
            frac = (sum(1 for b in hits if b) / len(hits)) if hits \
                else 0.0
            out[w] = frac / self.error_budget
        return out

    def burning(self, now: Optional[float] = None) -> bool:
        """True when every window's burn rate exceeds the threshold —
        the page/abort condition."""
        rates = self.burn_rates(now=now)
        return all(r > self.burn_threshold for r in rates.values())
