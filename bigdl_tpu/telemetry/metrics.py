"""Metrics registry: named Counter/Gauge/Histogram instruments with
label support.

The single pane every subsystem's counters report through (the role the
driver-side ``Metrics`` dump + scattered serving dicts played before):
the optimizer's phase times, the dataset prefetcher's queue depth, the
serving batcher's admission counters and the compile cache's
compilation costs all register here, and the ``telemetry.export``
writers (TensorBoard / Prometheus text / JSONL) read ONE
``MetricsRegistry.snapshot()`` so every exporter agrees on the numbers
by construction.

Conventions:

- **names** follow ``family/component/metric`` (lowercase
  ``[a-z0-9_]``) — ``serving/batcher/requests``,
  ``train/optimizer/data_time_s``. ``audit_names`` (and
  ``python -m bigdl_tpu.tools.check --telemetry-audit``) gate the
  scheme so dashboards can rely on it.
- **labels** are per-call kwargs (``requests.inc(model="resnet")``);
  each distinct label set is an independent series.
- **histograms** keep a bounded sample reservoir and digest it through
  ``utils.profiling.percentile_summary`` — the same percentile
  implementation serving latencies always used.

Instruments are cheap (one lock + dict op per update) and always
active: the serving stats must keep counting whether or not span
tracing is enabled, because ``InferenceService.metrics()`` is public
API. Registries create no threads and no files; only exporters do, and
only when explicitly constructed.
"""
from __future__ import annotations

import re
import threading
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "NAME_RE", "audit_names"]

#: the documented instrument naming scheme: family/component/metric
NAME_RE = re.compile(r"^[a-z0-9_]+/[a-z0-9_]+/[a-z0-9_]+$")

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Instrument:
    """Shared name/description/lock plumbing for the three kinds."""

    kind = "instrument"

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._lock = threading.Lock()

    def label_sets(self) -> List[Dict[str, str]]:
        """Every label combination this instrument has seen."""
        with self._lock:
            return [dict(k) for k in self._series()]

    def _series(self) -> Iterable[LabelKey]:
        raise NotImplementedError

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.name!r})"


class Counter(_Instrument):
    """Monotonically increasing count (requests, rows, compiles)."""

    kind = "counter"

    def __init__(self, name: str, description: str = ""):
        super().__init__(name, description)
        self._values: Dict[LabelKey, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        """Add ``amount`` (>= 0) to the series selected by ``labels``."""
        if amount < 0:
            raise ValueError(
                f"{self.name}: counters only go up (amount={amount}); "
                "use a Gauge for values that can fall")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        """Current total for one label set (0.0 if never incremented)."""
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def total(self) -> float:
        """Sum across every label set."""
        with self._lock:
            return sum(self._values.values())

    def _series(self):
        return list(self._values)


class Gauge(_Instrument):
    """Point-in-time level (queue depth, active versions)."""

    kind = "gauge"

    def __init__(self, name: str, description: str = ""):
        super().__init__(name, description)
        self._values: Dict[LabelKey, float] = {}

    def set(self, value: float, **labels) -> None:
        """Publish the current level for one label set."""
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def add(self, delta: float, **labels) -> None:
        """Adjust the level by ``delta`` (up or down)."""
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + delta

    def value(self, **labels) -> float:
        """Current level for one label set (0.0 if never set)."""
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def _series(self):
        return list(self._values)


class _HistoSeries:
    __slots__ = ("count", "sum", "reservoir")

    def __init__(self, reservoir_size: int):
        self.count = 0
        self.sum = 0.0
        self.reservoir: deque = deque(maxlen=reservoir_size)


class Histogram(_Instrument):
    """Distribution of observations (latencies, batch sizes): exact
    count/sum plus a bounded reservoir digested through
    ``utils.profiling.percentile_summary``."""

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 reservoir_size: int = 2048):
        super().__init__(name, description)
        self.reservoir_size = reservoir_size
        self._values: Dict[LabelKey, _HistoSeries] = {}

    def observe(self, value: float, **labels) -> None:
        """Record one observation into the series for ``labels``."""
        key = _label_key(labels)
        v = float(value)
        with self._lock:
            s = self._values.get(key)
            if s is None:
                s = self._values[key] = _HistoSeries(self.reservoir_size)
            s.count += 1
            s.sum += v
            s.reservoir.append(v)

    def count(self, **labels) -> int:
        """Observations recorded for one label set."""
        with self._lock:
            s = self._values.get(_label_key(labels))
            return s.count if s else 0

    def sum(self, **labels) -> float:
        """Exact sum of every observation for one label set (counts
        all observations, not just the reservoir)."""
        with self._lock:
            s = self._values.get(_label_key(labels))
            return s.sum if s else 0.0

    def samples(self, **labels) -> List[float]:
        """The retained reservoir for one label set (newest last)."""
        with self._lock:
            s = self._values.get(_label_key(labels))
            return list(s.reservoir) if s else []

    def percentiles(self, qs=(50, 90, 99), **labels) -> Dict[str, float]:
        """``{"p50": ...}`` digest of the reservoir via
        ``utils.profiling.percentile_summary``."""
        from bigdl_tpu.utils.profiling import percentile_summary
        return percentile_summary(self.samples(**labels), qs)

    def series_snapshot(self, qs=(50, 90, 99), include_samples=False,
                        **labels) -> Dict[str, float]:
        """Count, sum and percentile digest read under ONE lock
        acquisition — an exporter scrape taken mid-traffic must not mix
        a count from one instant with a sum from the next (sum/count
        averages would lie). ``include_samples`` adds the raw reservoir
        under ``"samples"`` so cross-process mergers
        (``telemetry.agg``) can re-digest exact percentiles."""
        from bigdl_tpu.utils.profiling import percentile_summary
        with self._lock:
            s = self._values.get(_label_key(labels))
            count = s.count if s else 0
            total = s.sum if s else 0.0
            samples = list(s.reservoir) if s else []
        out = {"count": count, "sum": total,
               **percentile_summary(samples, qs)}
        if include_samples:
            out["samples"] = samples
        return out

    def _series(self):
        return list(self._values)


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Named instruments, get-or-create, one snapshot for exporters.

    Subsystems call ``counter/gauge/histogram`` at module scope or
    construction time; re-requesting a name returns the SAME instrument
    (so two batchers for one model share series through labels) and a
    kind conflict raises instead of silently splitting the data.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}

    def _get(self, cls, name: str, description: str, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name, description,
                                                     **kw)
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"{name!r} is already registered as a {inst.kind}, "
                    f"not a {cls.kind}")
            return inst

    def counter(self, name: str, description: str = "") -> Counter:
        """Get-or-create the Counter registered under ``name``."""
        return self._get(Counter, name, description)

    def gauge(self, name: str, description: str = "") -> Gauge:
        """Get-or-create the Gauge registered under ``name``."""
        return self._get(Gauge, name, description)

    def histogram(self, name: str, description: str = "",
                  reservoir_size: int = 2048) -> Histogram:
        """Get-or-create the Histogram registered under ``name``."""
        return self._get(Histogram, name, description,
                         reservoir_size=reservoir_size)

    def names(self) -> List[str]:
        """Registered instrument names, sorted."""
        with self._lock:
            return sorted(self._instruments)

    def get(self, name: str) -> Optional[_Instrument]:
        """The instrument under ``name``, or None."""
        with self._lock:
            return self._instruments.get(name)

    def snapshot(self, include_samples: bool = False) -> List[dict]:
        """Point-in-time dump every exporter renders from: one row per
        instrument with per-label-set values (histograms carry count,
        sum and the percentile digest; ``include_samples`` adds each
        histogram series' raw reservoir for cross-process merging).

        Locking contract (audited against concurrent get-or-create):
        the instrument map is copied under the registry ``_lock`` —
        the same lock :meth:`_get` creates under — then each
        instrument's series are read under that instrument's own lock
        (``series_snapshot`` reads a histogram's count/sum/reservoir
        in ONE acquisition, so a row is never torn). An instrument
        registered after the copy simply lands in the next snapshot."""
        with self._lock:
            instruments = [self._instruments[n]
                           for n in sorted(self._instruments)]
        rows = []
        for inst in instruments:
            series = []
            for labels in inst.label_sets():
                if inst.kind == "histogram":
                    series.append({
                        "labels": labels,
                        **inst.series_snapshot((50, 90, 99),
                                               include_samples,
                                               **labels)})
                else:
                    series.append({"labels": labels,
                                   "value": inst.value(**labels)})
            rows.append({"name": inst.name, "kind": inst.kind,
                         "description": inst.description,
                         "series": series})
        return rows


def audit_names(registry: MetricsRegistry) -> List[str]:
    """Instrument names violating the documented
    ``family/component/metric`` scheme (``NAME_RE``); empty = clean.
    ``tools.check --telemetry-audit`` wraps this with stable exit
    codes."""
    return [n for n in registry.names() if not NAME_RE.match(n)]
