"""Cross-process telemetry aggregation: snapshot shipping + merging.

The PR 3 registry/tracer are strictly per-process, but the system spans
gang-launched multi-host training (``tools/launch``) and subprocess
fleet replicas (``fleet/replica.ProcessReplica``). This module is the
fleet-wide plane:

- a **snapshot shipper**: each process periodically appends an
  identity-stamped registry snapshot (the ``JsonlExporter`` wire
  format, histogram reservoirs included) to its own file in a shared
  directory. Arm with :func:`start_shipping` or
  ``BIGDL_TELEMETRY_SHIP_DIR=/path``; disarmed :func:`maybe_ship`
  costs ONE module-flag check (the ``telemetry.span`` discipline,
  micro-benchmark-asserted).
- an **aggregator** (:func:`aggregate_snapshots`) with defined
  semantics per instrument kind: counters sum, gauges keep per-source
  series (a ``host=``/``replica=`` label is injected), histograms
  merge exactly on count/sum and deterministically on reservoirs.
  Merged totals equal the sum of per-process snapshots to the digit
  (:func:`check_merge_invariant` asserts it; sums go through
  ``math.fsum`` over sorted values so the merge is order-independent
  and associative).
- a **trace merger** (:func:`merge_chrome_traces`): per-host Chrome
  trace files combine into one Perfetto timeline — each source becomes
  its own process track (pids remapped, a ``process_name`` metadata
  row added), thread/virtual-track tids are preserved verbatim, and
  flow-event ids are namespaced per source so PR 10 request flows
  never collide across hosts.

``tools/diagnose --fleet <dir>`` renders the merged
where-did-the-time-go report from a shipped-snapshot directory;
``telemetry.slo`` evaluates SLOs over the merged rows.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.telemetry.export import (JsonlExporter, process_identity,
                                        read_jsonl_with_identity)
from bigdl_tpu.telemetry.metrics import MetricsRegistry, _label_key
from bigdl_tpu.utils.profiling import percentile_summary

__all__ = ["start_shipping", "stop_shipping", "shipping", "maybe_ship",
           "read_snapshot_dir", "aggregate_snapshots",
           "check_merge_invariant", "detect_stragglers", "source_tag",
           "merge_chrome_traces", "merge_chrome_trace_files",
           "write_merged_trace", "register_agg_instruments",
           "MERGE_RESERVOIR"]

#: merged-reservoir cap per histogram series; below it the reservoir
#: merge is the exact sorted multiset union (associative and
#: order-independent), above it an even-stride decimation applies.
MERGE_RESERVOIR = 8192


def register_agg_instruments(r: MetricsRegistry) -> dict:
    """Get-or-create the ``telemetry/agg/*`` instruments in ``r``
    (covered by ``check --telemetry-audit``)."""
    return {
        "ship_lines": r.counter(
            "telemetry/agg/ship_lines",
            "snapshot lines appended by the periodic shipper"),
        "merges": r.counter(
            "telemetry/agg/merges", "aggregate_snapshots() calls"),
        "sources": r.counter(
            "telemetry/agg/sources",
            "per-process sources consumed by merges"),
    }


_INST = register_agg_instruments(telemetry.registry())

# the ONE flag the disarmed maybe_ship() fast path reads
_ARMED = False
_LOCK = threading.Lock()
_STATE: dict = {"exporter": None, "interval_s": 1.0, "last": 0.0,
                "path": None}


def shipping() -> bool:
    """Whether the periodic snapshot shipper is armed."""
    return _ARMED


def source_tag(identity: Optional[dict]) -> str:
    """Stable human tag for one source: the replica name when the
    identity carries one, else ``host<N>``, else the pid."""
    ident = identity or {}
    if ident.get("replica"):
        return str(ident["replica"])
    if ident.get("host") is not None:
        return f"host{ident['host']}"
    if ident.get("pid") is not None:
        return f"pid{ident['pid']}"
    return str(ident.get("file", "?"))


def start_shipping(directory: str, interval_s: float = 1.0,
                   registry: Optional[MetricsRegistry] = None,
                   identity: Optional[dict] = None) -> str:
    """Arm the shipper: :func:`maybe_ship` appends identity-stamped
    snapshots (reservoirs included) of ``registry`` (default: the
    process registry) to ``<directory>/snap-<tag>-<pid>.jsonl`` at most
    every ``interval_s`` seconds. Returns the snapshot file path.
    Also armed at import by ``BIGDL_TELEMETRY_SHIP_DIR=/path``
    (interval from ``BIGDL_TELEMETRY_SHIP_EVERY_S``)."""
    global _ARMED
    ident = identity if identity is not None else process_identity()
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(
        directory, f"snap-{source_tag(ident)}-{os.getpid()}.jsonl")
    with _LOCK:
        _STATE["exporter"] = JsonlExporter(
            registry if registry is not None else telemetry.registry(),
            path, identity=ident, include_samples=True)
        _STATE["interval_s"] = max(float(interval_s), 0.0)
        _STATE["last"] = 0.0
        _STATE["path"] = path
        _ARMED = True
    return path


def stop_shipping(final: bool = True) -> None:
    """Disarm the shipper; ``final=True`` ships one last snapshot
    first so the file carries the end-of-life totals."""
    global _ARMED
    if final and _ARMED:
        maybe_ship(force=True)
    _ARMED = False


def maybe_ship(force: bool = False) -> Optional[str]:
    """Ship one snapshot line if armed and the interval elapsed
    (``force=True`` skips the interval gate). Disarmed cost: ONE
    module-flag check — safe at optimizer-step cadence. Returns the
    snapshot file path when a line was written, else None."""
    if not _ARMED:
        return None
    return _ship(force)


def _ship(force: bool) -> Optional[str]:
    with _LOCK:
        exporter = _STATE["exporter"]
        if exporter is None:
            return None
        now = time.monotonic()
        if not force and now - _STATE["last"] < _STATE["interval_s"]:
            return None
        _STATE["last"] = now
    exporter.export()
    _INST["ship_lines"].inc()
    return exporter.path


def read_snapshot_dir(directory: str
                      ) -> List[Tuple[dict, List[dict]]]:
    """``[(identity, snapshot_rows)]`` from every ``*.jsonl`` file in
    ``directory`` (sorted by name, so merges are deterministic). The
    LAST record per file wins — counters are cumulative, so the final
    snapshot carries the totals. Torn trailing lines (a SIGKILLed
    shipper) are skipped; headerless files get a file-derived
    identity."""
    out: List[Tuple[dict, List[dict]]] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".jsonl"):
            continue
        path = os.path.join(directory, name)
        identity, records = read_jsonl_with_identity(path, tolerant=True)
        records = [r for r in records if isinstance(r.get("metrics"), list)]
        if not records:
            continue
        if identity is None:
            identity = {"file": name}
        out.append((identity, records[-1]["metrics"]))
    return out


def _fsum_sorted(values) -> float:
    return math.fsum(sorted(float(v) for v in values))


def _percentile_keys(series: dict) -> List[str]:
    return [k for k in series
            if k.startswith("p") and k[1:].isdigit()]


class _HistAcc:
    __slots__ = ("labels", "counts", "sums", "samples", "digests",
                 "exact")

    def __init__(self, labels):
        self.labels = labels
        self.counts: List[float] = []
        self.sums: List[float] = []
        self.samples: List[float] = []
        self.digests: List[Tuple[float, dict]] = []
        self.exact = True  # every source carried its reservoir


def _merge_histogram(acc: _HistAcc) -> dict:
    count = int(_fsum_sorted(acc.counts))
    total = _fsum_sorted(acc.sums)
    samples = sorted(acc.samples)
    if len(samples) > MERGE_RESERVOIR:
        stride = len(samples) / float(MERGE_RESERVOIR)
        samples = [samples[int(i * stride)]
                   for i in range(MERGE_RESERVOIR)]
    if acc.exact:
        pcts = percentile_summary(samples, (50, 90, 99))
    else:
        # a source shipped only its digest: fall back to the
        # count-weighted mean of per-source percentiles (deterministic,
        # documented as approximate in docs/telemetry.md)
        pcts = {}
        weight = sum(w for w, _ in acc.digests) or 1.0
        keys = sorted({k for _, d in acc.digests
                       for k in _percentile_keys(d)})
        for k in keys:
            pcts[k] = math.fsum(
                w * float(d.get(k, 0.0)) for w, d in acc.digests
            ) / weight
    out = {"labels": dict(acc.labels), "count": count, "sum": total}
    out.update(pcts)
    out["samples"] = samples
    return out


def aggregate_snapshots(sources: Sequence[Tuple[dict, List[dict]]]
                        ) -> List[dict]:
    """Merge per-process registry snapshots into one fleet snapshot
    (same row schema, so ``scalarize``/exporters/diagnose consume it
    unchanged).

    Per-kind semantics (the merge-algebra tests pin these):

    - **counters**: values sum per label set, exactly — ``fsum`` over
      sorted addends, so the total is independent of source order and
      equals the per-process sums to the digit.
    - **gauges**: a level has no cross-process sum; each source's
      series keeps its own identity via an injected ``replica=<name>``
      or ``host=<n>`` label (two files from one identity: the later
      file in sorted order wins).
    - **histograms**: count/sum merge exactly; reservoirs merge as the
      sorted multiset union (associative and order-independent up to
      :data:`MERGE_RESERVOIR`, then even-stride decimation) and
      percentiles are re-digested from the merged reservoir. A source
      without shipped samples degrades that series' percentiles to a
      count-weighted mean of per-source digests (count/sum stay
      exact).

    ``sources`` is ``[(identity, snapshot_rows)]`` as returned by
    :func:`read_snapshot_dir`.
    """
    _INST["merges"].inc()
    _INST["sources"].inc(len(sources))
    merged: Dict[str, dict] = {}
    for identity, rows in sources:
        ident = identity or {}
        if ident.get("replica"):
            skey, sval = "replica", str(ident["replica"])
        elif ident.get("host") is not None:
            skey, sval = "host", str(ident["host"])
        else:
            skey, sval = "host", source_tag(ident)
        for row in rows:
            name = row["name"]
            m = merged.get(name)
            if m is None:
                m = merged[name] = {
                    "name": name, "kind": row["kind"],
                    "description": row.get("description", ""),
                    "_series": {}}
            elif m["kind"] != row["kind"]:
                raise ValueError(
                    f"{name!r}: kind conflict across sources "
                    f"({m['kind']} vs {row['kind']})")
            acc = m["_series"]
            for s in row["series"]:
                labels = dict(s.get("labels") or {})
                if row["kind"] == "gauge":
                    labels[skey] = sval
                key = _label_key(labels)
                if row["kind"] == "counter":
                    acc.setdefault(key, {"labels": labels,
                                         "values": []})
                    acc[key]["values"].append(float(s["value"]))
                elif row["kind"] == "gauge":
                    acc[key] = {"labels": labels,
                                "value": float(s["value"])}
                else:
                    h = acc.get(key)
                    if h is None:
                        h = acc[key] = _HistAcc(labels)
                    h.counts.append(s["count"])
                    h.sums.append(s["sum"])
                    if "samples" in s:
                        h.samples.extend(float(v)
                                         for v in s["samples"])
                    else:
                        h.exact = False
                    h.digests.append(
                        (float(s["count"]),
                         {k: s[k] for k in _percentile_keys(s)}))
    out: List[dict] = []
    for name in sorted(merged):
        m = merged[name]
        series = []
        for key in sorted(m["_series"]):
            s = m["_series"][key]
            if m["kind"] == "counter":
                series.append({"labels": s["labels"],
                               "value": _fsum_sorted(s["values"])})
            elif m["kind"] == "gauge":
                series.append({"labels": s["labels"],
                               "value": s["value"]})
            else:
                series.append(_merge_histogram(s))
        out.append({"name": name, "kind": m["kind"],
                    "description": m["description"], "series": series})
    return out


def check_merge_invariant(sources: Sequence[Tuple[dict, List[dict]]],
                          merged: List[dict]) -> List[str]:
    """Violations of the merged-registry agreement (empty = clean):
    every counter total and histogram count/sum in ``merged`` must
    equal the per-process sums EXACTLY (same ``fsum``-over-sorted
    reduction on both sides, so float addition order cannot excuse a
    mismatch). Asserted by the merge-algebra tests and the
    ``diagnose --fleet`` invariant check."""
    bad: List[str] = []
    per_name: Dict[str, dict] = {}
    for _, rows in sources:
        for row in rows:
            e = per_name.setdefault(
                row["name"], {"kind": row["kind"], "values": [],
                              "counts": [], "sums": []})
            for s in row["series"]:
                if row["kind"] == "counter":
                    e["values"].append(s["value"])
                elif row["kind"] == "histogram":
                    e["counts"].append(s["count"])
                    e["sums"].append(s["sum"])
    for row in merged:
        e = per_name.get(row["name"])
        if e is None:
            bad.append(f"{row['name']}: present in merged snapshot "
                       "but in no source")
            continue
        if row["kind"] == "counter":
            want = _fsum_sorted(e["values"])
            got = _fsum_sorted(s["value"] for s in row["series"])
            if got != want:
                bad.append(f"{row['name']}: merged counter total "
                           f"{got!r} != per-process sum {want!r}")
        elif row["kind"] == "histogram":
            want_c = int(_fsum_sorted(e["counts"]))
            got_c = int(_fsum_sorted(s["count"]
                                     for s in row["series"]))
            if got_c != want_c:
                bad.append(f"{row['name']}: merged histogram count "
                           f"{got_c} != per-process sum {want_c}")
            want_s = _fsum_sorted(e["sums"])
            got_s = _fsum_sorted(s["sum"] for s in row["series"])
            if got_s != want_s:
                bad.append(f"{row['name']}: merged histogram sum "
                           f"{got_s!r} != per-process sum {want_s!r}")
    return bad


def detect_stragglers(sources: Sequence[Tuple[dict, List[dict]]],
                      metric: str = "train/optimizer/computing_time",
                      stat: str = "p50",
                      threshold: float = 1.5) -> dict:
    """Per-host skew on one histogram ``metric`` vs the fleet median.

    For each source, ``stat`` (``p50``/``p90``/``p99``) of ``metric``
    is computed — exactly from shipped reservoir samples when present,
    else as the count-weighted mean of per-series digests. A source
    whose value exceeds ``threshold`` x the fleet median is a
    straggler. Returns ``{"metric", "stat", "threshold", "per_source",
    "median", "stragglers"}`` where ``stragglers`` entries carry
    ``source``/``value``/``ratio``. Rendered by ``tools/diagnose
    --fleet`` (step time AND data wait) and fed to the host-kill chaos
    leg's SLO as a skew observation."""
    per_source: Dict[str, float] = {}
    for ident, rows in sources:
        tag = source_tag(ident)
        for row in rows:
            if row["name"] != metric or row["kind"] != "histogram":
                continue
            samples: List[float] = []
            digests: List[Tuple[float, float]] = []
            for s in row["series"]:
                if s.get("samples"):
                    samples.extend(float(v) for v in s["samples"])
                elif stat in s:
                    digests.append((float(s.get("count", 1)) or 1.0,
                                    float(s[stat])))
            if samples:
                q = int(stat[1:]) if stat.startswith("p") \
                    and stat[1:].isdigit() else 50
                val = percentile_summary(samples, (q,)).get(stat, 0.0)
            elif digests:
                weight = sum(c for c, _ in digests)
                val = math.fsum(c * v for c, v in digests) / weight
            else:
                continue
            per_source[tag] = float(val)
    values = sorted(per_source.values())
    if values:
        mid = len(values) // 2
        median = values[mid] if len(values) % 2 \
            else (values[mid - 1] + values[mid]) / 2.0
    else:
        median = 0.0
    stragglers = []
    for tag in sorted(per_source):
        val = per_source[tag]
        ratio = val / median if median > 0 \
            else (0.0 if val == 0.0 else float("inf"))
        if median > 0 and ratio > threshold:
            stragglers.append({"source": tag, "value": val,
                               "ratio": round(ratio, 3)})
    return {"metric": metric, "stat": stat, "threshold": threshold,
            "per_source": per_source, "median": median,
            "stragglers": stragglers}


# ------------------------------------------------------------ trace merge

def merge_chrome_traces(sources: Sequence[Tuple[object, List[dict]]]
                        ) -> List[dict]:
    """Combine per-host Chrome trace event lists into ONE Perfetto
    timeline. Each source ``(identity_or_label, events)`` becomes its
    own process track: pids are remapped to a deterministic per-source
    pid (1-based source index) with a ``process_name`` metadata row,
    tids — including the tracer's virtual-track tids — are preserved
    verbatim, and flow-event ``id``\\ s are prefixed with the source
    tag so request flows from different hosts never pair up."""
    merged: List[dict] = []
    seen_tags: Dict[str, int] = {}
    for idx, (identity, events) in enumerate(sources):
        tag = identity if isinstance(identity, str) \
            else source_tag(identity)
        if tag in seen_tags:
            seen_tags[tag] += 1
            tag = f"{tag}#{seen_tags[tag]}"
        else:
            seen_tags[tag] = 0
        pid = idx + 1
        merged.append({"ph": "M", "pid": pid, "tid": 0,
                       "name": "process_name", "args": {"name": tag}})
        for ev in events:
            ev = dict(ev)
            ev["pid"] = pid
            if "id" in ev:
                ev["id"] = f"{tag}:{ev['id']}"
            merged.append(ev)
    return merged


def merge_chrome_trace_files(paths: Sequence[str]) -> List[dict]:
    """Merge Chrome trace FILES (``{"traceEvents": [...]}`` or a bare
    event list; the tracer and flight bundles write the former) into
    one merged event list, labelling each source by file stem."""
    sources: List[Tuple[object, List[dict]]] = []
    for path in paths:
        with open(path) as f:
            doc = json.load(f)
        events = doc["traceEvents"] if isinstance(doc, dict) else doc
        stem = os.path.splitext(os.path.basename(path))[0]
        sources.append((stem, events))
    return merge_chrome_traces(sources)


def write_merged_trace(path: str,
                       sources: Sequence[Tuple[object, List[dict]]]
                       ) -> int:
    """Write the merged timeline of ``sources`` (see
    :func:`merge_chrome_traces`) as Chrome trace-event JSON; returns
    the merged event count."""
    events = merge_chrome_traces(sources)
    with open(path, "w") as f:
        json.dump({"traceEvents": events}, f)
    return len(events)


if os.environ.get("BIGDL_TELEMETRY_SHIP_DIR", "").strip():
    try:
        _every = float(
            os.environ.get("BIGDL_TELEMETRY_SHIP_EVERY_S", "") or 1.0)
    except ValueError:
        _every = 1.0
    start_shipping(os.environ["BIGDL_TELEMETRY_SHIP_DIR"],
                   interval_s=_every)
