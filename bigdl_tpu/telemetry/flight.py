"""Crash flight recorder: a bounded ring of recent events that dumps a
post-mortem bundle when something dies.

Production failures are diagnosed from what the process *was doing*
right before it died — but the span trace and metric snapshots live in
process memory, which is exactly what a crash destroys. The flight
recorder keeps an always-cheap bounded ring of notes (fault events,
fatal classifications, metric snapshots) and, on a fatal path, writes a
**post-mortem bundle** to disk: the ring as JSONL, the span tracer's
Chrome trace, the metrics registry snapshot and the program-profile
registry — everything ``python -m bigdl_tpu.tools.diagnose
--postmortem <dir>`` needs to reconstruct the last seconds.

Armed fatal paths (all no-ops while disarmed):

- the :class:`~bigdl_tpu.optim.optimizer.Optimizer` retry loop, when it
  classifies an error fatal (or exhausts its budget) and re-raises;
- the serving :class:`~bigdl_tpu.serving.batcher.MicroBatcher` and
  generation :class:`~bigdl_tpu.generation.loop.DecodeLoop`
  supervisors, when the worker thread dies (``WorkerDied``);
- :func:`bigdl_tpu.faults.point`'s SIGKILL action, immediately before
  the process kills itself (the bundle is the only survivor).

Disarmed is the default and costs **one module-flag check** per
:func:`note` — the ``telemetry.span`` discipline, asserted by a
micro-benchmark test. Arm with :func:`arm` (or ``BIGDL_FLIGHT_DIR=
/path``); the per-process dump count is capped so a crash loop cannot
fill a disk.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Optional

import bigdl_tpu.telemetry as telemetry

__all__ = ["arm", "disarm", "armed", "note", "note_metrics", "on_fatal",
           "dump", "events", "MANIFEST_FORMAT"]

#: bundle format tag the diagnose ingester checks
MANIFEST_FORMAT = "bigdl-flight-1"

_DUMPS = telemetry.counter(
    "telemetry/flight/dumps", "post-mortem bundles written")
_NOTES = telemetry.counter(
    "telemetry/flight/notes", "events recorded into the armed ring")

# the ONE flag the disarmed note() fast path reads
_ARMED = False
_DIR: Optional[str] = None
_RING: deque = deque(maxlen=4096)
_LOCK = threading.Lock()
_SEQ = [0]
_MAX_DUMPS = int(os.environ.get("BIGDL_FLIGHT_MAX_DUMPS", 8))


def armed() -> bool:
    """Whether the flight recorder is currently armed."""
    return _ARMED


def arm(directory: Optional[str] = None, capacity: int = 4096) -> str:
    """Arm the recorder: ring notes accumulate and fatal paths dump
    bundles under ``directory`` (default ``./flight``; created
    lazily). Returns the bundle base directory."""
    global _ARMED, _DIR, _RING
    with _LOCK:
        _DIR = directory or _DIR or "flight"
        if capacity != _RING.maxlen:
            _RING = deque(_RING, maxlen=capacity)
        _ARMED = True
        return _DIR


def disarm() -> None:
    """Disarm the recorder; the ring stays readable via
    :func:`events` until re-armed or the process exits."""
    global _ARMED
    _ARMED = False


def note(kind: str, **data) -> None:
    """Append one event to the ring (no-op while disarmed: one flag
    check, no clock, no lock)."""
    if not _ARMED:
        return
    rec = {"t": time.time(), "kind": kind}
    rec.update(data)
    with _LOCK:
        _RING.append(rec)
    _NOTES.inc(kind=kind)


def note_metrics(meta: Optional[dict] = None) -> None:
    """Ring-record a scalarized snapshot of the default metrics
    registry (call at sync cadence points; no-op while disarmed)."""
    if not _ARMED:
        return
    scalars = telemetry.scalarize(telemetry.registry().snapshot())
    note("metrics", meta=meta or {}, scalars=scalars)


def events() -> list:
    """Snapshot of the ring (oldest first)."""
    with _LOCK:
        return list(_RING)


def _error_payload(error: Optional[BaseException]) -> Optional[dict]:
    if error is None:
        return None
    return {"type": type(error).__name__, "message": str(error)}


def dump(reason: str, error: Optional[BaseException] = None,
         metrics=None) -> Optional[str]:
    """Write one post-mortem bundle directory and return its path
    (None while disarmed or past the per-process dump cap).

    Bundle contents: ``MANIFEST.json`` (format tag, reason, error,
    wall time, pid), ``events.jsonl`` (the ring), ``trace.json`` (the
    span tracer's Chrome trace — empty but well-formed when tracing
    was off), ``metrics.json`` (default-registry snapshot plus the
    optional ``metrics`` registry, e.g. a service's private one) and
    ``programs.json`` (the program-profile registry)."""
    if not _ARMED:
        return None
    with _LOCK:
        if _SEQ[0] >= _MAX_DUMPS:
            return None
        _SEQ[0] += 1
        seq = _SEQ[0]
        base = _DIR or "flight"
        ring = list(_RING)
    path = os.path.join(base, f"postmortem-{os.getpid()}-{seq:03d}")
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "events.jsonl"), "w") as f:
        for rec in ring:
            f.write(json.dumps(rec, default=str) + "\n")
    telemetry.tracer().export_chrome_trace(
        os.path.join(path, "trace.json"))
    snapshots = {"default": telemetry.registry().snapshot()}
    if metrics is not None and metrics is not telemetry.registry():
        snapshots["local"] = metrics.snapshot()
    with open(os.path.join(path, "metrics.json"), "w") as f:
        json.dump(snapshots, f, default=str)
    from bigdl_tpu.telemetry import programs
    with open(os.path.join(path, "programs.json"), "w") as f:
        json.dump(programs.registry().to_dict(), f, default=str)
    manifest = {"format": MANIFEST_FORMAT, "reason": reason,
                "error": _error_payload(error),
                "wall_time": time.time(), "pid": os.getpid(),
                "events": len(ring)}
    with open(os.path.join(path, "MANIFEST.json"), "w") as f:
        json.dump(manifest, f)
    _DUMPS.inc(reason=reason)
    return path


def on_fatal(source: str, error: Optional[BaseException] = None,
             metrics=None) -> Optional[str]:
    """The fatal-path hook: ring-note the death and dump a bundle
    (no-op while disarmed — one flag check). ``source`` names the
    dying subsystem (``train/optimizer``, ``serving/dispatch``,
    ``serving/decode``, ``faults/<point>``)."""
    if not _ARMED:
        return None
    note("fatal", source=source,
         error=_error_payload(error))
    return dump(source, error=error, metrics=metrics)


if os.environ.get("BIGDL_FLIGHT_DIR", "").strip():
    arm(os.environ["BIGDL_FLIGHT_DIR"])
