"""Host-side span tracer: nested, thread-aware wall-clock spans.

The missing observability layer between the driver's ``Metrics`` averages
and ``jax.profiler``'s device traces (utils/profiling.trace): *host*
attribution — where a step's wall-clock went across data staging,
compile, collective entry, serving queues — recorded with monotonic
clocks into a bounded ring buffer and exported as Chrome trace-event
JSON that loads in Perfetto / ``chrome://tracing``.

Design constraints (ISSUE 3 acceptance criteria):

- **near-zero overhead when disabled** — ``span()`` checks ONE module
  flag and returns a shared no-op context manager; no allocation, no
  clock read, no lock. A micro-benchmark test asserts the bound.
- **bounded memory** — finished spans land in a ``deque(maxlen=...)``
  ring; a forgotten-enabled tracer can never grow without limit.
- **thread-aware nesting** — each thread keeps its own open-span stack
  (``threading.local``), so serving batcher threads, prefetch stagers
  and the driver loop interleave without corrupting each other's
  nesting; Chrome trace ``tid`` separates them per track.

Spans are "complete" events (``ph: "X"``): one record per finished span
with ``ts``/``dur`` in microseconds on one monotonic clock, which is
what keeps the export loadable by the trace-event schema without
begin/end pairing fix-ups.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

__all__ = ["SpanRecord", "SpanTracer", "NOOP_SPAN"]


class SpanRecord:
    """One finished span: name, monotonic start, duration, thread,
    nesting depth, user args (the kwargs passed to ``span()``), and an
    optional ``flow`` link ``(flow_id, src_tid)`` — the Chrome-trace
    flow arrow tying this span's track back to the thread that
    recorded it (request tracks use it to point at the dispatch
    thread)."""

    __slots__ = ("name", "ts", "dur", "tid", "depth", "args", "flow")

    def __init__(self, name: str, ts: float, dur: float, tid: int,
                 depth: int, args: Optional[Dict[str, Any]],
                 flow: Optional[tuple] = None):
        self.name = name
        self.ts = ts          # seconds, monotonic clock
        self.dur = dur        # seconds
        self.tid = tid
        self.depth = depth
        self.args = args
        self.flow = flow

    def __repr__(self) -> str:
        return (f"SpanRecord({self.name!r} ts={self.ts:.6f} "
                f"dur={self.dur * 1e3:.3f}ms tid={self.tid} "
                f"depth={self.depth})")


class _NoopSpan:
    """The shared disabled-path context manager: no state, no clock."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


class _Span:
    """A live (enabled-path) span context manager."""

    __slots__ = ("_tracer", "name", "args", "_t0", "_depth")

    def __init__(self, tracer: "SpanTracer", name: str,
                 args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.args = args

    def __enter__(self):
        stack = self._tracer._stack()
        self._depth = len(stack)
        stack.append(self)
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        dur = time.monotonic() - self._t0
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        self._tracer._record(SpanRecord(
            self.name, self._t0, dur, threading.get_ident(),
            self._depth, self.args))
        return False


class SpanTracer:
    """Bounded ring buffer of finished spans + per-thread open stacks.

    ``span(name, **args)`` is the instrumentation surface (usually via
    ``bigdl_tpu.telemetry.span`` which adds the disabled fast path);
    ``record(name, duration_s)`` logs a pre-measured interval ending
    now — the optimizer uses it so the trace carries the EXACT
    ``t_data``/``t_compute`` numbers ``Metrics.summary()`` reports,
    keeping the two views arithmetically consistent.
    """

    #: virtual-track tids start here — far above any OS thread ident,
    #: so request tracks can never collide with a real thread's track
    _TRACK_BASE = 1 << 48
    #: bound on live virtual tracks: one track per in-flight request is
    #: plenty, and an unbounded name->tid dict would leak at traffic
    #: rate (the cardinality failure the ring buffer exists to prevent)
    _MAX_TRACKS = 4096

    def __init__(self, capacity: int = 65536):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=capacity)
        self._local = threading.local()
        self._thread_names: Dict[int, str] = {}
        self._tracks: "OrderedDict[str, int]" = OrderedDict()
        self._next_track = self._TRACK_BASE

    # ------------------------------------------------------ recording
    def _stack(self) -> List[_Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = []
            self._local.stack = stack
            tid = threading.get_ident()
            with self._lock:
                self._thread_names[tid] = threading.current_thread().name
        return stack

    def _record(self, rec: SpanRecord) -> None:
        with self._lock:
            self._spans.append(rec)

    def span(self, name: str,
             args: Optional[Dict[str, Any]] = None) -> _Span:
        """Context manager measuring the enclosed block as one span."""
        return _Span(self, name, args)

    def record(self, name: str, duration_s: float,
               args: Optional[Dict[str, Any]] = None,
               end: Optional[float] = None) -> None:
        """Log a pre-measured interval of ``duration_s`` seconds ending
        at ``end`` (monotonic; default: now). Depth nests under
        whatever span is currently open on this thread."""
        self._stack()  # register the thread name
        t1 = time.monotonic() if end is None else end
        self._record(SpanRecord(name, t1 - duration_s, float(duration_s),
                                threading.get_ident(),
                                len(self._stack()), args))

    def track(self, name: str) -> int:
        """Get-or-create a **virtual track**: a synthetic tid labelled
        ``name`` in the export, for spans that belong to a logical
        entity (one request's timeline) rather than a thread.

        The table is bounded (``_MAX_TRACKS``, oldest evicted): request
        trace_ids arrive at traffic rate, and an unbounded name->tid
        map would leak exactly the way the span ring is bounded not
        to. An evicted track's already-recorded spans stay in the ring;
        only their name-metadata row ages out of the export."""
        with self._lock:
            tid = self._tracks.get(name)
            if tid is None:
                if len(self._tracks) >= self._MAX_TRACKS:
                    _, old_tid = self._tracks.popitem(last=False)
                    self._thread_names.pop(old_tid, None)
                tid = self._next_track
                self._next_track += 1
                self._tracks[name] = tid
                self._thread_names[tid] = name
            return tid

    def record_span(self, name: str, start: float, dur: float,
                    tid: Optional[int] = None,
                    args: Optional[Dict[str, Any]] = None,
                    flow: Optional[str] = None) -> None:
        """Record a span with explicit monotonic ``start``/``dur`` and
        an explicit (usually virtual) ``tid``. With ``flow``, the
        export links this span back to the *recording* thread's track
        via a Chrome-trace flow arrow — how a request track points at
        the dispatch-thread span that served it."""
        link = (flow, threading.get_ident()) if flow is not None else None
        self._record(SpanRecord(
            name, start, float(dur),
            threading.get_ident() if tid is None else tid, 0, args,
            link))

    # ------------------------------------------------------ reading
    def spans(self) -> List[SpanRecord]:
        """Snapshot of the ring buffer (oldest first)."""
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        """Drop every recorded span (open spans are unaffected)."""
        with self._lock:
            self._spans.clear()

    def set_capacity(self, capacity: int) -> None:
        """Re-bound the ring, keeping the newest recorded spans."""
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        with self._lock:
            self._spans = deque(self._spans, maxlen=capacity)
            self.capacity = capacity

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    # ------------------------------------------------------ export
    def chrome_trace_events(self) -> List[Dict[str, Any]]:
        """The trace-event list: one ``ph: "X"`` complete event per
        span (``ts``/``dur`` in µs on the shared monotonic clock) plus
        ``ph: "M"`` thread_name metadata so Perfetto labels tracks."""
        pid = os.getpid()
        with self._lock:
            spans = list(self._spans)
            names = dict(self._thread_names)
        events: List[Dict[str, Any]] = []
        for tid, tname in sorted(names.items()):
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": tname}})
        for s in spans:
            ev: Dict[str, Any] = {
                "ph": "X", "pid": pid, "tid": s.tid, "name": s.name,
                "cat": s.name.split("/")[0],
                "ts": round(s.ts * 1e6, 3),
                "dur": round(s.dur * 1e6, 3),
            }
            if s.args:
                ev["args"] = {k: _jsonable(v) for k, v in s.args.items()}
            events.append(ev)
            if s.flow is not None:
                # flow arrow: start ("s") on the recording thread's
                # track, finish ("f", bind-enclosing) on the span's own
                # (virtual) track — Perfetto draws the link between
                # the dispatch thread and the request timeline
                flow_id, src_tid = s.flow
                ts = round(s.ts * 1e6, 3)
                events.append({"ph": "s", "id": str(flow_id),
                               "pid": pid, "tid": src_tid, "ts": ts,
                               "name": "request", "cat": "request"})
                events.append({"ph": "f", "bp": "e", "id": str(flow_id),
                               "pid": pid, "tid": s.tid, "ts": ts,
                               "name": "request", "cat": "request"})
        return events

    def export_chrome_trace(self, path: str) -> int:
        """Write ``{"traceEvents": [...]}`` JSON loadable in Perfetto /
        ``chrome://tracing``; returns the number of span events."""
        events = self.chrome_trace_events()
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "displayTimeUnit": "ms"}, f)
        return sum(1 for e in events if e["ph"] == "X")


def _jsonable(v):
    """Span args must serialize: keep JSON natives, stringify the rest
    (a jax array in span args must not break the export)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    try:
        return float(v)
    except (TypeError, ValueError):
        return str(v)
