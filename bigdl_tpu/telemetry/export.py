"""Exporters: one ``MetricsRegistry.snapshot()`` rendered three ways.

- :class:`TensorBoardExporter` — scalars through the existing
  ``visualization.tensorboard.FileWriter`` (the same event files
  training curves live in; ``FileReader.read_scalar`` reads them back).
- :func:`write_prometheus` / :func:`parse_prometheus_text` — the
  Prometheus text exposition format as a file (node-exporter textfile
  style), with proper label escaping; the parser exists so tests
  round-trip it and ``tools.diagnose`` can ingest it.
- :class:`JsonlExporter` / :func:`read_jsonl` — append-only JSONL
  snapshots (one self-contained JSON object per line) for offline
  trajectory analysis; ``tools/perf``, ``tools/ceiling`` and
  ``bench.py`` emit these behind a flag so BENCH runs carry phase
  breakdowns, not just totals.

All three render the SAME snapshot rows, so counter totals agree
across exporters by construction (asserted in tests).
"""
from __future__ import annotations

import json
import re
import time
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.telemetry.metrics import MetricsRegistry

__all__ = ["TensorBoardExporter", "JsonlExporter", "write_prometheus",
           "prometheus_text", "parse_prometheus_text", "read_jsonl",
           "scalarize"]


def scalarize(snapshot: List[dict]) -> Dict[str, float]:
    """Flatten snapshot rows to ``{tag: value}`` scalars.

    Tags are ``name[label=value,...]`` for labelled series (labels
    sorted), bare ``name`` otherwise; histograms emit ``.count``,
    ``.sum`` and percentile sub-tags. Every exporter and the diagnose
    report read THIS flattening, so the three outputs can never
    disagree on a value."""
    out: Dict[str, float] = {}
    for row in snapshot:
        for s in row["series"]:
            labels = s.get("labels") or {}
            tag = row["name"]
            if labels:
                inner = ",".join(f"{k}={v}"
                                 for k, v in sorted(labels.items()))
                tag = f"{tag}[{inner}]"
            if row["kind"] == "histogram":
                out[f"{tag}.count"] = float(s["count"])
                out[f"{tag}.sum"] = float(s["sum"])
                for k, v in s.items():
                    if k.startswith("p") and k[1:].isdigit():
                        out[f"{tag}.{k}"] = float(v)
            else:
                out[tag] = float(s["value"])
    return out


class TensorBoardExporter:
    """Write registry snapshots as TensorBoard scalars.

    One ``export(step)`` call per cadence point; tags are the
    ``scalarize`` flattening (slashes render as TensorBoard groups, so
    ``serving/batcher/requests`` lands in a ``serving`` card next to
    the training curves). Reuses ``visualization.tensorboard
    .FileWriter`` — same wire format, readable back via
    ``FileReader.read_scalar``."""

    def __init__(self, registry: MetricsRegistry, log_dir: str):
        from bigdl_tpu.visualization.tensorboard import FileWriter
        self.registry = registry
        self.log_dir = log_dir
        self.writer = FileWriter(log_dir)

    def export(self, step: int) -> int:
        """Write the current snapshot at ``step``; returns scalar
        count."""
        scalars = scalarize(self.registry.snapshot())
        for tag, value in scalars.items():
            self.writer.add_scalar(tag, value, step)
        return len(scalars)

    def flush(self) -> None:
        """Block until exported events are on disk."""
        self.writer.flush()

    def close(self) -> None:
        """Flush and stop the writer thread."""
        self.writer.close()


# ------------------------------------------------------------- Prometheus

def _prom_name(name: str, suffix: str = "") -> str:
    # family/component/metric -> family_component_metric
    return name.replace("/", "_").replace("-", "_") + suffix


def _prom_escape(value: str) -> str:
    return (value.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _prom_unescape(value: str) -> str:
    out, i = [], 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ('"', "\\"):
                out.append(nxt)
            else:
                out.append(c + nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _prom_labels(labels: Dict[str, str],
                 extra: Optional[List[Tuple[str, str]]] = None) -> str:
    pairs = [(k, str(v)) for k, v in sorted(labels.items())]
    pairs += extra or []
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_prom_escape(v)}"' for k, v in pairs)
    return "{" + inner + "}"


def prometheus_text(snapshot: List[dict]) -> str:
    """Render a snapshot in the Prometheus text exposition format.

    Counters/gauges map directly; histograms export as summaries
    (``{quantile="0.5"}`` series plus ``_sum``/``_count``), which is
    what the percentile reservoir actually holds."""
    lines: List[str] = []
    for row in snapshot:
        name = _prom_name(row["name"])
        ptype = {"counter": "counter", "gauge": "gauge",
                 "histogram": "summary"}[row["kind"]]
        if row["description"]:
            lines.append(f"# HELP {name} "
                         f"{_prom_escape(row['description'])}")
        lines.append(f"# TYPE {name} {ptype}")
        for s in row["series"]:
            labels = s.get("labels") or {}
            if row["kind"] == "histogram":
                for k, v in sorted(s.items()):
                    if k.startswith("p") and k[1:].isdigit():
                        q = str(int(k[1:]) / 100.0)
                        lines.append(
                            f"{name}"
                            f"{_prom_labels(labels, [('quantile', q)])}"
                            f" {_fmt(v)}")
                lines.append(f"{name}_sum{_prom_labels(labels)} "
                             f"{_fmt(s['sum'])}")
                lines.append(f"{name}_count{_prom_labels(labels)} "
                             f"{_fmt(s['count'])}")
            else:
                lines.append(f"{name}{_prom_labels(labels)} "
                             f"{_fmt(s['value'])}")
    return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"  # prometheus text legally carries NaN/±Inf
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(int(f)) if f == int(f) else repr(f)


def write_prometheus(registry: MetricsRegistry, path: str) -> str:
    """Write the registry's current snapshot as a Prometheus text file
    (atomic replace — a scraper never reads a half-written file);
    returns the rendered text."""
    import os
    text = prometheus_text(registry.snapshot())
    tmp = path + ".part"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return text


_PROM_SERIES = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
_PROM_LABEL = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(text: str) -> Dict[Tuple, float]:
    """Parse exposition text back to ``{(name, ((label, value), ...)):
    value}`` — the round-trip half the escaping tests (and diagnose
    ingestion) rely on."""
    out: Dict[Tuple, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_SERIES.match(line)
        if not m:
            raise ValueError(f"unparseable prometheus line: {line!r}")
        name, raw_labels, value = m.groups()
        labels = tuple(sorted(
            (k, _prom_unescape(v))
            for k, v in _PROM_LABEL.findall(raw_labels or "")))
        out[(name, labels)] = float(value)
    return out


# ------------------------------------------------------------------ JSONL

class JsonlExporter:
    """Append-only JSONL snapshots: one self-contained JSON object per
    ``export()`` call (wall time, optional step/run metadata, full
    snapshot rows). Files append across runs so a BENCH trajectory
    accumulates one line per run."""

    def __init__(self, registry: MetricsRegistry, path: str):
        self.registry = registry
        self.path = path

    def export(self, step: Optional[int] = None,
               meta: Optional[dict] = None) -> dict:
        """Append one snapshot line; returns the record written."""
        rec = {"wall_time": time.time(), "step": step,
               "meta": meta or {},
               "metrics": self.registry.snapshot()}
        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")
        return rec


def read_jsonl(path: str) -> List[dict]:
    """Read every snapshot record from a JSONL metrics file."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
