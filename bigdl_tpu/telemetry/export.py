"""Exporters: one ``MetricsRegistry.snapshot()`` rendered three ways.

- :class:`TensorBoardExporter` — scalars through the existing
  ``visualization.tensorboard.FileWriter`` (the same event files
  training curves live in; ``FileReader.read_scalar`` reads them back).
- :func:`write_prometheus` / :func:`parse_prometheus_text` — the
  Prometheus text exposition format as a file (node-exporter textfile
  style), with proper label escaping; the parser exists so tests
  round-trip it and ``tools.diagnose`` can ingest it.
- :class:`JsonlExporter` / :func:`read_jsonl` — append-only JSONL
  snapshots (one self-contained JSON object per line) for offline
  trajectory analysis; ``tools/perf``, ``tools/ceiling`` and
  ``bench.py`` emit these behind a flag so BENCH runs carry phase
  breakdowns, not just totals.

All three render the SAME snapshot rows, so counter totals agree
across exporters by construction (asserted in tests).
"""
from __future__ import annotations

import json
import os
import re
import time
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.telemetry.metrics import MetricsRegistry

__all__ = ["TensorBoardExporter", "JsonlExporter", "write_prometheus",
           "prometheus_text", "parse_prometheus_text", "read_jsonl",
           "read_jsonl_with_identity", "process_identity", "scalarize",
           "SNAPSHOT_HEADER_FORMAT"]

#: schema tag of the process-identity header line new JSONL snapshot
#: files start with (``{"header": SNAPSHOT_HEADER_FORMAT, ...}``);
#: headerless pre-header files still parse (back-compat).
SNAPSHOT_HEADER_FORMAT = "bigdl-snap-1"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def process_identity(**overrides) -> dict:
    """This process's identity stamp for cross-process telemetry: host
    (gang process index from ``JAX_PROCESS_ID``), process_count
    (``JAX_NUM_PROCESSES``), replica id (``BIGDL_REPLICA_ID``, set by
    fleet ProcessReplica parents) and pid. ``overrides`` replace any
    field; ``telemetry.agg`` keys merged gauge series off this."""
    ident = {
        "pid": os.getpid(),
        "host": _env_int("JAX_PROCESS_ID", 0),
        "process_count": _env_int("JAX_NUM_PROCESSES", 1),
        "replica": os.environ.get("BIGDL_REPLICA_ID") or None,
    }
    ident.update(overrides)
    return ident


def scalarize(snapshot: List[dict]) -> Dict[str, float]:
    """Flatten snapshot rows to ``{tag: value}`` scalars.

    Tags are ``name[label=value,...]`` for labelled series (labels
    sorted), bare ``name`` otherwise; histograms emit ``.count``,
    ``.sum`` and percentile sub-tags. Every exporter and the diagnose
    report read THIS flattening, so the three outputs can never
    disagree on a value."""
    out: Dict[str, float] = {}
    for row in snapshot:
        for s in row["series"]:
            labels = s.get("labels") or {}
            tag = row["name"]
            if labels:
                inner = ",".join(f"{k}={v}"
                                 for k, v in sorted(labels.items()))
                tag = f"{tag}[{inner}]"
            if row["kind"] == "histogram":
                out[f"{tag}.count"] = float(s["count"])
                out[f"{tag}.sum"] = float(s["sum"])
                for k, v in s.items():
                    if k.startswith("p") and k[1:].isdigit():
                        out[f"{tag}.{k}"] = float(v)
            else:
                out[tag] = float(s["value"])
    return out


class TensorBoardExporter:
    """Write registry snapshots as TensorBoard scalars.

    One ``export(step)`` call per cadence point; tags are the
    ``scalarize`` flattening (slashes render as TensorBoard groups, so
    ``serving/batcher/requests`` lands in a ``serving`` card next to
    the training curves). Reuses ``visualization.tensorboard
    .FileWriter`` — same wire format, readable back via
    ``FileReader.read_scalar``."""

    def __init__(self, registry: MetricsRegistry, log_dir: str):
        from bigdl_tpu.visualization.tensorboard import FileWriter
        self.registry = registry
        self.log_dir = log_dir
        self.writer = FileWriter(log_dir)

    def export(self, step: int) -> int:
        """Write the current snapshot at ``step``; returns scalar
        count."""
        scalars = scalarize(self.registry.snapshot())
        for tag, value in scalars.items():
            self.writer.add_scalar(tag, value, step)
        return len(scalars)

    def flush(self) -> None:
        """Block until exported events are on disk."""
        self.writer.flush()

    def close(self) -> None:
        """Flush and stop the writer thread."""
        self.writer.close()


# ------------------------------------------------------------- Prometheus

def _prom_name(name: str, suffix: str = "") -> str:
    # family/component/metric -> family_component_metric
    return name.replace("/", "_").replace("-", "_") + suffix


def _prom_escape(value: str) -> str:
    return (value.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _prom_unescape(value: str) -> str:
    out, i = [], 0
    while i < len(value):
        c = value[i]
        if c == "\\" and i + 1 < len(value):
            nxt = value[i + 1]
            if nxt == "n":
                out.append("\n")
            elif nxt in ('"', "\\"):
                out.append(nxt)
            else:
                out.append(c + nxt)
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _prom_labels(labels: Dict[str, str],
                 extra: Optional[List[Tuple[str, str]]] = None) -> str:
    pairs = [(k, str(v)) for k, v in sorted(labels.items())]
    pairs += extra or []
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_prom_escape(v)}"' for k, v in pairs)
    return "{" + inner + "}"


def prometheus_text(snapshot: List[dict]) -> str:
    """Render a snapshot in the Prometheus text exposition format.

    Counters/gauges map directly; histograms export as summaries
    (``{quantile="0.5"}`` series plus ``_sum``/``_count``), which is
    what the percentile reservoir actually holds."""
    lines: List[str] = []
    for row in snapshot:
        name = _prom_name(row["name"])
        ptype = {"counter": "counter", "gauge": "gauge",
                 "histogram": "summary"}[row["kind"]]
        if row["description"]:
            lines.append(f"# HELP {name} "
                         f"{_prom_escape(row['description'])}")
        lines.append(f"# TYPE {name} {ptype}")
        for s in row["series"]:
            labels = s.get("labels") or {}
            if row["kind"] == "histogram":
                for k, v in sorted(s.items()):
                    if k.startswith("p") and k[1:].isdigit():
                        q = str(int(k[1:]) / 100.0)
                        lines.append(
                            f"{name}"
                            f"{_prom_labels(labels, [('quantile', q)])}"
                            f" {_fmt(v)}")
                lines.append(f"{name}_sum{_prom_labels(labels)} "
                             f"{_fmt(s['sum'])}")
                lines.append(f"{name}_count{_prom_labels(labels)} "
                             f"{_fmt(s['count'])}")
            else:
                lines.append(f"{name}{_prom_labels(labels)} "
                             f"{_fmt(s['value'])}")
    return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"  # prometheus text legally carries NaN/±Inf
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(int(f)) if f == int(f) else repr(f)


def write_prometheus(registry: MetricsRegistry, path: str) -> str:
    """Write the registry's current snapshot as a Prometheus text file
    (atomic replace — a scraper never reads a half-written file);
    returns the rendered text."""
    import os
    text = prometheus_text(registry.snapshot())
    tmp = path + ".part"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return text


_PROM_SERIES = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$')
_PROM_LABEL = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus_text(text: str) -> Dict[Tuple, float]:
    """Parse exposition text back to ``{(name, ((label, value), ...)):
    value}`` — the round-trip half the escaping tests (and diagnose
    ingestion) rely on."""
    out: Dict[Tuple, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _PROM_SERIES.match(line)
        if not m:
            raise ValueError(f"unparseable prometheus line: {line!r}")
        name, raw_labels, value = m.groups()
        labels = tuple(sorted(
            (k, _prom_unescape(v))
            for k, v in _PROM_LABEL.findall(raw_labels or "")))
        out[(name, labels)] = float(value)
    return out


# ------------------------------------------------------------------ JSONL

class JsonlExporter:
    """Append-only JSONL snapshots: one self-contained JSON object per
    ``export()`` call (wall time, optional step/run metadata, full
    snapshot rows). Files append across runs so a BENCH trajectory
    accumulates one line per run.

    A new (absent or empty) file starts with a process-identity header
    line (``SNAPSHOT_HEADER_FORMAT``) so ``telemetry.agg`` can merge
    snapshots from many processes; ``read_jsonl`` skips it, so
    pre-header readers and files interoperate both ways.
    ``include_samples=True`` ships each histogram series' raw reservoir
    — required for exact cross-process percentile merging."""

    def __init__(self, registry: MetricsRegistry, path: str,
                 identity: Optional[dict] = None,
                 include_samples: bool = False):
        self.registry = registry
        self.path = path
        self.identity = identity if identity is not None \
            else process_identity()
        self.include_samples = include_samples

    def _header_needed(self) -> bool:
        try:
            return os.path.getsize(self.path) == 0
        except OSError:
            return True

    def export(self, step: Optional[int] = None,
               meta: Optional[dict] = None) -> dict:
        """Append one snapshot line; returns the record written."""
        rec = {"wall_time": time.time(), "step": step,
               "meta": meta or {},
               "metrics": self.registry.snapshot(self.include_samples)}
        header = None
        if self._header_needed():
            header = {"header": SNAPSHOT_HEADER_FORMAT, "schema": 1,
                      "identity": self.identity}
        with open(self.path, "a") as f:
            if header is not None:
                f.write(json.dumps(header) + "\n")
            f.write(json.dumps(rec) + "\n")
        return rec


def _is_header(rec: dict) -> bool:
    return isinstance(rec, dict) and isinstance(rec.get("header"), str)


def read_jsonl(path: str) -> List[dict]:
    """Read every snapshot record from a JSONL metrics file
    (process-identity header lines are skipped, so headered and
    pre-header files read identically)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rec = json.loads(line)
                if not _is_header(rec):
                    out.append(rec)
    return out


def read_jsonl_with_identity(path: str, tolerant: bool = False
                             ) -> Tuple[Optional[dict], List[dict]]:
    """``(identity, records)`` from a JSONL metrics file: the header's
    identity dict (None for pre-header files) plus every snapshot
    record. ``tolerant=True`` skips undecodable lines instead of
    raising — a process SIGKILLed mid-append leaves a torn final line,
    and the postmortem reader must still recover the rest."""
    identity: Optional[dict] = None
    out: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                if tolerant:
                    continue
                raise
            if _is_header(rec):
                if identity is None:
                    identity = rec.get("identity") or {}
            else:
                out.append(rec)
    return identity, out
