"""bigdl_tpu.telemetry — unified host-side observability.

One subsystem answers "where did this step's wall-clock go": a
**span tracer** (nested, thread-aware spans into a bounded ring buffer,
exportable as Chrome trace-event JSON for Perfetto/``chrome://tracing``)
plus a **metrics registry** (named Counter/Gauge/Histogram instruments
with labels, exportable as TensorBoard scalars, Prometheus text, or
JSONL snapshots). The optimizer's step phases, the dataset prefetcher,
the serving batcher/compile-cache, checkpoints and the ``parallel/``
collective boundaries all report through it; ``python -m
bigdl_tpu.tools.diagnose`` renders the where-did-the-time-go report.

Usage::

    from bigdl_tpu import telemetry

    telemetry.enable()                      # or BIGDL_TELEMETRY=1
    with telemetry.span("optimizer/step", step=i):
        ...
    telemetry.export_chrome_trace("trace.json")   # load in Perfetto

    reqs = telemetry.counter("serving/batcher/requests", "...")
    reqs.inc(model="lenet")

**Disabled is the default and costs almost nothing**: ``span()`` checks
one module flag and returns a shared no-op context manager — no clock
read, no allocation, no background thread, no file (a micro-benchmark
test asserts the bound). Instruments are always live (they are plain
counters; serving's public stats depend on them) but create no threads
or files either — only explicitly constructed exporters touch disk.

Telemetry is **host-side only**: a ``span``/``inc`` inside jit/grad/
scan-traced code would run once at trace time and then lie forever; the
``telemetry-in-trace`` lint rule (``python -m bigdl_tpu.tools.check``)
flags exactly that.
"""
from __future__ import annotations

import os
from typing import Optional

from bigdl_tpu.telemetry.export import (SNAPSHOT_HEADER_FORMAT,
                                        JsonlExporter, TensorBoardExporter,
                                        parse_prometheus_text,
                                        process_identity, prometheus_text,
                                        read_jsonl, read_jsonl_with_identity,
                                        scalarize, write_prometheus)
from bigdl_tpu.telemetry.metrics import (NAME_RE, Counter, Gauge, Histogram,
                                         MetricsRegistry, audit_names)
from bigdl_tpu.telemetry.tracer import NOOP_SPAN, SpanRecord, SpanTracer

__all__ = [
    "span", "record", "enable", "disable", "enabled", "tracer",
    "export_chrome_trace", "registry", "counter", "gauge", "histogram",
    "snapshot_to_jsonl",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "SpanTracer",
    "SpanRecord", "TensorBoardExporter", "JsonlExporter",
    "write_prometheus", "prometheus_text", "parse_prometheus_text",
    "read_jsonl", "read_jsonl_with_identity", "process_identity",
    "SNAPSHOT_HEADER_FORMAT", "scalarize", "audit_names", "NAME_RE",
]

# -- the process-wide tracer ---------------------------------------------
# _ENABLED is the ONE flag the span() fast path reads; the tracer object
# itself is created lazily so a disabled process allocates nothing.
_ENABLED = False
_TRACER: Optional[SpanTracer] = None

# -- the process-wide default metrics registry ---------------------------
_REGISTRY = MetricsRegistry()


def enabled() -> bool:
    """Whether span tracing is currently on."""
    return _ENABLED


def enable(capacity: Optional[int] = None) -> SpanTracer:
    """Turn span tracing on (idempotent); returns the tracer.

    An explicit ``capacity`` re-bounds the ring (keeping the newest
    spans) even when the tracer already exists — a memory-bounding
    request must not be silently dropped just because ``tracer()`` was
    touched first; omitted, the existing buffer (default 65536) is
    kept."""
    global _ENABLED, _TRACER
    if _TRACER is None:
        _TRACER = SpanTracer(capacity if capacity is not None else 65536)
    elif capacity is not None and capacity != _TRACER.capacity:
        _TRACER.set_capacity(capacity)
    _ENABLED = True
    return _TRACER


def disable() -> None:
    """Turn span tracing off; recorded spans stay readable via
    ``tracer()`` until ``enable()`` is called again or they rotate
    out of the ring."""
    global _ENABLED
    _ENABLED = False


def tracer() -> SpanTracer:
    """The process tracer (created on first use, even if disabled —
    lets tests inspect an empty buffer)."""
    global _TRACER
    if _TRACER is None:
        _TRACER = SpanTracer()
    return _TRACER


def span(name: str, **args):
    """Measure the enclosed block as one named span.

    Disabled fast path: one flag check, then a shared no-op context
    manager — safe to leave in production hot loops."""
    if not _ENABLED:
        return NOOP_SPAN
    return _TRACER.span(name, args or None)


def record(name: str, duration_s: float, **args) -> None:
    """Log a pre-measured interval ending now (no-op when disabled).

    This is how the optimizer ships its exact ``t_data``/``t_compute``
    numbers into the trace, so trace phase sums and
    ``Metrics.summary()`` agree to the digit."""
    if not _ENABLED:
        return
    _TRACER.record(name, duration_s, args or None)


def export_chrome_trace(path: str) -> int:
    """Write the tracer's ring buffer as Chrome trace-event JSON
    (Perfetto / ``chrome://tracing``); returns the span-event count."""
    return tracer().export_chrome_trace(path)


def registry() -> MetricsRegistry:
    """The process-wide default metrics registry (training/data paths
    report here; an ``InferenceService`` holds its own so concurrent
    services don't mix counts)."""
    return _REGISTRY


def counter(name: str, description: str = "") -> Counter:
    """Get-or-create a Counter in the default registry."""
    return _REGISTRY.counter(name, description)


def gauge(name: str, description: str = "") -> Gauge:
    """Get-or-create a Gauge in the default registry."""
    return _REGISTRY.gauge(name, description)


def histogram(name: str, description: str = "",
              reservoir_size: int = 2048) -> Histogram:
    """Get-or-create a Histogram in the default registry."""
    return _REGISTRY.histogram(name, description, reservoir_size)


def snapshot_to_jsonl(path: str, step: Optional[int] = None,
                      meta: Optional[dict] = None) -> dict:
    """Append one default-registry snapshot line to ``path`` — the
    one-call form ``tools/perf``, ``tools/ceiling`` and ``bench.py``
    use (flag / ``BIGDL_METRICS_JSONL``) so BENCH trajectories carry
    phase breakdowns; returns the record written."""
    return JsonlExporter(_REGISTRY, path).export(step=step, meta=meta)


# opt-in via environment, for instrumenting existing entry points
# without code changes (BIGDL_TELEMETRY=1 python -m bigdl_tpu.tools.perf)
if os.environ.get("BIGDL_TELEMETRY", "").strip() not in ("", "0"):
    enable()

# sibling subsystems, imported LAST so their module-level instrument
# registrations find counter()/registry() already defined:
# - telemetry.programs — XLA program profile registry (cost/memory
#   analysis, MFU math; BIGDL_PROGRAM_PROFILES=1 arms compile sites)
# - telemetry.flight — crash flight recorder (post-mortem bundles;
#   BIGDL_FLIGHT_DIR=/path arms it)
# - telemetry.agg — cross-process snapshot shipping + merging
#   (BIGDL_TELEMETRY_SHIP_DIR=/path arms the shipper)
# - telemetry.slo — declarative SLOs over merged snapshots
from bigdl_tpu.telemetry import agg, flight, programs, slo  # noqa: E402,F401

__all__ += ["agg", "flight", "programs", "slo"]
