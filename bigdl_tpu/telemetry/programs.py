"""Program profile registry: what each compiled XLA program costs.

The device-side half of observability. The span tracer and metrics
registry answer "where did the *host* wall-clock go"; this module
answers "which compiled program burned the FLOPs and what HBM it
holds": every program compiled through ``optim.build_train_step`` /
``build_eval_step``, the serving :class:`~bigdl_tpu.serving.
compile_cache.CompileCache` and the generation
:class:`~bigdl_tpu.generation.engine.DecodeEngine` can register its
``compiled.cost_analysis()`` FLOPs / bytes-accessed, its
``memory_analysis()`` HBM footprint (arguments / outputs / temps),
its compile time and its donation summary — and, combined with a
measured rate, its achieved TFLOP/s and MFU against the device peak.

Profiling is **opt-in** (``enable()`` or ``BIGDL_PROGRAM_PROFILES=1``)
because the compile-site hooks pay one extra ahead-of-time compile per
program to obtain the analyses; disabled (the default), every hook is
one module-flag check and the jitted callables pass through untouched.

This module is also the ONE home of the cost-analysis → MFU math that
``tools/ceiling`` pioneered — including the scan-body-counted-once
caveat (:func:`resolve_per_item_flops`): XLA's ``cost_analysis`` counts
a ``lax.scan`` body once, not times its trip count, on the backends we
measured, but that is backend/version-dependent, so the disambiguation
against a hand estimate lives HERE and ``tools/ceiling``,
``tools/perf`` and ``bench.py`` all consume it.

Profiles land as gauges (``train/program/*`` / ``serving/program/*``,
labelled ``program=<name>``) in the default telemetry registry, so the
TensorBoard / Prometheus / JSONL exporters and ``tools.diagnose``'s
"device:" section see them like any other series.
"""
from __future__ import annotations

import math
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = ["ProgramProfile", "ProgramRegistry", "registry", "enable",
           "disable", "enabled", "enable_checks", "disable_checks",
           "checks_enabled", "analyze_compiled",
           "resolve_per_item_flops", "mfu_fields", "record_rate",
           "maybe_wrap_jitted", "register_program_instruments",
           "DEVICE_TFS"]

#: MFU denominator: device peak TFLOP/s (v5e bf16 peak by default;
#: override with BIGDL_DEVICE_TFS — the same knob tools/ceiling and
#: tools/perf always honored)
DEVICE_TFS = float(os.environ.get("BIGDL_DEVICE_TFS", 197.0))

# the ONE flag the disabled compile-site hooks read (telemetry.span
# discipline: profiling off must cost a flag check, nothing else)
_ENABLED = False

#: gauge metrics each registered profile publishes, per family
_PROFILE_GAUGES = {
    "flops": "analytic FLOPs per program execution (cost_analysis)",
    "bytes_accessed": "analytic bytes accessed per execution",
    "hbm_bytes": "HBM footprint: arguments + outputs + temps bytes",
    "compile_s": "seconds to compile the program",
    "arithmetic_intensity": "analytic FLOPs / bytes accessed",
}
_RATE_GAUGES = {
    "achieved_tfs": "measured-rate x analytic-flops TFLOP/s",
    "mfu": "achieved TFLOP/s / device peak (BIGDL_DEVICE_TFS)",
}


def enabled() -> bool:
    """Whether program profiling (the extra AOT compile per program)
    is on."""
    return _ENABLED


def enable() -> None:
    """Turn program profiling on: compile sites built AFTER this call
    register cost/memory profiles (idempotent)."""
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    """Turn program profiling off; registered profiles stay
    readable."""
    global _ENABLED
    _ENABLED = False


# like _ENABLED: one flag the compile-site hooks read; on, every
# program compiled through maybe_wrap_jitted additionally runs the
# context-light static HLO checks (analysis.programs) and stores the
# findings on its profile — diagnose shows them per program and
# flight-recorder bundles ship them in programs.json
_CHECKS_ENABLED = False


def checks_enabled() -> bool:
    """Whether compile-site static HLO checks are on."""
    return _CHECKS_ENABLED


def enable_checks() -> None:
    """Run the static program checks (``bigdl_tpu.analysis``) at every
    profiled compile site; findings land on
    :attr:`ProgramProfile.checks` (idempotent; implies nothing about
    :func:`enabled` — profiles must also be on for sites to compile
    ahead of time)."""
    global _CHECKS_ENABLED
    _CHECKS_ENABLED = True


def disable_checks() -> None:
    """Turn compile-site checks off (profiles keep prior verdicts)."""
    global _CHECKS_ENABLED
    _CHECKS_ENABLED = False


def register_program_instruments(r) -> Dict[str, object]:
    """Get-or-create every ``*/program/*`` gauge in registry ``r`` —
    the profile registry's whole metric surface, factored out so
    ``tools.check --telemetry-audit`` audits the real registration
    calls."""
    out = {}
    for family in ("train", "serving"):
        for metric, desc in {**_PROFILE_GAUGES, **_RATE_GAUGES}.items():
            name = f"{family}/program/{metric}"
            out[name] = r.gauge(name, desc)
    return out


def analyze_compiled(compiled) -> Dict[str, float]:
    """Cost + memory analysis of an AOT-compiled program
    (``jax.jit(f).lower(...).compile()``), robust to backends that
    support neither: absent quantities report 0.0.

    Returns flops, bytes_accessed, arg/out/temp/alias bytes and their
    ``hbm_bytes`` total (arguments + outputs + temps — what the
    program pins while it runs)."""
    out = {"flops": 0.0, "bytes_accessed": 0.0, "arg_bytes": 0.0,
           "out_bytes": 0.0, "temp_bytes": 0.0, "alias_bytes": 0.0,
           "hbm_bytes": 0.0}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        if cost:
            out["flops"] = float(cost.get("flops", 0.0))
            out["bytes_accessed"] = float(cost.get("bytes accessed", 0.0))
    except Exception:
        pass
    try:
        mem = compiled.memory_analysis()
        out["arg_bytes"] = float(mem.argument_size_in_bytes)
        out["out_bytes"] = float(mem.output_size_in_bytes)
        out["temp_bytes"] = float(mem.temp_size_in_bytes)
        out["alias_bytes"] = float(mem.alias_size_in_bytes)
        out["hbm_bytes"] = (out["arg_bytes"] + out["out_bytes"]
                            + out["temp_bytes"])
    except Exception:
        pass
    return out


def resolve_per_item_flops(flops_per_call: float, items_per_call: float,
                           scan_length: int = 1,
                           per_item_estimate: Optional[float] = None
                           ) -> float:
    """Per-item FLOPs from a compiled call's analytic total — THE home
    of the scan-body caveat.

    XLA's ``cost_analysis`` counts a ``lax.scan`` body once, not times
    its trip count (verified on this backend) — but that is backend/
    version-dependent, so when the caller supplies a hand-computed
    ``per_item_estimate`` we pick the interpretation (body-once vs
    body x ``scan_length``) closest to it, and fall back to the
    estimate outright when neither is within 4x (a silently-wrong
    convention would inflate MFU by ``scan_length`` x)."""
    per_item = flops_per_call / items_per_call  # body counted once
    if per_item_estimate:
        cands = (per_item,
                 flops_per_call / (items_per_call * scan_length))
        per_item = min(cands, key=lambda c:
                       abs(math.log(c / per_item_estimate)))
        if not 0.25 < per_item / per_item_estimate < 4.0:
            per_item = per_item_estimate
    return per_item


def mfu_fields(rate_per_sec: float, *, flops_per_call: float = None,
               items_per_call: float = 1.0, scan_length: int = 1,
               per_item_estimate: Optional[float] = None,
               peak_tfs: Optional[float] = None) -> Dict[str, float]:
    """``{achieved_tfs, mfu_vs_peak, peak_tfs}`` from a measured item
    rate and the compiled call's analytic FLOPs (fallback: the
    caller-supplied per-item estimate) — byte-compatible with the
    fields ``tools/ceiling`` always printed; empty when neither FLOPs
    source is available."""
    peak = DEVICE_TFS if peak_tfs is None else peak_tfs
    if flops_per_call is not None and flops_per_call > 0:
        per_item = resolve_per_item_flops(
            flops_per_call, items_per_call, scan_length,
            per_item_estimate)
        tfs = per_item * rate_per_sec / 1e12
    elif per_item_estimate:
        tfs = per_item_estimate * rate_per_sec / 1e12
    else:
        return {}
    return {"achieved_tfs": round(tfs, 2),
            "mfu_vs_peak": round(tfs / peak, 3),
            "peak_tfs": peak}


class ProgramProfile:
    """One compiled program's registered profile: analytic cost
    (FLOPs, bytes accessed), HBM footprint (argument/output/temp
    bytes), compile time, scan length and donation summary — plus the
    measured-rate derived ``achieved_tfs`` / ``mfu`` once
    :meth:`ProgramRegistry.record_rate` has seen a rate."""

    __slots__ = ("name", "kind", "flops", "bytes_accessed", "arg_bytes",
                 "out_bytes", "temp_bytes", "alias_bytes", "hbm_bytes",
                 "compile_s", "scan_length", "items_per_call",
                 "donation", "kernel", "extra", "rate_items_per_s",
                 "achieved_tfs", "mfu", "checks")

    def __init__(self, name: str, kind: str, analysis: Dict[str, float],
                 compile_s: float, scan_length: int = 1,
                 items_per_call: Optional[float] = None,
                 donation: str = "", extra: Optional[dict] = None,
                 kernel: Optional[str] = None):
        self.name = name
        self.kind = kind  # "train" | "serving" — the gauge family
        #: which kernel path built this program: "pallas" |
        #: "reference" | None (None = registered with kernels off and
        #: no explicit label — the pre-kernel series identity)
        self.kernel = kernel
        self.flops = analysis.get("flops", 0.0)
        self.bytes_accessed = analysis.get("bytes_accessed", 0.0)
        self.arg_bytes = analysis.get("arg_bytes", 0.0)
        self.out_bytes = analysis.get("out_bytes", 0.0)
        self.temp_bytes = analysis.get("temp_bytes", 0.0)
        self.alias_bytes = analysis.get("alias_bytes", 0.0)
        self.hbm_bytes = analysis.get("hbm_bytes", 0.0)
        self.compile_s = compile_s
        self.scan_length = scan_length
        self.items_per_call = items_per_call
        self.donation = donation
        self.extra = dict(extra or {})
        self.rate_items_per_s: Optional[float] = None
        self.achieved_tfs: Optional[float] = None
        self.mfu: Optional[float] = None
        #: static HLO check verdict (None until a verifier ran):
        #: {"clean": bool, "findings": [finding dicts]} — the payload
        #: diagnose renders per program and programs.json bundles ship
        self.checks: Optional[dict] = None

    def to_dict(self) -> dict:
        """JSON-ready dump (the ``programs.json`` bundle format and
        ``diagnose --json``'s device rows)."""
        return {s: getattr(self, s) for s in self.__slots__}

    def __repr__(self) -> str:
        return (f"ProgramProfile({self.name!r} kind={self.kind} "
                f"flops={self.flops:.3g} hbm={self.hbm_bytes:.3g}B "
                f"compile={self.compile_s:.3f}s)")


class ProgramRegistry:
    """Named :class:`ProgramProfile` store publishing
    ``<kind>/program/*`` gauges (labelled ``program=<name>``) into a
    telemetry metrics registry (default: the process-wide one)."""

    def __init__(self, metrics=None):
        self._lock = threading.Lock()
        self._profiles: Dict[str, ProgramProfile] = {}
        self._metrics = metrics

    def _registry(self):
        if self._metrics is not None:
            return self._metrics
        import bigdl_tpu.telemetry as telemetry
        return telemetry.registry()

    def register(self, name: str, kind: str, *, compiled=None,
                 analysis: Optional[Dict[str, float]] = None,
                 compile_s: float = 0.0, scan_length: int = 1,
                 items_per_call: Optional[float] = None,
                 donation: str = "", extra: Optional[dict] = None,
                 kernel: Optional[str] = None) -> ProgramProfile:
        """Register (or replace) one program's profile from either an
        AOT ``compiled`` object (analyzed here) or a pre-computed
        ``analysis`` dict; publishes the profile gauges and returns
        the profile.

        ``kernel`` labels which kernel path built the program —
        ``"pallas"`` | ``"reference"`` (``bigdl_tpu.kernels``). The
        wrapped compile sites (:func:`maybe_wrap_jitted`) set it from
        trace EVIDENCE — whether the program's trace actually routed
        through a pallas kernel — never from the global config, so a
        program with no kernel-eligible ops stays unlabeled even on a
        kernels-on backend and the pre-kernel gauge series identity
        never churns. Gauge series carry the extra ``kernel=`` label
        whenever the value is set, so MFU/HBM gauges compare the two
        paths side by side (bench's KERNELS row passes
        ``kernel="reference"`` explicitly for its off-legs)."""
        if kind not in ("train", "serving"):
            raise ValueError(f"kind must be train|serving, got {kind!r}")
        if analysis is None:
            analysis = analyze_compiled(compiled) if compiled is not None \
                else {}
        prof = ProgramProfile(name, kind, analysis, compile_s,
                              scan_length, items_per_call, donation,
                              extra, kernel)
        with self._lock:
            self._profiles[name] = prof
        r = self._registry()
        labels = {"program": name}
        if kernel is not None:
            labels["kernel"] = kernel
        r.gauge(f"{kind}/program/flops",
                _PROFILE_GAUGES["flops"]).set(prof.flops, **labels)
        r.gauge(f"{kind}/program/bytes_accessed",
                _PROFILE_GAUGES["bytes_accessed"]).set(
            prof.bytes_accessed, **labels)
        r.gauge(f"{kind}/program/hbm_bytes",
                _PROFILE_GAUGES["hbm_bytes"]).set(prof.hbm_bytes,
                                                  **labels)
        r.gauge(f"{kind}/program/compile_s",
                _PROFILE_GAUGES["compile_s"]).set(prof.compile_s,
                                                  **labels)
        if prof.bytes_accessed > 0:
            r.gauge(f"{kind}/program/arithmetic_intensity",
                    _PROFILE_GAUGES["arithmetic_intensity"]).set(
                prof.flops / prof.bytes_accessed, **labels)
        return prof

    def record_rate(self, name: str, items_per_s: float,
                    peak_tfs: Optional[float] = None
                    ) -> Optional[ProgramProfile]:
        """Combine a measured item rate with the registered analytic
        FLOPs into ``achieved_tfs`` / ``mfu`` gauges. Items are the
        profile's own unit (rows, images, tokens — whatever
        ``items_per_call`` counted); unknown names are a no-op so
        callers need not care whether profiling was on."""
        with self._lock:
            prof = self._profiles.get(name)
        if prof is None or items_per_s <= 0:
            return None
        if not prof.flops > 0:
            return prof
        # unrounded, unlike the display-precision mfu_fields dict —
        # a gauge must not flatten a small-but-real MFU to 0
        per_item = resolve_per_item_flops(
            prof.flops, prof.items_per_call or 1.0, prof.scan_length)
        peak = DEVICE_TFS if peak_tfs is None else peak_tfs
        prof.rate_items_per_s = items_per_s
        prof.achieved_tfs = per_item * items_per_s / 1e12
        prof.mfu = prof.achieved_tfs / peak
        r = self._registry()
        labels = {"program": name}
        if prof.kernel is not None:
            labels["kernel"] = prof.kernel
        r.gauge(f"{prof.kind}/program/achieved_tfs",
                _RATE_GAUGES["achieved_tfs"]).set(prof.achieved_tfs,
                                                  **labels)
        r.gauge(f"{prof.kind}/program/mfu",
                _RATE_GAUGES["mfu"]).set(prof.mfu, **labels)
        return prof

    def attach_checks(self, name: str, findings) -> None:
        """Record a static-verification verdict on profile ``name``
        (no-op for unknown names): ``findings`` is a list of finding
        dicts (``analysis.hlo.ProgramFinding.to_dict``); the verdict
        counts only unsuppressed ones as dirty. Shared surface:
        ``tools.diagnose`` prints it next to the MFU/HBM rows and
        flight-recorder ``programs.json`` bundles carry it into
        ``--postmortem``."""
        rows = [f if isinstance(f, dict) else f.to_dict()
                for f in (findings or [])]
        verdict = {"clean": not any(not r.get("suppressed")
                                    for r in rows),
                   "findings": rows}
        with self._lock:
            prof = self._profiles.get(name)
            if prof is not None:
                prof.checks = verdict

    def get(self, name: str) -> Optional[ProgramProfile]:
        """The profile registered under ``name``, or None."""
        with self._lock:
            return self._profiles.get(name)

    def profiles(self) -> List[ProgramProfile]:
        """Every registered profile, sorted by name."""
        with self._lock:
            return [self._profiles[n] for n in sorted(self._profiles)]

    def clear(self) -> None:
        """Drop every registered profile (gauge series persist in the
        metrics registry — they are history, not state)."""
        with self._lock:
            self._profiles.clear()

    def to_dict(self) -> List[dict]:
        """JSON-ready list of every profile (the flight-recorder
        ``programs.json`` payload)."""
        return [p.to_dict() for p in self.profiles()]


_REGISTRY = ProgramRegistry()


def registry() -> ProgramRegistry:
    """The process-wide program profile registry."""
    return _REGISTRY


def record_rate(name: str, items_per_s: float,
                peak_tfs: Optional[float] = None):
    """Record a measured rate against the default registry's profile
    ``name`` (no-op for unknown names)."""
    return _REGISTRY.record_rate(name, items_per_s, peak_tfs)


def _has_tracer(leaves) -> bool:
    import jax

    return any(isinstance(leaf, jax.core.Tracer) for leaf in leaves)


def _signature(leaves) -> tuple:
    return tuple(
        (tuple(getattr(leaf, "shape", ())),
         str(getattr(leaf, "dtype", type(leaf).__name__)))
        for leaf in leaves)


class _ProfiledProgram:
    """The enabled-path wrapper ``maybe_wrap_jitted`` returns: on the
    first call per argument signature it compiles the wrapped jit
    ahead of time (timing the compile), registers the program's
    profile, and executes the compiled object from then on. Attribute
    access (``.lower``, ``.trace``) delegates to the wrapped jit, so
    AOT-consuming callers keep working."""

    def __init__(self, name: str, kind: str, jitted, *, donation: str,
                 scan_length_for: Optional[Callable] = None,
                 items_for: Optional[Callable] = None,
                 auto_rate: bool = False, prog_registry=None):
        self._name = name
        self._kind = kind
        self._jitted = jitted
        self._donation = donation
        self._scan_length_for = scan_length_for
        self._items_for = items_for
        self._auto_rate = auto_rate
        self._registry = prog_registry or _REGISTRY
        self._lock = threading.Lock()
        self._compiled: Dict[tuple, Any] = {}
        self._names: Dict[tuple, str] = {}

    def __getattr__(self, attr):
        return getattr(self._jitted, attr)

    def _compile_and_register(self, sig, args, kwargs):
        import jax  # noqa: F401  (jax present whenever programs exist)

        from bigdl_tpu.kernels.dispatch import taken_in_thread

        t0 = time.perf_counter()
        # tracing runs on THIS thread: a pallas dispatch taken during
        # lower() is evidence this program embeds a kernel — the honest
        # basis for its kernel= label (a config-based guess would tag
        # kernel-free programs on any kernels-on backend)
        taken_before = taken_in_thread()
        lowered = self._jitted.lower(*args, **kwargs)
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
        kernel = "pallas" if taken_in_thread() > taken_before else None
        with self._lock:
            # one profile per signature: the first keeps the bare
            # name, later specializations get a #N suffix
            n = len(self._names)
            name = self._name if n == 0 else f"{self._name}#{n + 1}"
            self._names[sig] = name
        scan_length = 1
        if self._scan_length_for is not None:
            try:
                scan_length = int(self._scan_length_for(args, kwargs))
            except Exception:
                scan_length = 1
        items = None
        if self._items_for is not None:
            try:
                items = float(self._items_for(args, kwargs))
            except Exception:
                items = None
        self._registry.register(
            name, self._kind, compiled=compiled, compile_s=compile_s,
            scan_length=scan_length, items_per_call=items,
            donation=self._donation, kernel=kernel)
        if _CHECKS_ENABLED:
            # static verification of the freshly compiled program
            # (lowering already paid; zero executions) — the verdict
            # rides the profile into diagnose and flight bundles
            try:
                from bigdl_tpu.analysis.programs import \
                    check_compiled_program
                self._registry.attach_checks(name, check_compiled_program(
                    name, lowered, compiled, scan_length=scan_length))
            except Exception:
                pass  # verification is observability, never a crash
        return compiled

    def __call__(self, *args, **kwargs):
        import jax

        leaves = jax.tree_util.tree_leaves((args, kwargs))
        if _has_tracer(leaves):
            # traced through an outer jit/scan: the OUTER program is
            # the compiled artifact — stay transparent
            return self._jitted(*args, **kwargs)
        sig = _signature(leaves)
        with self._lock:
            compiled = self._compiled.get(sig)
        if compiled is None:
            try:
                compiled = self._compile_and_register(sig, args, kwargs)
            except Exception:
                compiled = self._jitted  # backend without AOT analysis
            with self._lock:
                compiled = self._compiled.setdefault(sig, compiled)
        if not self._auto_rate:
            return compiled(*args, **kwargs)
        t0 = time.perf_counter()
        out = compiled(*args, **kwargs)
        # close the timing window on EXECUTION, not dispatch: an
        # accelerator returns array futures immediately, and a
        # dispatch-only dt would inflate the MFU gauge by orders of
        # magnitude (profiling-enabled cost only; callers consume the
        # result synchronously right after anyway)
        jax.block_until_ready(out)
        dt = time.perf_counter() - t0
        name = self._names.get(sig, self._name)
        if dt > 0 and self._items_for is not None:
            try:
                self._registry.record_rate(
                    name, float(self._items_for(args, kwargs)) / dt)
            except Exception:
                pass
        return out


def maybe_wrap_jitted(name: str, kind: str, jitted, *, donation: str = "",
                      scan_length_for: Optional[Callable] = None,
                      items_for: Optional[Callable] = None,
                      auto_rate: bool = False, prog_registry=None):
    """The compile-site hook: when profiling is enabled, wrap a
    ``jax.jit`` callable so its programs register cost/memory profiles
    (see :class:`_ProfiledProgram`); disabled — the default — return
    ``jitted`` untouched (one flag check, zero wrapping).

    ``scan_length_for(args, kwargs)`` supplies the fused-window length
    for the scan-body FLOPs caveat; ``items_for(args, kwargs)`` counts
    the items (rows/images/tokens) one call processes; ``auto_rate``
    additionally records measured item rates per call — only sensible
    for programs whose callers consume the result synchronously (the
    serving paths), never for async-dispatched training steps."""
    if not _ENABLED:
        return jitted
    return _ProfiledProgram(name, kind, jitted, donation=donation,
                            scan_length_for=scan_length_for,
                            items_for=items_for, auto_rate=auto_rate,
                            prog_registry=prog_registry)


if os.environ.get("BIGDL_PROGRAM_PROFILES", "").strip() not in ("", "0"):
    enable()
if os.environ.get("BIGDL_PROGRAM_CHECKS", "").strip() not in ("", "0"):
    enable()        # checks need the AOT compile the profile hook pays
    enable_checks()
