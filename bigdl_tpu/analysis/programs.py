"""Program enumeration for the static HLO verifier.

Builds and **lowers** (never executes) the package's representative
compiled programs — train/eval steps, a ``steps_per_sync`` window, a
ZeRO-2 step on the CPU mesh, a bf16-policy step, a sequence-parallel
window (where ``jax.shard_map`` exists), and the generation
prefill/decode pairs (single-shot and chunked-prefill engines) — into
:class:`~bigdl_tpu.analysis.hlo.ProgramSpec`
records the check registry runs over. ``python -m bigdl_tpu.tools.check
--programs`` is the CLI; ``tests/test_check_self.py`` is the tier-1
gate that keeps the package's own programs clean.

Everything here is abstract: arguments are ``jax.ShapeDtypeStruct``
trees (optimizer state and RNG keys derived via ``jax.eval_shape``), so
enumeration performs **zero executions and zero device transfers** —
lowering and ahead-of-time compilation only, asserted by the
backend-compile/execution counter test. That is exactly the dry-run
regime ROADMAP item 4's autotuner needs: :func:`spec_from_lowered` +
:func:`bigdl_tpu.analysis.hlo.hbm_fit` price a candidate config's HBM
feasibility without running it.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.analysis.hlo import (ProgramFinding, ProgramSpec,
                                    parse_hlo, run_checks)

__all__ = ["donated_leaf_count", "abstract_tree", "spec_from_lowered",
           "enumerate_programs", "verify_programs",
           "check_compiled_program", "default_hbm_budget"]

#: default per-device HBM budget for the hbm-over-budget check when
#: neither the caller nor BIGDL_HBM_BUDGET_GB says otherwise — generous
#: on purpose (the self-gate verifies feasibility, the autotuner passes
#: the real device budget per candidate)
_DEFAULT_BUDGET_GB = 32.0


def default_hbm_budget() -> int:
    """Per-device HBM budget in bytes (``BIGDL_HBM_BUDGET_GB``
    override)."""
    gb = float(os.environ.get("BIGDL_HBM_BUDGET_GB", _DEFAULT_BUDGET_GB))
    return int(gb * (1 << 30))


def donated_leaf_count(lowered) -> int:
    """How many flat argument leaves the jit declared donated — read
    from the lowering's own ``args_info``, so the expectation and the
    compiled aliasing table come from the same program."""
    import jax

    flat = jax.tree_util.tree_leaves(
        lowered.args_info, is_leaf=lambda a: hasattr(a, "donated"))
    return sum(1 for a in flat if a.donated)


def abstract_tree(tree):
    """A ``jax.ShapeDtypeStruct`` tree mirroring ``tree`` (host arrays,
    device arrays or structs alike) — what every lowering here consumes
    instead of live buffers; attach shardings by mapping over the
    result (:func:`_with_sharding`)."""
    import jax

    def leaf(a):
        shape = tuple(getattr(a, "shape", ()) or ())
        dtype = np.dtype(getattr(a, "dtype", np.float32))
        return jax.ShapeDtypeStruct(shape, dtype)

    return jax.tree.map(leaf, tree)


def _key_struct():
    import jax

    return jax.eval_shape(lambda: jax.random.PRNGKey(0))


def _sds(shape, dtype, mesh=None, spec=None):
    import jax

    if mesh is None:
        return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))
    from jax.sharding import NamedSharding
    return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype),
                                sharding=NamedSharding(mesh, spec))


def _with_sharding(tree, mesh, specs):
    """Re-issue an abstract tree with per-leaf NamedShardings."""
    import jax
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda a, sp: jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=NamedSharding(mesh, sp)),
        tree, specs)


def spec_from_lowered(name: str, lowered, compiled=None,
                      **ctx) -> ProgramSpec:
    """Compile ``lowered`` ahead of time (no execution) and build the
    :class:`ProgramSpec` the checks consume: parsed compiled text
    (aliasing, collective placement), parsed pre-optimization text
    (shardings, dtype intent), ``memory_analysis`` numbers and the
    donated-leaf expectation from ``args_info``. Extra keyword context
    (``window``, ``zero_stage``, ``policy`` ...) passes through to the
    spec; pass ``compiled`` to reuse an already-compiled artifact."""
    if compiled is None:
        compiled = lowered.compile()
    module = parse_hlo(compiled.as_text())
    try:
        lowered_mod = parse_hlo(lowered.as_text(dialect="hlo"))
    except Exception:
        lowered_mod = None  # backend without the HLO dialect printer
    memory = None
    try:
        mem = compiled.memory_analysis()
        memory = {"arg_bytes": float(mem.argument_size_in_bytes),
                  "out_bytes": float(mem.output_size_in_bytes),
                  "temp_bytes": float(mem.temp_size_in_bytes)}
    except Exception:
        pass
    donated = ctx.pop("donated", None)
    if donated is None:
        try:
            donated = donated_leaf_count(lowered)
        except Exception:
            donated = -1
    return ProgramSpec(name=name, module=module, lowered=lowered_mod,
                       donated=donated, memory=memory, **ctx)


# ----------------------------------------------------------- the zoo legs

def _tiny_lm():
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.utils.random import RandomGenerator

    RandomGenerator.set_seed(7)
    m = TransformerLM(vocab_size=64, hidden_size=32, num_layers=1,
                      num_heads=4, max_len=16).training()
    m.ensure_initialized()
    return m


def _mlp():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.random import RandomGenerator

    RandomGenerator.set_seed(7)
    m = nn.Sequential().add(nn.Linear(16, 32)).add(nn.Tanh()) \
        .add(nn.Linear(32, 4)).add(nn.LogSoftMax())
    m.training().ensure_initialized()
    return m


def _lenet():
    from bigdl_tpu.models import LeNet5
    from bigdl_tpu.utils.random import RandomGenerator

    RandomGenerator.set_seed(7)
    m = LeNet5(10).training()
    m.ensure_initialized()
    return m


def _train_abstract(model, optim, policy=None):
    """(params, opt_state, model_state) as abstract trees — optimizer
    state (and the precision policy's master/scaler seeds) derived via
    ``jax.eval_shape``, so nothing touches a device."""
    import jax

    params = abstract_tree(model.get_parameters())
    mstate = abstract_tree(model.get_state())

    def seed_state(p):
        opt = optim.init_state(p)
        if policy is not None:
            from bigdl_tpu.precision import (MASTER_KEY, SCALER_KEY,
                                             DynamicLossScaler)
            if policy.needs_master:
                opt[MASTER_KEY] = policy.cast_to_accum(p)
            if policy.needs_loss_scaling:
                opt[SCALER_KEY] = DynamicLossScaler().init_state()
        return opt

    opt_state = jax.eval_shape(seed_state, params)
    if policy is not None and policy.needs_master:
        params = jax.eval_shape(policy.cast_to_param, params)
    return params, opt_state, mstate


def _train_step_spec(name, model, criterion, x_sds, y_sds, *,
                     policy=None, budget=None, suppress=()):
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step

    optim = SGD(learning_rate=0.1, momentum=0.9)
    params, opt_state, mstate = _train_abstract(model, optim, policy)
    step = build_train_step(model, criterion, optim, precision=policy)
    lowered = step.lower(params, opt_state, mstate, _key_struct(),
                         _sds((), np.float32), x_sds, y_sds)
    pol_name = policy.name if policy is not None else None
    compute = policy.compute_dtype.name if policy is not None else None
    if compute == "float16":
        compute = "f16"
    elif compute == "bfloat16":
        compute = "bf16"
    return spec_from_lowered(name, lowered, policy=pol_name,
                             compute_dtype=compute, hbm_budget=budget,
                             suppress=tuple(suppress),
                             extra={"kind": "train"})


def _eval_step_spec(name, model, x_sds, budget=None):
    from bigdl_tpu.optim.optimizer import build_eval_step

    params = abstract_tree(model.get_parameters())
    mstate = abstract_tree(model.get_state())
    step = build_eval_step(model.evaluate())
    lowered = step.lower(params, mstate, x_sds)
    model.training()
    return spec_from_lowered(name, lowered, hbm_budget=budget,
                             extra={"kind": "eval"})


def _window_specs(budget=None) -> List[ProgramSpec]:
    """The ``steps_per_sync`` window contract at K=8 (with a K=2
    companion for scan-dispatch-ratio): on a multi-device CPU mesh the
    window carries a ZeRO-2 sharded optimizer state, so the compiled
    program contains real collectives and the entry-collective check
    verifies the PR 8 dispatch-boundary contract structurally."""
    import jax
    from jax.sharding import PartitionSpec as P

    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import (build_train_step,
                                           make_host_window)

    model = _mlp()
    optim = SGD(learning_rate=0.1, momentum=0.9)
    ndev = min(len(jax.devices()), 8)
    mesh = cfg = None
    if ndev > 1:
        from bigdl_tpu.parallel import ZeroConfig, make_mesh
        from bigdl_tpu.parallel.zero import tree_zero_specs
        mesh = make_mesh([ndev], ["data"], jax.devices()[:ndev])
        cfg = ZeroConfig(stage=2)
    params, opt_state, mstate = _train_abstract(model, optim)
    if mesh is not None:
        params = _with_sharding(params, mesh,
                                jax.tree.map(lambda _: P(), params))
        opt_state = _with_sharding(
            opt_state, mesh, tree_zero_specs(opt_state, mesh, cfg))
        mstate = _with_sharding(mstate, mesh,
                                jax.tree.map(lambda _: P(), mstate))
    step = build_train_step(model, nn.ClassNLLCriterion(), optim,
                            zero=cfg, mesh=mesh)
    window = make_host_window(step)
    key = _key_struct()
    rows = 16

    def lower_at(k):
        keys = _sds((k,) + key.shape, key.dtype)
        lrs = _sds((k,), np.float32)
        if mesh is None:
            xs = _sds((k, rows, 16), np.float32)
            ys = _sds((k, rows), np.float32)
        else:
            xs = _sds((k, rows, 16), np.float32, mesh, P(None, "data"))
            ys = _sds((k, rows), np.float32, mesh, P(None, "data"))
        return window.lower(params, opt_state, mstate, keys, lrs, xs, ys)

    shared = dict(window=True, zero_stage=cfg.stage if cfg else 0,
                  ndev=ndev, hbm_budget=budget,
                  extra={"kind": "window"})
    companion = spec_from_lowered("train/mlp/window@k2", lower_at(2),
                                  scan_length=2, **shared)
    spec = spec_from_lowered("train/mlp/window@k8", lower_at(8),
                             scan_length=8, companion=companion,
                             **shared)
    return [spec, companion]


def _zero_step_spec(budget=None) -> Optional[ProgramSpec]:
    """A plain (unwindowed) ZeRO-2 train step on the CPU mesh, with the
    opt-state parameter indices marked for replicated-large-operand.
    None when the process has a single device."""
    import jax
    from jax.sharding import PartitionSpec as P

    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.parallel import ZeroConfig, make_mesh
    from bigdl_tpu.parallel.zero import tree_zero_specs

    ndev = min(len(jax.devices()), 8)
    if ndev < 2:
        return None
    mesh = make_mesh([ndev], ["data"], jax.devices()[:ndev])
    cfg = ZeroConfig(stage=2)
    model = _mlp()
    optim = SGD(learning_rate=0.1, momentum=0.9)
    params, opt_state, mstate = _train_abstract(model, optim)
    n_params = len(jax.tree.leaves(params))
    n_opt = len(jax.tree.leaves(opt_state))
    params = _with_sharding(params, mesh,
                            jax.tree.map(lambda _: P(), params))
    opt_state = _with_sharding(
        opt_state, mesh, tree_zero_specs(opt_state, mesh, cfg))
    mstate = _with_sharding(mstate, mesh,
                            jax.tree.map(lambda _: P(), mstate))
    step = build_train_step(model, nn.ClassNLLCriterion(), optim,
                            zero=cfg, mesh=mesh)
    lowered = step.lower(
        params, opt_state, mstate, _key_struct(), _sds((), np.float32),
        _sds((16, 16), np.float32, mesh, P("data")),
        _sds((16,), np.float32, mesh, P("data")))
    return spec_from_lowered(
        "train/mlp/zero2/step", lowered, zero_stage=2, ndev=ndev,
        sharded_params=tuple(range(n_params, n_params + n_opt)),
        # the MLP's leaves are KB-sized; verify their placement anyway
        large_bytes=1 << 10, hbm_budget=budget,
        extra={"kind": "zero"})


def _seq_parallel_window_spec(budget=None) -> Optional[ProgramSpec]:
    """A ``steps_per_sync`` window over a sequence-parallel transformer
    step: build_train_step(seq_parallel=...) on a ["seq"] mesh, K=2.
    This is the structural proof of the long-context composition
    contract — the ring collectives (``collective-permute`` /
    ``all-to-all``, both in the entry-collective check's
    COMMUNICATION_OPS) trace inside the scan body, so the windowed
    dispatch boundary stays collective-free. None (with a note) when
    the process cannot run it: single device, or a jax build without
    ``jax.shard_map``."""
    import jax
    from jax.sharding import PartitionSpec as P

    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import (build_train_step,
                                           make_host_window)
    from bigdl_tpu.parallel import SeqParallelConfig, make_mesh
    from bigdl_tpu.parallel.sequence import sequence_parallel_available

    ndev = min(len(jax.devices()), 8)
    if ndev < 2 or not sequence_parallel_available():
        return None
    mesh = make_mesh([ndev], ["seq"], jax.devices()[:ndev])
    model = _tiny_lm()
    optim = SGD(learning_rate=0.1, momentum=0.9)
    params, opt_state, mstate = _train_abstract(model, optim)
    params = _with_sharding(params, mesh,
                            jax.tree.map(lambda _: P(), params))
    opt_state = _with_sharding(opt_state, mesh,
                               jax.tree.map(lambda _: P(), opt_state))
    mstate = _with_sharding(mstate, mesh,
                            jax.tree.map(lambda _: P(), mstate))
    step = build_train_step(
        model, nn.SequenceCrossEntropyCriterion(), optim, mesh=mesh,
        seq_parallel=SeqParallelConfig(axis="seq", mesh=mesh))
    window = make_host_window(step)
    key = _key_struct()
    keys = _sds((2,) + key.shape, key.dtype)
    lowered = window.lower(
        params, opt_state, mstate, keys, _sds((2,), np.float32),
        _sds((2, 4, 16), np.int32), _sds((2, 4, 16), np.int32))
    return spec_from_lowered(
        "train/transformer_lm/seq_parallel/window@k2", lowered,
        window=True, scan_length=2, ndev=ndev, hbm_budget=budget,
        extra={"kind": "window"})


def _generation_specs(budget=None) -> List[ProgramSpec]:
    """The serving prefill/decode program pair (donated KV cache) via
    the DecodeEngine's enumeration hook — the exact jits the engine
    compiles, lowered over abstract cache/params trees. A second
    engine with ``prefill_chunk`` enumerates the CHUNKED long-prompt
    admission programs: the prefill jit's token operand is chunk-wide
    (never rung-wide), which is the whole point — a 128K rung admits
    through the same fixed-width program, and the donation/boundary
    checks hold for it like any other serving program."""
    from bigdl_tpu.generation.engine import DecodeEngine
    from bigdl_tpu.serving.compile_cache import BucketLadder, CompileCache

    model = _tiny_lm()
    params = abstract_tree(model.get_parameters())
    state = abstract_tree(model.get_state())
    out = []
    for tag, engine in (
            ("", DecodeEngine(CompileCache(),
                              BucketLadder(16, buckets=(16,)),
                              slots=4, prefill_rows=2)),
            ("chunked/", DecodeEngine(CompileCache(),
                                      BucketLadder(16, buckets=(8, 16)),
                                      slots=4, prefill_rows=2,
                                      prefill_chunk=8))):
        for name, jitted, args in engine.abstract_programs(
                model, params, state, kv_dtype=np.float32):
            lowered = jitted.lower(*args)
            out.append(spec_from_lowered(
                f"serving/transformer_lm/{tag}{name}", lowered,
                hbm_budget=budget, extra={"kind": "serving"}))
    return out


def _serving_eval_spec(budget=None) -> ProgramSpec:
    """One bucketed serving eval program through the CompileCache's
    enumeration hook (the program ``step_for`` would compile)."""
    from bigdl_tpu.serving.compile_cache import CompileCache

    model = _lenet()
    model.evaluate()
    params = abstract_tree(model.get_parameters())
    state = abstract_tree(model.get_state())
    jitted = CompileCache.abstract_step(model)
    lowered = jitted.lower(params, state, _sds((8, 1, 28, 28),
                                               np.float32))
    model.training()
    return spec_from_lowered("serving/lenet5/eval/8", lowered,
                             hbm_budget=budget,
                             extra={"kind": "serving"})


def enumerate_programs(hbm_budget: Optional[int] = None
                       ) -> Tuple[List[ProgramSpec], List[str]]:
    """Build + lower the verification suite; returns ``(specs,
    notes)`` — notes name legs that were skipped (single-device
    process) so reports stay honest about coverage."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.precision import PrecisionPolicy

    budget = default_hbm_budget() if hbm_budget is None else hbm_budget
    notes: List[str] = []
    specs: List[ProgramSpec] = []

    lenet = _lenet()
    specs.append(_train_step_spec(
        "train/lenet5/step", lenet, nn.ClassNLLCriterion(),
        _sds((8, 1, 28, 28), np.float32), _sds((8,), np.float32),
        budget=budget))
    specs.append(_eval_step_spec("train/lenet5/eval", lenet,
                                 _sds((8, 1, 28, 28), np.float32),
                                 budget=budget))
    lm = _tiny_lm()
    specs.append(_train_step_spec(
        "train/transformer_lm/step", lm,
        nn.SequenceCrossEntropyCriterion(),
        _sds((4, 16), np.int32), _sds((4, 16), np.int32),
        budget=budget))
    specs.append(_train_step_spec(
        "train/transformer_lm/step@bf16", _tiny_lm(),
        nn.SequenceCrossEntropyCriterion(),
        _sds((4, 16), np.int32), _sds((4, 16), np.int32),
        policy=PrecisionPolicy.bf16_mixed(), budget=budget))
    specs.extend(_window_specs(budget))
    zero = _zero_step_spec(budget)
    if zero is not None:
        specs.append(zero)
    else:
        notes.append("zero leg skipped (single-device process; run "
                     "under XLA_FLAGS=--xla_force_host_platform_"
                     "device_count=8 for the mesh contract)")
    sp = _seq_parallel_window_spec(budget)
    if sp is not None:
        specs.append(sp)
    else:
        notes.append("seq-parallel window leg skipped (needs "
                     "jax.shard_map and a multi-device process; the "
                     "entry-collective contract for ring/Ulysses "
                     "collectives is verified where both exist)")
    specs.append(_serving_eval_spec(budget))
    specs.extend(_generation_specs(budget))
    return specs, notes


def verify_programs(checks: Optional[Sequence[str]] = None,
                    hbm_budget: Optional[int] = None
                    ) -> Tuple[List[ProgramFinding], List[ProgramSpec],
                               List[str]]:
    """Enumerate the suite and run the (optionally restricted) check
    set: ``(findings, specs, notes)``. Lowering/compiling only — zero
    executions (tested)."""
    specs, notes = enumerate_programs(hbm_budget)
    return run_checks(specs, checks), specs, notes


def check_compiled_program(name: str, lowered, compiled,
                           scan_length: int = 1,
                           hbm_budget: Optional[int] = None
                           ) -> List[Dict[str, object]]:
    """Context-light verification of ONE freshly compiled program —
    the ``telemetry.programs`` compile-site hook (enable with
    ``BIGDL_PROGRAM_CHECKS=1``): donation, dispatch-boundary and HBM
    checks run with whatever context the jit itself carries; policy/
    ZeRO contracts need the enumerated suite. Returns finding dicts
    (what ``ProgramProfile.checks`` stores and flight-recorder
    ``programs.json`` bundles ship)."""
    spec = spec_from_lowered(
        name, lowered, compiled=compiled,
        window=scan_length > 1, scan_length=scan_length,
        hbm_budget=default_hbm_budget() if hbm_budget is None
        else hbm_budget)
    return [f.to_dict() for f in run_checks([spec])]
