"""Collective-placement checks for windowed (``steps_per_sync``)
programs: the dispatch boundary stays collective-free and the
per-dispatch collective count stays K-independent."""
from __future__ import annotations

from bigdl_tpu.analysis.hlo import (COMMUNICATION_OPS, ProgramSpec,
                                    collective_counts, hlo_check)


@hlo_check(
    "entry-collective",
    "a communication collective in the ENTRY computation of a windowed "
    "program — it runs at the host dispatch boundary instead of "
    "overlapping with compute inside the scan")
def entry_collective(spec: ProgramSpec):
    if not spec.window or spec.module is None:
        return
    counts = collective_counts(spec.module)
    for op in COMMUNICATION_OPS:
        n = counts[op]["entry"]
        if n:
            yield ("error",
                   f"{n} `{op}` op{'s' if n != 1 else ''} in the ENTRY "
                   "computation of a steps_per_sync window program; "
                   "collectives must live inside the scan body where "
                   "XLA overlaps them with the neighbouring steps' "
                   "compute (docs/performance.md, the PR 8 contract)")


@hlo_check(
    "scan-dispatch-ratio",
    "a window program whose per-dispatch collective count grows with "
    "K — the window unrolled (or its gathers un-hoisted from the scan)")
def scan_dispatch_ratio(spec: ProgramSpec):
    if not spec.window or spec.module is None or spec.companion is None:
        return
    if spec.companion.module is None:
        return
    k_hi = max(spec.scan_length, 1)
    k_lo = max(spec.companion.scan_length, 1)
    if k_hi <= k_lo:
        return
    def total(module):
        counts = collective_counts(module)
        return sum(counts[op]["total"] for op in COMMUNICATION_OPS)
    hi, lo = total(spec.module), total(spec.companion.module)
    # a lax.scan body appears ONCE in the program text whatever its trip
    # count, so the instruction count must not scale with K; growth
    # means the K steps were unrolled (or per-step gathers escaped the
    # scan into K copies) and every dispatch pays them serially
    if lo >= 0 and hi > lo:
        yield ("error",
               f"per-dispatch collective op count grew with K: "
               f"{lo} ops at K={k_lo} vs {hi} at K={k_hi}; a scanned "
               "window embeds its per-step collectives ONCE (the scan "
               "body) — this program unrolls them per step, so each "
               "dispatch serializes K rounds of communication")
