"""precision-leak: f32 compute escaping the sanctioned islands of a
bf16/f16-policy program.

The policy's contract (docs/precision.md): forward/backward run in
``compute_dtype``; only the norm-stat / softmax / loss islands (and
the master-copy update, which contains no matmuls) hold f32. Backends
legalize dtypes during compilation (XLA CPU rewrites every bf16 dot to
f32), so this check reads the **lowered** HLO — the policy's intent —
and uses the parser's def-use edges to resolve the operand dtypes that
lowered text leaves implicit.

A ``dot``/``convolution`` with a large f32 operand is the leak
signature *candidate* — but two legitimate patterns look the same at
one-op distance, so the check classifies each wide operand's
contiguous f32 def region (the walk follows def-use edges while
results stay f32/f64 and stops at ``convert`` ops, the casts that
delimit every island):

- the region contains a transcendental (``exponential``/``log``/
  ``rsqrt``/...) — it *is* a sanctioned island or its gradient flow
  (the attention backward multiplies f32 softmax cotangents into
  dQ/dK); sanctioned.
- the region is a bare up-convert reached through shape-only ops —
  the ``preferred_element_type`` accumulation boundary (bf16 operands
  up-cast at the MXU's own f32-accumulate edge, including the
  transposed weight-gradient dots every Linear emits); sanctioned.
- the region performs **f32 arithmetic with no island evidence** — a
  cast escaped and real compute now runs wide; flagged.
"""
from __future__ import annotations

from bigdl_tpu.analysis.hlo import (HloComputation, HloModule, HloOp,
                                    ProgramSpec, hlo_check)

_LOW_PRECISION = {"bf16", "f16"}
_WIDE = {"f32", "f64"}

#: transcendental opcodes that mark a sanctioned f32 island — softmax
#: (exp), log-softmax / NLL loss (log), norm statistics (rsqrt/sqrt),
#: saturating activations computed wide (tanh/logistic/erf)
_ISLAND_OPS = {
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "rsqrt", "sqrt", "cbrt", "power", "tanh", "logistic", "erf",
    "erf-inv", "atan2",
}

#: data-movement opcodes: allowed between the up-convert and the dot
#: without making the region "compute" (the accumulation pattern moves
#: casts through transposes/reshapes)
_SHAPE_OPS = {
    "transpose", "reshape", "broadcast", "copy", "bitcast", "slice",
    "get-tuple-element", "tuple", "concatenate", "reverse", "pad",
    "parameter", "constant", "iota", "convert",
}


def _region_verdict(module: HloModule, comp: HloComputation,
                    start: HloOp, limit: int = 4096) -> bool:
    """True when ``start``'s f32 region is sanctioned: island evidence
    found, or no real arithmetic at all (a bare accumulation-boundary
    up-cast). Gives up sanctioning-side past ``limit`` visited ops —
    a silent false positive on a monster program would be worse than
    a miss."""
    stack = [start]
    seen = set()
    compute_seen = False
    while stack:
        op = stack.pop()
        if op.name in seen:
            continue
        seen.add(op.name)
        if len(seen) > limit:
            return True
        if op.opcode in _ISLAND_OPS:
            return True
        for cname in op.called.values():
            sub = module.computations.get(cname)
            if sub is not None and any(o.opcode in _ISLAND_OPS
                                       for o in sub.ops):
                return True
        if op.opcode not in _SHAPE_OPS:
            compute_seen = True  # real f32 arithmetic in the region
        if op.opcode == "convert":
            continue  # island boundary: the cast ends the f32 region
        for nm in op.operands:
            nxt = comp.by_name.get(nm)
            if nxt is not None and nxt.dtype in _WIDE:
                stack.append(nxt)
    return not compute_seen


@hlo_check(
    "precision-leak",
    "f32 compute on large tensors inside a bf16/f16-policy program, "
    "outside the sanctioned norm/softmax/loss islands")
def precision_leak(spec: ProgramSpec):
    if spec.compute_dtype not in _LOW_PRECISION:
        return  # f32 policy (or unknown): nothing to leak
    module = spec.lowered if spec.lowered is not None else spec.module
    if module is None:
        return
    for comp, op in module.find_ops():
        if op.opcode in ("dot", "convolution"):
            resolved = [comp.by_name.get(nm) for nm in op.operands]
            wide = [src for src in resolved
                    if src is not None and src.dtype in _WIDE]
            big = [src for src in wide
                   if src.result_elements() >= spec.dot_elems]
            if not big:
                continue
            bad = [src for src in big
                   if not _region_verdict(module, comp, src)]
            if not bad:
                continue  # island gradient flow / accumulation casts
            src = bad[0]
            yield ("error",
                   f"{op.opcode} `{op.name}` consumes a "
                   f"{src.dtype}{list(src.dims)} operand "
                   f"(`{src.name}`) computed by f32 arithmetic under "
                   f"the {spec.policy or spec.compute_dtype} policy; "
                   f"matmuls must run on {spec.compute_dtype} operands "
                   "(f32 belongs only to the norm/softmax/loss "
                   "islands and the master update) — drop the stray "
                   "astype/upcast or route accumulation through "
                   "preferred_element_type")
        elif op.opcode == "convert" and op.dtype in _WIDE:
            size = op.result_bytes()
            if size >= spec.convert_bytes:
                yield ("warning",
                       f"convert `{op.name}` materializes "
                       f"{op.dtype}{list(op.dims)} ({size:,} bytes) "
                       f"under the {spec.policy or spec.compute_dtype} "
                       "policy — larger than any sanctioned island; "
                       "check for an activation-sized upcast")
