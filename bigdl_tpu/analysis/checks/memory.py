"""hbm-over-budget: static HBM infeasibility from ``memory_analysis``
— arguments + outputs + temps against a per-device budget, no
compilation beyond the one already paid and no execution ever.

The check is a thin wrapper over :func:`bigdl_tpu.analysis.hlo.
hbm_fit`, which is deliberately a standalone API: the profile-guided
autotuner (ROADMAP item 4) calls it per candidate configuration to
prune HBM-infeasible points before measuring anything."""
from __future__ import annotations

from bigdl_tpu.analysis.hlo import ProgramSpec, hbm_fit, hlo_check


@hlo_check(
    "hbm-over-budget",
    "memory_analysis arguments+outputs+temps exceed the per-device HBM "
    "budget — the program cannot fit, statically, before any execution")
def hbm_over_budget(spec: ProgramSpec):
    if spec.memory is None or spec.hbm_budget is None:
        return
    fit = hbm_fit(spec.memory, spec.hbm_budget)
    if fit["fits"]:
        return
    b = fit["breakdown"]
    yield ("error",
           f"program pins {fit['total_bytes']:,} bytes "
           f"(args {int(b['arg_bytes']):,} + outputs "
           f"{int(b['out_bytes']):,} + temps {int(b['temp_bytes']):,}) "
           f"against a {spec.hbm_budget:,}-byte per-device budget; "
           "shrink the batch/window, raise the ZeRO stage, or lower "
           "the precision policy (tools/autotune prunes such configs "
           "with this same analysis)")
