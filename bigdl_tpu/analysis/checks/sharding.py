"""replicated-large-operand: under an active ZeRO stage >= 2 config on
a multi-device mesh, the state the policy promised to shard must not
arrive replicated — a large replicated operand silently costs n x its
sharded footprint on every chip."""
from __future__ import annotations

from bigdl_tpu.analysis.hlo import ProgramSpec, hlo_check


def _shardable(dims, ndev: int) -> bool:
    """Mirror of ``parallel.zero.extend_spec``'s eligibility: some dim
    divides the data axis, so the leaf COULD have been sharded."""
    return any(d > 0 and d % ndev == 0 for d in dims)


@hlo_check(
    "replicated-large-operand",
    "a large parameter the ZeRO (stage >= 2) policy should shard is "
    "replicated on a multi-device mesh — n x the planned memory")
def replicated_large_operand(spec: ProgramSpec):
    if spec.zero_stage < 2 or spec.ndev <= 1 or not spec.sharded_params:
        return
    # shardings live on the PRE-partitioning parameters: compiled SPMD
    # text already splits shapes per device and drops the annotations
    module = spec.lowered if spec.lowered is not None else spec.module
    if module is None:
        return
    params = {p.parameter_index: p for p in module.entry_params()}
    for idx in spec.sharded_params:
        op = params.get(idx)
        if op is None:
            continue
        size = op.result_bytes()
        if size < spec.large_bytes or not op.replicated \
                or not _shardable(op.dims, spec.ndev):
            continue
        yield ("error",
               f"parameter {idx} ({op.dtype}{list(op.dims)}, "
               f"{size:,} bytes) is replicated across the "
               f"{spec.ndev}-device mesh under ZeRO stage "
               f"{spec.zero_stage}; shard it with "
               "parallel.zero.shard_zero_tree / constrain_zero (or it "
               f"costs {spec.ndev}x its sharded footprint per chip)")
