"""Built-in compiled-program checks (the HLO twin of
``bigdl_tpu.analysis.rules``).

Importing this package registers every built-in check with the
:func:`bigdl_tpu.analysis.hlo.hlo_check` registry:

- ``donation-dropped`` — an input declared donated has no entry in the
  program's aliasing/donor table (silent 2x memory).
- ``entry-collective`` — a communication collective in the ENTRY
  computation of a windowed (``steps_per_sync``) program: the PR 8
  dispatch-boundary contract as a reusable check.
- ``scan-dispatch-ratio`` — a window program whose per-dispatch
  collective count grows with K (an unrolled window / un-hoisted
  gathers).
- ``replicated-large-operand`` — a large, shardable entry parameter
  left replicated on a multi-device mesh under ZeRO stage >= 2.
- ``precision-leak`` — f32 compute escaping the sanctioned
  norm/softmax/loss islands of a bf16/f16-policy program.
- ``hbm-over-budget`` — ``memory_analysis`` arguments+outputs+temps
  exceed the per-device budget: static infeasibility, no execution
  (the autotuner's pruning primitive, ROADMAP item 4).
"""
from bigdl_tpu.analysis.checks import (  # noqa: F401  register on import
    collectives, donation, memory, precision, sharding)

from bigdl_tpu.analysis.hlo import available_checks  # noqa: F401

__all__ = ["available_checks"]
