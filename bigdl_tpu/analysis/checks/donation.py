"""donation-dropped: every donated input must be aliased (or declared
donatable) in the compiled program, or the donation silently buys
nothing and the program holds 2x the memory the caller planned for."""
from __future__ import annotations

from bigdl_tpu.analysis.hlo import ProgramSpec, hlo_check


@hlo_check(
    "donation-dropped",
    "an input declared in donate_argnums has no entry in the compiled "
    "program's input/output aliasing table — silent 2x memory")
def donation_dropped(spec: ProgramSpec):
    if spec.donated < 0 or spec.module is None:
        return  # no donation contract declared for this program
    honored = len(spec.module.donated_params)
    if honored >= spec.donated:
        return
    n_params = len(spec.module.entry_params())
    detail = ""
    if honored and spec.module.aliased_params:
        missing = sorted(
            set(range(spec.donated)) - spec.module.donated_params)
        if missing:
            detail = f" (parameter indices {missing[:8]} unaliased)"
    yield ("error",
           f"{spec.donated} leaves declared donated but only {honored} "
           f"aliased/donatable in the compiled program "
           f"({n_params} entry parameters){detail}; the un-aliased "
           "donations hold BOTH the old and new buffer live — donate "
           "only inputs an output can reuse (same shape/dtype), or "
           "drop them from donate_argnums")
