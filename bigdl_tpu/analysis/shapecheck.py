"""Pre-compile shape/dtype checking for Module graphs.

The whole check runs under ``jax.eval_shape``: parameters, state and the
forward are traced with abstract values only — zero FLOPs, zero device
transfers, zero XLA compilations — so a mis-wired ResNet-50 is rejected in
milliseconds with a diagnostic naming the offending *layer path*
("``sequential[3]/linear2``: dot_general requires ...") instead of a deep
XLA stack after a 30-second compile. This is the JAX-side counterpart of
the reference's graph-build-time typed layer errors (BigDL layers validate
``inputShape`` eagerly; the TensorFlow paper argues the same static-
validation-before-compilation point).

The batch dimension may be **symbolic** (``spec(("b", 3, 224, 224))``):
the trace then proves the graph correct for *every* batch size at once via
``jax.export`` shape polymorphism. When a layer genuinely cannot trace
under a symbolic dim, the checker falls back to a concrete probe batch and
reports the symbolic limitation as a warning rather than an error.

Per-layer attribution works by *interception*: every submodule's bound
``apply`` is temporarily shadowed with a wrapper that converts the first
trace-time failure into a :class:`Diagnostic` carrying the structural path
of the deepest failing module. The wrapper also performs an explicit
dtype-compatibility check (floating params fed integer inputs) that JAX's
value promotion would otherwise silently accept.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.module import Module

__all__ = ["Diagnostic", "ShapeCheckError", "ShapeReport", "check_module",
           "spec"]

# shape entries may be ints (concrete), or strings/None (symbolic dims;
# None means the default symbolic batch name "b")
DimLike = Union[int, str, None]


@dataclass
class Diagnostic:
    """One shape-checker finding, attributed to a layer path."""

    path: str                # e.g. "sequential[2]/linear"
    layer: str               # class name of the failing module
    message: str             # first line of the underlying error
    severity: str = "error"  # "error" fails the check; "warning" does not
    input_shapes: Optional[str] = None
    policy: Optional[str] = None  # the precision regime checked under

    def __str__(self) -> str:
        loc = f"`{self.path}` ({self.layer})"
        msg = f"{loc}: {self.message}"
        if self.input_shapes:
            msg += f" [input: {self.input_shapes}]"
        if self.policy:
            msg += f" [policy: {self.policy}]"
        return msg


class ShapeCheckError(ValueError):
    """Raised by ``Module.check`` / pre-flight hooks on a failed check."""

    def __init__(self, diagnostics: Sequence[Diagnostic]):
        self.diagnostics = list(diagnostics)
        lines = "\n  ".join(str(d) for d in self.diagnostics)
        super().__init__(
            f"shape check failed ({len(self.diagnostics)} finding"
            f"{'s' if len(self.diagnostics) != 1 else ''}):\n  {lines}")


@dataclass
class ShapeReport:
    """Result of :func:`check_module`.

    ``output`` holds the abstract output pytree (``jax.ShapeDtypeStruct``
    leaves) on success; ``symbolic`` records whether the successful trace
    ran with the symbolic batch dimension (False = concrete fallback).
    """

    ok: bool
    diagnostics: List[Diagnostic] = field(default_factory=list)
    output: Any = None
    symbolic: bool = False

    @property
    def errors(self) -> List[Diagnostic]:
        """Only the check-failing diagnostics (severity == error)."""
        return [d for d in self.diagnostics if d.severity == "error"]

    def __str__(self) -> str:
        if self.ok:
            shapes = jax.tree.map(
                lambda o: f"{o.dtype.name}{list(o.shape)}", self.output)
            head = f"ok: output {shapes}"
        else:
            head = "FAILED"
        body = "".join(f"\n  {d}" for d in self.diagnostics)
        return head + body


# --------------------------------------------------------------- input specs

def spec(shape: Sequence[DimLike], dtype=jnp.float32):
    """Declare one input: ``spec((\"b\", 3, 224, 224))`` or
    ``spec((\"b\", 128), jnp.int32)``. Strings/None are symbolic dims."""
    return (tuple(shape), jnp.dtype(dtype))


def _dtype_like(x) -> bool:
    if isinstance(x, (tuple, list, jax.ShapeDtypeStruct)):
        return False
    try:
        jnp.dtype(x)
        return True
    except TypeError:
        return False


def _normalize(input_spec) -> List[Tuple[Tuple[DimLike, ...], Any]]:
    """Accept spec(), ShapeDtypeStruct, a bare shape tuple, or a list of
    those (multi-input); return a flat list of (shape, dtype) pairs."""
    if isinstance(input_spec, jax.ShapeDtypeStruct):
        return [(tuple(input_spec.shape), input_spec.dtype)]
    if isinstance(input_spec, tuple) and len(input_spec) == 2 \
            and isinstance(input_spec[0], tuple) \
            and all(isinstance(d, (int, str, type(None)))
                    for d in input_spec[0]) \
            and _dtype_like(input_spec[1]):
        # a spec() result — the dtype test disambiguates it from a
        # 2-tuple of specs (whose second element is itself a pair)
        return [(input_spec[0], jnp.dtype(input_spec[1]))]
    if isinstance(input_spec, (list, tuple)) and input_spec and \
            all(isinstance(d, (int, str, type(None)))
                for d in input_spec):
        return [(tuple(input_spec), jnp.dtype(jnp.float32))]  # bare shape
    if isinstance(input_spec, (list, tuple)):
        out = []
        for s in input_spec:
            out.extend(_normalize(s))
        return out
    raise TypeError(f"cannot interpret input spec {input_spec!r}; use "
                    "spec(shape, dtype) or a list of them")


def _build_structs(pairs, concrete_batch: Optional[int]):
    """(shape, dtype) pairs -> ShapeDtypeStructs, resolving symbolic dims
    through one shared jax.export scope (or ``concrete_batch`` ints)."""
    names: List[str] = []
    for shape, _ in pairs:
        for d in shape:
            n = "b" if d is None else d
            if isinstance(n, str) and n not in names:
                names.append(n)
    symdims: Dict[str, Any] = {}
    if names and concrete_batch is None:
        from jax import export
        for name, dim in zip(names, export.symbolic_shape(",".join(names))):
            symdims[name] = dim

    def resolve(d):
        if isinstance(d, int):
            return d
        name = "b" if d is None else d
        return symdims.get(name, concrete_batch)

    structs = [jax.ShapeDtypeStruct(tuple(resolve(d) for d in shape), dt)
               for shape, dt in pairs]
    return structs, bool(names)


# ------------------------------------------------------------- module walk

def _label(m: Module) -> str:
    return m._name or type(m).__name__.lower()


def _iter_children(m: Module):
    """(path-suffix, child) pairs; containers/Graph get index/node labels,
    other composites are discovered through their Module attributes."""
    from bigdl_tpu.nn.container import Container
    from bigdl_tpu.nn.graph import Graph
    if isinstance(m, Graph):
        for n in m.exec_order:
            yield f"/{m.node_names[id(n)]}", n.element
        return
    if isinstance(m, Container):
        for i, c in enumerate(m.modules):
            yield f"[{i}]/{_label(c)}", c
        return
    for attr, v in vars(m).items():
        if attr.startswith("_"):
            continue
        if isinstance(v, Module):
            yield f".{attr}", v
        elif isinstance(v, (list, tuple)):
            for i, e in enumerate(v):
                if isinstance(e, Module):
                    yield f".{attr}[{i}]", e


def _collect_paths(m: Module, path: str, out: Dict[int, Tuple[str, Module]]):
    if id(m) in out:
        return  # shared submodule (MapTable): first path wins
    out[id(m)] = (path, m)
    for suffix, child in _iter_children(m):
        _collect_paths(child, path + suffix, out)


def _fmt_shapes(x) -> str:
    def one(leaf):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None:
            return type(leaf).__name__
        return f"{getattr(dtype, 'name', dtype)}{list(shape)}"
    try:
        return str(jax.tree.map(one, x))
    except Exception:
        return repr(type(x).__name__)


class _Failure(Exception):
    """Internal carrier: the deepest failing module's diagnostic."""

    def __init__(self, diagnostic: Diagnostic):
        self.diagnostic = diagnostic
        super().__init__(str(diagnostic))


def _first_line(e: BaseException) -> str:
    text = str(e).strip() or type(e).__name__
    return text.splitlines()[0]


def _int_params_mismatch(m: Module, params, x) -> bool:
    """Floating params about to consume an all-integer input — silently
    legal under JAX promotion, almost always a wiring bug (unless the
    layer declares ``integer_input_ok``, e.g. LookupTable)."""
    if getattr(m, "integer_input_ok", False):
        return False
    p_leaves = jax.tree.leaves(params)
    if not p_leaves or not any(jnp.issubdtype(p.dtype, jnp.floating)
                               for p in p_leaves):
        return False
    x_leaves = [leaf for leaf in jax.tree.leaves(x)
                if hasattr(leaf, "dtype")]
    return bool(x_leaves) and all(
        jnp.issubdtype(leaf.dtype, jnp.integer) for leaf in x_leaves)


class _Interceptor:
    """Temporarily shadow every submodule's ``apply`` with a wrapper that
    attributes the first trace failure to that module's path."""

    def __init__(self, root: Module):
        self.paths: Dict[int, Tuple[str, Module]] = {}
        _collect_paths(root, _label(root), self.paths)
        self.leaves = {mid for mid, (_, m) in self.paths.items()
                       if not any(True for _ in _iter_children(m))}

    def __enter__(self):
        for mid, (path, m) in self.paths.items():
            self._wrap(m, path, mid in self.leaves)
        return self

    def __exit__(self, *exc):
        for _, m in self.paths.values():
            m.__dict__.pop("apply", None)
        return False

    def _wrap(self, m: Module, path: str, is_leaf: bool):
        orig = type(m).apply.__get__(m)

        def wrapped(params, state, input, *, training=False, rng=None):
            if is_leaf and _int_params_mismatch(m, params, input):
                raise _Failure(Diagnostic(
                    path=path, layer=type(m).__name__,
                    message="dtype mismatch: floating-point parameters "
                            "applied to an integer input (JAX would "
                            "silently promote; insert a cast or an "
                            "embedding layer)",
                    input_shapes=_fmt_shapes(input)))
            try:
                return orig(params, state, input, training=training,
                            rng=rng)
            except _Failure:
                raise  # deepest module already attributed
            except Exception as e:
                raise _Failure(Diagnostic(
                    path=path, layer=type(m).__name__,
                    message=_first_line(e),
                    input_shapes=_fmt_shapes(input))) from e

        m.__dict__["apply"] = wrapped


# ------------------------------------------------------------------- driver

def _run_abstract(module: Module, structs, training: bool,
                  policy=None) -> ShapeReport:
    # the PRNG key enters as an abstract spec too, so nothing — params,
    # state, key, forward — ever materializes or compiles
    key_spec = jax.eval_shape(jax.random.PRNGKey,
                              jax.ShapeDtypeStruct((), jnp.uint32))
    from bigdl_tpu.utils.table import T
    x_spec = structs[0] if len(structs) == 1 else T(*structs)

    def forward(key, x):
        ki, kr = jax.random.split(key)
        params = module.init(ki)
        state = module.initial_state()
        if policy is not None and not policy.is_noop:
            # trace the graph exactly as the policy's train/eval step
            # would run it: params and inputs cast to compute dtype on
            # entry, output cast on exit — so the abstract dtypes the
            # diagnostics print are the dtypes the compile would see
            return policy.apply_module(module, params, state, x,
                                       training=training, rng=kr)
        return module.apply(params, state, x, training=training, rng=kr)

    with _Interceptor(module):
        try:
            out, _ = jax.eval_shape(forward, key_spec, x_spec)
        except _Failure as e:
            return ShapeReport(ok=False, diagnostics=[e.diagnostic])
        except Exception as e:  # failed outside any module apply
            return ShapeReport(ok=False, diagnostics=[Diagnostic(
                path=_label(module), layer=type(module).__name__,
                message=_first_line(e))])
    return ShapeReport(ok=True, output=out)


def check_module(module: Module, input_spec, *, training: bool = False,
                 probe_batch: int = 4, policy=None) -> ShapeReport:
    """Shape/dtype-check ``module`` against ``input_spec`` without any
    compilation or FLOPs.

    ``input_spec``: :func:`spec` result, ``jax.ShapeDtypeStruct``, a bare
    shape tuple (float32), or a list of those for multi-input modules.
    Symbolic dims (strings / None) prove the graph for every batch size;
    if a layer cannot trace symbolically the checker retries with
    ``probe_batch`` and downgrades the symbolic failure to a warning.

    ``policy`` (a ``precision.PrecisionPolicy``) traces the graph under
    that mixed-precision regime: floating input specs are re-dtyped to
    ``compute_dtype``, params cast on entry exactly like the compiled
    step, and every diagnostic carries the policy's dtypes — so layer
    paths in the report show the bf16/f16 dtypes the real compile
    would see.
    """
    pairs = _normalize(input_spec)
    if policy is not None and not policy.is_noop:
        pairs = [(shape,
                  policy.compute_dtype
                  if jnp.issubdtype(dt, jnp.floating) else dt)
                 for shape, dt in pairs]

    def tag(report: ShapeReport) -> ShapeReport:
        if policy is not None and not policy.is_noop:
            note = (f"{policy.name}: param={policy.param_dtype.name} "
                    f"compute={policy.compute_dtype.name} "
                    f"accum={policy.accum_dtype.name}")
            for d in report.diagnostics:
                d.policy = note
        return report

    structs, had_symbolic = _build_structs(pairs, concrete_batch=None)
    report = _run_abstract(module, structs, training, policy)
    report.symbolic = had_symbolic
    if report.ok or not had_symbolic:
        return tag(report)
    # disambiguate "mis-wired model" from "layer can't trace symbolically"
    concrete, _ = _build_structs(pairs, concrete_batch=probe_batch)
    retry = _run_abstract(module, concrete, training, policy)
    if retry.ok:
        first = report.diagnostics[0]
        retry.diagnostics.append(Diagnostic(
            path=first.path, layer=first.layer, severity="warning",
            message="traces with a concrete batch but not with a "
                    f"symbolic batch dim ({first.message})"))
        retry.symbolic = False
    return tag(retry)
