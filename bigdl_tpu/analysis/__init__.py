"""bigdl_tpu.analysis — pre-compile static analysis for models and source.

Two passes, both free of XLA compilation:

- **Shape/dtype checking** (:mod:`bigdl_tpu.analysis.shapecheck`): walk any
  :class:`~bigdl_tpu.nn.module.Module` graph under ``jax.eval_shape`` with a
  (symbolic) batch dimension and attribute failures to the exact layer path
  ("``sequential[3]/linear2``") — the JAX-side equivalent of BigDL's typed
  graph-build-time layer errors, instead of a deep XLA trace stack after a
  30-second compile. Exposed as ``Module.check(input_spec)`` and as opt-in
  pre-flight hooks on ``Optimizer`` and ``serving.ModelRegistry``.

- **JAX-pitfall linting** (:mod:`bigdl_tpu.analysis.lint` +
  :mod:`bigdl_tpu.analysis.rules`): a pluggable AST rule registry flagging
  host syncs reachable from traced code, Python branching on traced values,
  per-iteration array construction, jit static-arg mistakes, impure
  ``apply`` methods, host clocks/global RNG in traces, and bare ``except``.
  Findings support ``# bigdl: disable=RULE`` suppressions.

- **Concurrency checks** (:mod:`bigdl_tpu.analysis.concur`): compositional
  lock-discipline inference over the package's own threads — thread-escape
  roots, lock-guarded attribute inference, a package-wide lock-order graph
  with deadlock-cycle detection, blocking calls under held locks, and the
  flag-only signal-handler contract. Same suppression grammar, its own
  ``[concur]`` namespace in ``tools.check``.

- **Compiled-program checks** (:mod:`bigdl_tpu.analysis.hlo` +
  :mod:`bigdl_tpu.analysis.checks` + :mod:`bigdl_tpu.analysis.programs`):
  a structural parser over lowered/compiled XLA text and a pluggable
  check registry verifying the contracts that only exist *after*
  lowering — donated buffers actually aliased, zero collectives at the
  windowed dispatch boundary, ZeRO shardings in place, f32 islands
  inside the precision policy, programs fitting HBM. Lowering/compiling
  only, zero executions.

``python -m bigdl_tpu.tools.check`` runs every pass; the repository
dogfoods it over ``bigdl_tpu`` itself (tests/test_lint_self.py,
tests/test_check_self.py).
"""
from bigdl_tpu.analysis.shapecheck import (Diagnostic, ShapeCheckError,
                                           ShapeReport, check_module, spec)
from bigdl_tpu.analysis.lint import (Finding, available_rules, format_text,
                                     lint_paths, lint_source, to_json)
from bigdl_tpu.analysis.hlo import (HloModule, ProgramFinding, ProgramSpec,
                                    available_checks, parse_hlo, run_checks)
from bigdl_tpu.analysis.concur import (analyze_paths, analyze_source,
                                       available_concur_rules)

__all__ = [
    "Diagnostic", "ShapeCheckError", "ShapeReport", "check_module", "spec",
    "Finding", "available_rules", "format_text", "lint_paths",
    "lint_source", "to_json",
    "HloModule", "ProgramFinding", "ProgramSpec", "available_checks",
    "parse_hlo", "run_checks",
    "analyze_paths", "analyze_source", "available_concur_rules",
]
