"""Static concurrency analyzer: lock-discipline inference over the package.

The framework runs much of its hot path off the main thread — the
MicroBatcher dispatch worker, the DecodeLoop continuous-batching driver,
the AsyncCheckpointWriter, the prefetch stager, fleet replica drivers —
and the bug class this breeds (silent-hang workers, pin leaks from
verdicts read outside the lock, torn multi-attribute rebinds) is
mechanical enough to check statically. This module rides the
:mod:`bigdl_tpu.analysis.lint` engine primitives (``FileContext`` parent
links + alias-aware ``canon()``, the ``# bigdl: disable=`` suppression
grammar, the ``Finding`` record) but registers its own ``[concur]``
namespace, the way :mod:`bigdl_tpu.analysis.hlo` owns ``[hlo]``.

Compositional, per-class inference in the RacerD style — no whole-program
may-alias analysis:

* **thread-escape** — a function is an off-main-thread root when it is
  passed as ``threading.Thread(target=...)``, handed to an executor
  ``.submit``, installed with ``signal.signal``, or named like a known
  worker entry point; reachability propagates through intra-class
  ``self._helper()`` calls and lexical nesting, exactly like the lint
  engine's traced-context analysis.
* **guarded-attribute inference** — per class, attributes written under
  ``with self._lock:`` (in any method outside ``__init__``) are inferred
  lock-guarded. ``*_locked``-suffixed methods run with the caller holding
  the lock by convention: their writes infer guardedness and their
  accesses are exempt.
* **lock-order graph** — ``with``-acquisitions nested lexically or
  through resolvable calls (``self.helper()``, ``self.attr.method()``
  where ``self.attr = SomeClass(...)``) build a directed graph over lock
  *classes* ``Owner.attr``; cycles are deadlock candidates.

Rules (``--rules`` namespace shared with lint/hlo via
``python -m bigdl_tpu.tools.check``):

``unguarded-shared-state``  guarded attr touched by an escaping method
                            outside the lock
``torn-invariant-write``    partial rebind of a multi-attribute invariant
                            (attrs always stored together under the lock)
``lock-order-cycle``        cycle in the package lock-order graph
``blocking-under-lock``     Future.result / queue get-put / thread.join /
                            jax.block_until_ready / subprocess /
                            retry sleeps inside a held-lock region
``signal-handler-impure``   signal handlers must be flag-only (the PR 12
                            GraceHandler contract: simple stores or
                            ``Event.set()``, no locks/IO/jnp)

Suppression is the lint grammar: ``# bigdl: disable=rule`` on (or the
standalone comment line above) the flagged line, stating the invariant
that makes the site safe.
"""
from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import (Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Set, Tuple)

from bigdl_tpu.analysis.lint import (Finding, FileContext,
                                     iter_python_files)

__all__ = ["ConcurRule", "concur_rule", "available_concur_rules",
           "analyze_source", "analyze_paths", "Finding"]

# ------------------------------------------------------------ vocabulary

LOCK_CTORS = {"threading.Lock", "threading.RLock", "threading.Condition"}
EVENT_CTORS = {"threading.Event"}
THREAD_CTORS = {"threading.Thread"}
QUEUE_CTORS = {"queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
               "queue.SimpleQueue"}

# worker entry points by convention: bodies that run off the main thread
# even when the Thread(...) construction lives elsewhere
WORKER_ENTRY_NAMES = frozenset({
    "_dispatch_loop", "_decode_loop", "_read_loop", "_stage_loop",
    "_worker_loop", "_supervised", "_worker", "_control_loop",
    "_deploy_loop"})

# container mutations that count as writes for guarded-attr inference
MUTATORS = frozenset({
    "append", "appendleft", "add", "clear", "discard", "extend",
    "insert", "pop", "popitem", "popleft", "remove", "update",
    "setdefault", "move_to_end", "sort", "reverse"})

# canonical dotted calls that block the calling thread
BLOCKING_CANON = {
    "time.sleep", "jax.block_until_ready", "subprocess.run",
    "subprocess.call", "subprocess.check_call", "subprocess.check_output",
}
# suffix match for package-relative imports of the retry/backoff sleeps
BLOCKING_SUFFIXES = ("faults.retry.retry_call",)

# caller-holds-the-lock convention marker
HELD_UNKNOWN = "*"


# ------------------------------------------------------------- registry

@dataclass
class ConcurRule:
    """A registered concurrency rule: ``fn(pkg)`` yields
    ``(module, node, message)`` findings over the whole package."""

    name: str
    description: str
    fn: Callable[["Package"],
                 Iterator[Tuple["ModuleInfo", ast.AST, str]]]


_CONCUR_RULES: Dict[str, ConcurRule] = {}


def concur_rule(name: str, description: str):
    """Decorator registering a concurrency rule under ``name``."""
    def deco(fn):
        if name in _CONCUR_RULES:
            raise ValueError(f"duplicate concur rule {name!r}")
        _CONCUR_RULES[name] = ConcurRule(name, description, fn)
        return fn
    return deco


def available_concur_rules() -> List[ConcurRule]:
    """All registered concurrency rules, sorted by name."""
    return [_CONCUR_RULES[k] for k in sorted(_CONCUR_RULES)]


# ------------------------------------------------------------ class facts

def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``"X"`` (plain one-level attribute on self)."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _flat_targets(targets: Iterable[ast.AST]) -> Iterator[ast.AST]:
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield e
        else:
            yield t


class ClassInfo:
    """Per-class concurrency facts: lock/event/queue/thread attributes,
    thread-escaping methods, inferred guarded attributes and the
    multi-attribute invariant groups written together under one lock."""

    def __init__(self, ctx: FileContext, node: ast.ClassDef, module: str):
        self.ctx = ctx
        self.node = node
        self.module = module
        self.name = node.name
        self.methods: Dict[str, ast.AST] = {
            m.name: m for m in node.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.lock_attrs: Dict[str, str] = {}
        self.event_attrs: Set[str] = set()
        self.queue_attrs: Set[str] = set()
        self.thread_attrs: Set[str] = set()
        # self.<attr> = SomeClass(...): canonical class name, for
        # resolving cross-object lock acquisition in the order graph
        self.attr_classes: Dict[str, str] = {}
        self._collect_attr_types()
        self.escaping: Set[str] = set()   # filled by ModuleInfo
        self.guarded: Dict[str, str] = {}
        self.groups: List[Tuple[str, frozenset]] = []

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module, self.name)

    def _collect_attr_types(self) -> None:
        for m in self.methods.values():
            for n in ast.walk(m):
                if not isinstance(n, (ast.Assign, ast.AnnAssign)):
                    continue
                value = n.value
                if not isinstance(value, ast.Call):
                    continue
                canon = self.ctx.canon(value.func)
                if canon is None:
                    continue
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in _flat_targets(targets):
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    if canon in LOCK_CTORS:
                        self.lock_attrs[attr] = canon.rsplit(".", 1)[-1]
                    elif canon in EVENT_CTORS:
                        self.event_attrs.add(attr)
                    elif canon in QUEUE_CTORS:
                        self.queue_attrs.add(attr)
                    elif canon in THREAD_CTORS:
                        self.thread_attrs.add(attr)
                    elif canon[:1].isupper() or "." in canon:
                        self.attr_classes.setdefault(attr, canon)

    # ---- lexical lock regions -------------------------------------------
    def with_locks(self, with_node: ast.With) -> List[str]:
        """Lock attrs of ``self`` acquired by one ``with`` statement."""
        out = []
        for item in with_node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and attr in self.lock_attrs:
                out.append(attr)
        return out

    def held_locks(self, node: ast.AST, fn: ast.AST) -> Set[str]:
        """Lock attrs lexically held at ``node`` inside method ``fn``.
        Stops at nested function boundaries (a closure defined under a
        lock is not assumed to run under it); ``*_locked`` methods add
        the :data:`HELD_UNKNOWN` marker (caller holds the lock by
        convention)."""
        held: Set[str] = set()
        cur = self.ctx.parent(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            cur = node if node is fn else self.ctx.parent(node)
        while cur is not None:
            if isinstance(cur, ast.With):
                held.update(self.with_locks(cur))
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                if cur is fn and getattr(fn, "name", "").endswith(
                        "_locked"):
                    held.add(HELD_UNKNOWN)
                break
            cur = self.ctx.parent(cur)
        return held

    # ---- writes ----------------------------------------------------------
    def attr_writes(self, node: ast.AST) -> Iterator[
            Tuple[str, ast.AST, bool]]:
        """``(attr, site, plain_store)`` for every write of a ``self``
        attribute under ``node``: rebinds, subscript stores, deletes and
        known container-mutator calls."""
        for n in ast.walk(node):
            targets: List[ast.AST] = []
            if isinstance(n, ast.Assign):
                targets = list(_flat_targets(n.targets))
            elif isinstance(n, ast.AugAssign):
                targets = [n.target]
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                targets = [n.target]
            elif isinstance(n, ast.Delete):
                targets = list(_flat_targets(n.targets))
            for t in targets:
                attr = _self_attr(t)
                if attr is not None:
                    yield attr, n, not isinstance(n, ast.Delete)
                elif isinstance(t, ast.Subscript):
                    attr = _self_attr(t.value)
                    if attr is not None:
                        yield attr, n, False
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in MUTATORS:
                attr = _self_attr(n.func.value)
                if attr is not None:
                    yield attr, n, False

    def infer_guarded(self) -> None:
        """Infer lock-guarded attributes and invariant groups. ``__init__``
        writes are exempt (happens-before thread start); lock/event/queue/
        thread handles are lifecycle state, never inferred guarded."""
        handles = (set(self.lock_attrs) | self.event_attrs
                   | self.queue_attrs | self.thread_attrs)
        sole_lock = next(iter(self.lock_attrs)) \
            if len(self.lock_attrs) == 1 else None
        for name, m in self.methods.items():
            if name in ("__init__", "__new__"):
                continue
            for attr, site, _plain in self.attr_writes(m):
                if attr in handles or attr in self.guarded:
                    continue
                held = self.held_locks(site, m)
                real = [h for h in held if h != HELD_UNKNOWN]
                if real:
                    self.guarded[attr] = real[0]
                elif HELD_UNKNOWN in held and sole_lock is not None:
                    self.guarded[attr] = sole_lock
        # invariant groups: attrs PLAIN-stored together in one with-block
        seen: Set[Tuple[str, frozenset]] = set()
        for name, m in self.methods.items():
            if name in ("__init__", "__new__"):
                continue
            for w in ast.walk(m):
                if not isinstance(w, ast.With):
                    continue
                locks = self.with_locks(w)
                if not locks:
                    continue
                stored = frozenset(
                    attr for attr, _site, plain in self.attr_writes(w)
                    if plain and attr not in handles)
                if len(stored) >= 2:
                    key = (locks[0], stored)
                    if key not in seen:
                        seen.add(key)
                        self.groups.append(key)


# ----------------------------------------------------------- module facts

def _module_name(path: str) -> str:
    parts = os.path.normpath(path).split(os.sep)
    if "bigdl_tpu" in parts:
        parts = parts[parts.index("bigdl_tpu"):]
        if parts[-1].endswith(".py"):
            parts[-1] = parts[-1][:-3]
        if parts[-1] == "__init__":
            parts.pop()
        return ".".join(parts)
    base = os.path.basename(path)
    return base[:-3] if base.endswith(".py") else base


class ModuleInfo:
    """One parsed file: its classes, thread-escape roots and the signal
    handlers installed from it."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.path = ctx.path
        self.module = _module_name(ctx.path)
        self.classes: Dict[str, ClassInfo] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = ClassInfo(ctx, node, self.module)
        self._defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs.setdefault(node.name, []).append(node)
        self.signal_handlers: List[ast.AST] = []
        self.escaping_ids: Set[int] = set()
        self._find_escape_roots()
        self._propagate_escape()
        for ci in self.classes.values():
            ci.escaping = {name for name, m in ci.methods.items()
                           if id(m) in self.escaping_ids}
            ci.infer_guarded()
        # names bound to bare lock constructions anywhere in the file
        # (module-level / function-local locks, for blocking-under-lock)
        self.lock_names: Set[str] = set()
        for n in ast.walk(ctx.tree):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and ctx.canon(n.value.func) in LOCK_CTORS:
                for t in _flat_targets(n.targets):
                    if isinstance(t, ast.Name):
                        self.lock_names.add(t.id)

    # ---- thread-escape analysis -----------------------------------------
    def _mark(self, arg: ast.AST, cls: Optional[ClassInfo],
              handler: bool = False) -> None:
        """Mark the function behind ``arg`` (a Name, ``self.method`` or
        lambda) as an off-main-thread root."""
        fns: List[ast.AST] = []
        if isinstance(arg, ast.Lambda):
            fns = [arg]
        elif isinstance(arg, ast.Name):
            fns = self._defs.get(arg.id, [])
        else:
            attr = _self_attr(arg)
            if attr is not None:
                if cls is not None and attr in cls.methods:
                    fns = [cls.methods[attr]]
                else:  # self.X outside a resolvable class: any match
                    for ci in self.classes.values():
                        if attr in ci.methods:
                            fns.append(ci.methods[attr])
        for fn in fns:
            self.escaping_ids.add(id(fn))
            if handler:
                self.signal_handlers.append(fn)

    def _enclosing_class(self, node: ast.AST) -> Optional[ClassInfo]:
        cls = self.ctx.enclosing(node, ast.ClassDef)
        return self.classes.get(cls.name) if cls is not None else None

    def _find_escape_roots(self) -> None:
        for call in self.ctx.walk(ast.Call):
            canon = self.ctx.canon(call.func)
            cls = self._enclosing_class(call)
            if canon in THREAD_CTORS:
                for kw in call.keywords:
                    if kw.arg == "target":
                        self._mark(kw.value, cls)
            elif canon == "signal.signal" and len(call.args) >= 2:
                self._mark(call.args[1], cls, handler=True)
            elif isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "submit" and call.args:
                # executor.submit(fn, ...): only when the first argument
                # resolves to a function in this file (data submits to
                # e.g. MicroBatcher.submit stay invisible)
                first = call.args[0]
                if isinstance(first, (ast.Name, ast.Lambda)) \
                        or _self_attr(first) is not None:
                    self._mark(first, cls)
        for ci in self.classes.values():
            for name, m in ci.methods.items():
                if name in WORKER_ENTRY_NAMES:
                    self.escaping_ids.add(id(m))

    def _propagate_escape(self) -> None:
        """Fixpoint: lexical nesting + intra-class self-calls, the same
        propagation shape as the lint engine's traced-context set."""
        changed = True
        while changed:
            changed = False
            for node in self.ctx.walk(ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda):
                if id(node) in self.escaping_ids:
                    continue
                cur = self.ctx.parent(node)
                while cur is not None:
                    if isinstance(cur, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.Lambda)) \
                            and id(cur) in self.escaping_ids:
                        self.escaping_ids.add(id(node))
                        changed = True
                        break
                    cur = self.ctx.parent(cur)
            for ci in self.classes.values():
                for m in ci.methods.values():
                    if id(m) not in self.escaping_ids:
                        continue
                    for call in ast.walk(m):
                        if not isinstance(call, ast.Call):
                            continue
                        attr = _self_attr(call.func)
                        callee = ci.methods.get(attr) if attr else None
                        if callee is not None \
                                and id(callee) not in self.escaping_ids:
                            self.escaping_ids.add(id(callee))
                            changed = True


# -------------------------------------------------------------- package

LockId = Tuple[str, str, str]  # (module, class, lock attr)


def _lock_label(lid: LockId) -> str:
    return f"{lid[1]}.{lid[2]}"


class Package:
    """All modules under analysis + cross-module class resolution and the
    lock-order graph (computed lazily)."""

    def __init__(self, modules: List[ModuleInfo]):
        self.modules = modules
        self.by_canon: Dict[str, ClassInfo] = {}
        by_name: Dict[str, List[ClassInfo]] = {}
        for mi in modules:
            for ci in mi.classes.values():
                self.by_canon[f"{ci.module}.{ci.name}"] = ci
                by_name.setdefault(ci.name, []).append(ci)
        # bare-name resolution only when unambiguous package-wide
        self.by_name: Dict[str, ClassInfo] = {
            n: cis[0] for n, cis in by_name.items() if len(cis) == 1}
        self._summaries: Optional[Dict[Tuple[Tuple[str, str], str],
                                       Set[LockId]]] = None

    def resolve_class(self, canon: str) -> Optional[ClassInfo]:
        ci = self.by_canon.get(canon)
        if ci is not None:
            return ci
        return self.by_name.get(canon.rsplit(".", 1)[-1])

    def _callee(self, ci: ClassInfo, call: ast.Call) \
            -> Optional[Tuple[Tuple[str, str], str]]:
        """Resolve ``self.m()`` and ``self.attr.m()`` call targets to a
        ``(class key, method)`` summary key."""
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        attr = _self_attr(func)
        if attr is not None:
            return (ci.key, attr) if attr in ci.methods else None
        inner = _self_attr(func.value)
        if inner is not None and inner in ci.attr_classes:
            target = self.resolve_class(ci.attr_classes[inner])
            if target is not None and func.attr in target.methods:
                return (target.key, func.attr)
        return None

    def summaries(self) -> Dict[Tuple[Tuple[str, str], str], Set[LockId]]:
        """Fixpoint ``(class, method) -> lock classes acquired``,
        transitively through resolvable calls — the compositional
        summary the lock-order graph is built from."""
        if self._summaries is not None:
            return self._summaries
        summ: Dict[Tuple[Tuple[str, str], str], Set[LockId]] = {}
        all_methods = [(mi, ci, name, m) for mi in self.modules
                       for ci in mi.classes.values()
                       for name, m in ci.methods.items()]
        for _mi, ci, name, m in all_methods:
            acquired: Set[LockId] = set()
            for w in ast.walk(m):
                if isinstance(w, ast.With):
                    for lock in ci.with_locks(w):
                        acquired.add((ci.module, ci.name, lock))
            summ[(ci.key, name)] = acquired
        changed = True
        while changed:
            changed = False
            for _mi, ci, name, m in all_methods:
                s = summ[(ci.key, name)]
                for call in ast.walk(m):
                    if not isinstance(call, ast.Call):
                        continue
                    key = self._callee(ci, call)
                    if key is not None and key in summ:
                        extra = summ[key] - s
                        if extra:
                            s |= extra
                            changed = True
        self._summaries = summ
        return summ

    def lock_edges(self) -> Dict[Tuple[LockId, LockId],
                                 Tuple[ModuleInfo, ast.AST]]:
        """Directed lock-order edges ``held -> acquired`` with one
        witness site each: lexically nested ``with`` blocks plus calls
        made under a held lock whose summary acquires other locks."""
        summ = self.summaries()
        edges: Dict[Tuple[LockId, LockId],
                    Tuple[ModuleInfo, ast.AST]] = {}

        def add(src: LockId, dst: LockId, mi: ModuleInfo,
                node: ast.AST) -> None:
            if src != dst:
                edges.setdefault((src, dst), (mi, node))

        for mi in self.modules:
            for ci in mi.classes.values():
                for m in ci.methods.values():
                    self._walk_edges(mi, ci, m, m, [], add, summ)
        return edges

    def _walk_edges(self, mi, ci, fn, node, held, add, summ) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                # a closure starts with no lexically held locks
                self._walk_edges(mi, ci, fn, child, [], add, summ)
                continue
            inner = held
            if isinstance(child, ast.With):
                acquired = [(ci.module, ci.name, lock)
                            for lock in ci.with_locks(child)]
                for h in held:
                    for a in acquired:
                        add(h, a, mi, child)
                inner = held + acquired
            if isinstance(child, ast.Call) and held:
                key = self._callee(ci, child)
                if key is not None:
                    for dst in summ.get(key, ()):
                        if dst not in held:
                            for h in held:
                                add(h, dst, mi, child)
            self._walk_edges(mi, ci, fn, child, inner, add, summ)


def _find_cycles(edges: Dict[Tuple[LockId, LockId], object]) \
        -> List[List[LockId]]:
    """Distinct simple cycles in the lock-order graph (one per cyclic
    strongly-connected region, canonicalized by rotation)."""
    adj: Dict[LockId, List[LockId]] = {}
    for (src, dst) in edges:
        adj.setdefault(src, []).append(dst)
        adj.setdefault(dst, [])
    cycles: List[List[LockId]] = []
    seen: Set[Tuple[LockId, ...]] = set()
    for start in sorted(adj):
        stack: List[Tuple[LockId, List[LockId]]] = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(adj.get(node, ())):
                if nxt == start and len(path) >= 1 and len(path) <= 8:
                    i = path.index(min(path))
                    canonical = tuple(path[i:] + path[:i])
                    if canonical not in seen:
                        seen.add(canonical)
                        cycles.append(list(canonical))
                elif nxt not in path and len(path) < 8 and nxt > start:
                    stack.append((nxt, path + [nxt]))
    return cycles


# ---------------------------------------------------------------- rules

def _escaping_checked_methods(ci: ClassInfo) -> Iterator[
        Tuple[str, ast.AST]]:
    """Escaping methods whose bodies are subject to the unlocked-access
    rules: ``__init__`` (happens-before thread start) and
    ``*_locked``-suffixed methods (caller holds the lock) are exempt."""
    for name in sorted(ci.escaping):
        if name in ("__init__", "__new__") or name.endswith("_locked"):
            continue
        yield name, ci.methods[name]


@concur_rule("unguarded-shared-state",
             "lock-guarded attribute accessed off-thread without the lock")
def unguarded_shared_state(pkg: "Package"):
    for mi in pkg.modules:
        for ci in mi.classes.values():
            if not ci.lock_attrs or not ci.guarded or not ci.escaping:
                continue
            for mname, m in _escaping_checked_methods(ci):
                for node in ast.walk(m):
                    attr = _self_attr(node)
                    if attr is None or attr not in ci.guarded:
                        continue
                    lock = ci.guarded[attr]
                    held = ci.held_locks(node, m)
                    if lock in held or HELD_UNKNOWN in held:
                        continue
                    yield mi, node, (
                        f"`self.{attr}` is guarded by `self.{lock}` "
                        f"(written under it elsewhere in {ci.name}) but "
                        f"`{mname}` runs off the main thread and touches "
                        f"it without the lock; wrap the access in `with "
                        f"self.{lock}:` or add `# bigdl: disable="
                        f"unguarded-shared-state` stating the invariant")


@concur_rule("torn-invariant-write",
             "partial rebind of a multi-attribute lock invariant")
def torn_invariant_write(pkg: "Package"):
    for mi in pkg.modules:
        for ci in mi.classes.values():
            if not ci.groups:
                continue
            # (a) an escaping method rebinds part of an invariant group
            # outside the lock: readers can observe the torn pair
            for mname, m in _escaping_checked_methods(ci):
                for stmt in ast.walk(m):
                    if not isinstance(stmt, (ast.Assign, ast.AugAssign,
                                             ast.AnnAssign)):
                        continue
                    targets = stmt.targets \
                        if isinstance(stmt, ast.Assign) else [stmt.target]
                    wrote = {a for a in
                             (_self_attr(t)
                              for t in _flat_targets(targets))
                             if a is not None}
                    if not wrote:
                        continue
                    if ci.held_locks(stmt, m):
                        continue
                    for lock, group in ci.groups:
                        part = wrote & group
                        if part and part < group:
                            missing = ", ".join(sorted(group - part))
                            yield mi, stmt, (
                                f"partial unlocked write of invariant "
                                f"({', '.join(sorted(group))}) — "
                                f"{ci.name} stores these together under "
                                f"`self.{lock}`; rebinding only "
                                f"{', '.join(sorted(part))} (not "
                                f"{missing}) lets readers see a torn "
                                f"pair; rebind atomically under the "
                                f"lock")
            # (b) one method splits an invariant group across separate
            # lock acquisitions: the window between them is a torn state
            for mname, m in ci.methods.items():
                if mname in ("__init__", "__new__"):
                    continue
                blocks: List[Tuple[ast.With, Set[str]]] = []
                for w in ast.walk(m):
                    if isinstance(w, ast.With) and ci.with_locks(w):
                        stored = {a for a, _s, plain in ci.attr_writes(w)
                                  if plain}
                        blocks.append((w, stored))
                for lock, group in ci.groups:
                    hits = [(w, s & group) for w, s in blocks if s & group]
                    union: Set[str] = set()
                    for _w, s in hits:
                        union |= s
                    if len(hits) >= 2 and len(union) >= 2 \
                            and not any(s == group for _w, s in hits):
                        yield mi, hits[1][0], (
                            f"invariant ({', '.join(sorted(group))}) "
                            f"updated across separate `with self.{lock}:`"
                            f" blocks in `{mname}`; the window between "
                            f"acquisitions exposes a torn state — "
                            f"update the group under one acquisition")


@concur_rule("lock-order-cycle",
             "cycle in the package-wide lock acquisition-order graph")
def lock_order_cycle(pkg: "Package"):
    edges = pkg.lock_edges()
    for cycle in _find_cycles(edges):
        ring = cycle + [cycle[0]]
        legs = []
        witness_mi: Optional[ModuleInfo] = None
        witness_node: Optional[ast.AST] = None
        for src, dst in zip(ring, ring[1:]):
            mi, node = edges[(src, dst)]
            if witness_mi is None:
                witness_mi, witness_node = mi, node
            legs.append(f"{_lock_label(src)} -> {_lock_label(dst)} "
                        f"({mi.path}:{getattr(node, 'lineno', 1)})")
        assert witness_mi is not None and witness_node is not None
        yield witness_mi, witness_node, (
            "lock-order cycle: " + "; ".join(legs)
            + " — threads taking these locks in different orders can "
              "deadlock; pick one global order")


def _call_desc(ctx: FileContext, call: ast.Call) -> str:
    canon = ctx.canon(call.func)
    if canon:
        return canon
    if isinstance(call.func, ast.Attribute):
        return f".{call.func.attr}"
    return "<call>"


def _local_assigned_from(ctx: FileContext, fn: ast.AST, name: str,
                         ctors: Set[str]) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and ctx.canon(n.value.func) in ctors:
            for t in _flat_targets(n.targets):
                if isinstance(t, ast.Name) and t.id == name:
                    return True
    return False


@concur_rule("blocking-under-lock",
             "blocking call (future/queue/join/sync/sleep) in a "
             "held-lock region")
def blocking_under_lock(pkg: "Package"):
    for mi in pkg.modules:
        ctx = mi.ctx
        for ci in mi.classes.values():
            if not ci.lock_attrs:
                continue
            for mname, m in ci.methods.items():
                for call in ast.walk(m):
                    if not isinstance(call, ast.Call):
                        continue
                    held = ci.held_locks(call, m)
                    if not held:
                        continue
                    reason = _blocking_reason(mi, ci, m, call, held)
                    if reason is None:
                        continue
                    real = sorted(h for h in held if h != HELD_UNKNOWN)
                    where = f"under `with self.{real[0]}:`" if real else \
                        "in a `*_locked` method (caller holds the lock)"
                    yield mi, call, (
                        f"{reason} {where} blocks every thread waiting "
                        f"on the lock; move it outside the held region")


def _blocking_reason(mi: ModuleInfo, ci: ClassInfo, fn: ast.AST,
                     call: ast.Call, held: Set[str]) -> Optional[str]:
    ctx = mi.ctx
    canon = ctx.canon(call.func)
    if canon in BLOCKING_CANON or (
            canon and canon.endswith(BLOCKING_SUFFIXES)):
        return f"blocking call `{canon}(...)`"
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    recv = call.func.value
    recv_attr = _self_attr(recv)
    if attr == "result":
        return "`Future.result()`"
    if attr in ("get", "put"):
        blocked = True
        for kw in call.keywords:
            if kw.arg == "block" and isinstance(kw.value, ast.Constant) \
                    and kw.value.value is False:
                blocked = False
        is_queue = (recv_attr in ci.queue_attrs) or (
            isinstance(recv, ast.Name) and _local_assigned_from(
                ctx, fn, recv.id, QUEUE_CTORS))
        if is_queue and blocked:
            return f"blocking `queue.{attr}()`"
        return None
    if attr == "join":
        is_thread = (recv_attr in ci.thread_attrs) or (
            isinstance(recv, ast.Name) and _local_assigned_from(
                ctx, fn, recv.id, THREAD_CTORS))
        if is_thread or (recv_attr or "").endswith("thread") \
                or (isinstance(recv, ast.Name)
                    and recv.id.endswith("thread")):
            return "`thread.join()`"
        if recv_attr in ci.queue_attrs:
            return "`queue.join()`"
        return None
    if attr in ("wait", "wait_for"):
        if recv_attr is not None and recv_attr in held:
            return None  # cond.wait() on the HELD condition releases it
        if recv_attr in ci.event_attrs or (
                isinstance(recv, ast.Name) and _local_assigned_from(
                    ctx, fn, recv.id, EVENT_CTORS)):
            return f"`Event.{attr}()`"
        if recv_attr is not None and \
                ci.lock_attrs.get(recv_attr) == "Condition":
            return f"`Condition.{attr}()` on a condition this region " \
                   "does not hold"
        if canon and canon.startswith("subprocess."):
            return f"`{canon}(...)`"
        return None
    if attr in ("communicate",) and canon is None:
        return "`Popen.communicate()`" \
            if (recv_attr or "").startswith(("proc", "_proc")) or (
                isinstance(recv, ast.Name)
                and recv.id.startswith("proc")) else None
    if attr == "block_until_ready":
        return "`.block_until_ready()`"
    return None


_ALLOWED_VALUES = (ast.Constant, ast.Name, ast.Attribute)


def _handler_stmt_ok(stmt: ast.stmt) -> bool:
    if isinstance(stmt, (ast.Pass, ast.Global, ast.Nonlocal)):
        return True
    if isinstance(stmt, ast.Return):
        return stmt.value is None \
            or isinstance(stmt.value, _ALLOWED_VALUES)
    if isinstance(stmt, ast.Expr):
        v = stmt.value
        if isinstance(v, ast.Constant):  # docstring
            return True
        return (isinstance(v, ast.Call)
                and isinstance(v.func, ast.Attribute)
                and v.func.attr == "set"
                and not v.args and not v.keywords)
    if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
        value = stmt.value
        if value is None:
            return True
        if isinstance(value, ast.Tuple):
            return all(isinstance(e, _ALLOWED_VALUES)
                       for e in value.elts)
        return isinstance(value, _ALLOWED_VALUES)
    if isinstance(stmt, ast.If):
        return _expr_call_free(stmt.test) \
            and all(_handler_stmt_ok(s) for s in stmt.body) \
            and all(_handler_stmt_ok(s) for s in stmt.orelse)
    return False


def _expr_call_free(expr: ast.AST) -> bool:
    return not any(isinstance(n, ast.Call) for n in ast.walk(expr))


@concur_rule("signal-handler-impure",
             "signal handler does more than set a flag (GraceHandler "
             "contract)")
def signal_handler_impure(pkg: "Package"):
    for mi in pkg.modules:
        for fn in mi.signal_handlers:
            name = getattr(fn, "name", "<lambda>")
            if isinstance(fn, ast.Lambda):
                body = fn.body
                ok = (isinstance(body, ast.Call)
                      and isinstance(body.func, ast.Attribute)
                      and body.func.attr == "set"
                      and not body.args and not body.keywords) \
                    or isinstance(body, _ALLOWED_VALUES)
                if not ok:
                    yield mi, fn, (
                        "signal handler lambda must only set a flag "
                        "(`event.set()`); anything else — locks, IO, "
                        "jnp, telemetry — is unsafe at interrupt time")
                continue
            for stmt in fn.body:
                if not _handler_stmt_ok(stmt):
                    yield mi, stmt, (
                        f"signal handler `{name}` must be flag-only "
                        f"(simple stores or `event.set()`); this "
                        f"statement can deadlock or re-enter at "
                        f"interrupt time — set a flag here and act on "
                        f"it from the main loop")


# --------------------------------------------------------------- engine

def _run(pkg: Package,
         rules: Optional[Sequence[str]] = None) -> List[Finding]:
    if rules:
        unknown = [r for r in rules if r not in _CONCUR_RULES]
        if unknown:
            raise KeyError(unknown[0])
        selected = [_CONCUR_RULES[r] for r in rules]
    else:
        selected = available_concur_rules()
    findings: List[Finding] = []
    seen = set()
    for r in selected:
        for mi, node, message in r.fn(pkg):
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
            key = (r.name, mi.path, line, col, message)
            if key in seen:
                continue
            seen.add(key)
            on_line = mi.ctx.line_disables.get(line, set())
            suppressed = (r.name in mi.ctx.file_disables
                          or "all" in mi.ctx.file_disables
                          or r.name in on_line or "all" in on_line)
            findings.append(Finding(r.name, mi.path, line, col, message,
                                    suppressed))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_source(source: str, path: str = "<string>",
                   rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Analyze one source string (single-module package view)."""
    try:
        ctx = FileContext(source, path)
    except SyntaxError as e:
        return [Finding("parse-error", path, e.lineno or 1, 0,
                        f"could not parse: {e.msg}")]
    return _run(Package([ModuleInfo(ctx)]), rules)


def analyze_paths(paths: Iterable[str],
                  rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Analyze every .py file under ``paths`` as ONE package — the
    lock-order graph spans files; unknown rule names raise KeyError."""
    modules: List[ModuleInfo] = []
    findings: List[Finding] = []
    for fp in iter_python_files(paths):
        with open(fp, encoding="utf-8") as f:
            source = f.read()
        try:
            ctx = FileContext(source, fp)
        except SyntaxError as e:
            findings.append(Finding("parse-error", fp, e.lineno or 1, 0,
                                    f"could not parse: {e.msg}"))
            continue
        modules.append(ModuleInfo(ctx))
    findings.extend(_run(Package(modules), rules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
