"""Recovery-path rules: retry loops that can't actually recover.

A retry loop that catches ``Exception`` and sleeps a FIXED interval
has two failure modes this package just paid to remove from its own
optimizer: structural errors (wrong types, shape mismatches) replay
identically on every attempt — the loop burns its budget re-raising
the same diagnostic — and a fleet of workers retrying on the same
fixed clock stampedes whatever dependency just recovered. The
sanctioned pattern is classified retry with exponential backoff +
jitter (``bigdl_tpu.faults.retry``); deliberate fixed-sleep sites
carry an auditable ``# bigdl: disable=retry-no-backoff``.
"""
from __future__ import annotations

import ast

from bigdl_tpu.analysis.lint import FileContext, rule


def _catches_broadly(handler: ast.ExceptHandler) -> bool:
    """``except:`` / ``except Exception`` / ``except (..., Exception)``
    — the catch-everything shapes a retry loop wraps its body in."""
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    for n in names:
        if isinstance(n, ast.Name) and n.id in ("Exception",
                                                "BaseException"):
            return True
        if isinstance(n, ast.Attribute) and n.attr in ("Exception",
                                                       "BaseException"):
            return True
    return False


def _dotted(node: ast.AST):
    """``self.delay`` -> "self.delay" (None for non-name chains)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _loop_bound_names(loop: ast.AST) -> set:
    """Names (and dotted attribute chains like ``self.delay``)
    assigned anywhere in the loop body — a sleep over one of these is
    (potentially) a computed, growing delay, not a fixed interval."""
    bound = set()
    for node in ast.walk(loop):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                d = _dotted(t)
                if d is not None:
                    bound.add(d)
                for e in ast.walk(t):
                    if isinstance(e, ast.Name):
                        bound.add(e.id)
        elif isinstance(node, (ast.For, ast.comprehension)):
            for e in ast.walk(node.target):
                if isinstance(e, ast.Name):
                    bound.add(e.id)
    return bound


def _is_fixed_interval(arg: ast.AST, loop_bound: set) -> bool:
    """A sleep argument that cannot change across attempts: a literal,
    an attribute never reassigned in the loop
    (``self.retry_interval_s``, the config-knob shape — but not
    ``self.delay`` after ``self.delay *= 2``), or a name the loop
    never rebinds."""
    if isinstance(arg, ast.Constant):
        return True
    if isinstance(arg, ast.Attribute):
        d = _dotted(arg)
        return d is None or d not in loop_bound
    if isinstance(arg, ast.Name):
        return arg.id not in loop_bound
    return False


def _checkpoint_surface(ctx: FileContext) -> bool:
    """True when the file participates in the checkpoint surface: it
    imports ``bigdl_tpu.utils.serialization`` or ``bigdl_tpu.elastic``
    (writers, the optimizer's checkpoint call sites, chaos/bench
    harnesses) — the files whose host loops are the optimizer hot path
    a blocking copy would stall."""
    mods = ("bigdl_tpu.utils.serialization", "bigdl_tpu.elastic")
    for node in ctx.walk(ast.Import):
        if any(a.name.startswith(mods) for a in node.names):
            return True
    for node in ctx.walk(ast.ImportFrom):
        if node.module and node.module.startswith(mods):
            return True
    return False


@rule("blocking-copy-in-checkpoint",
      "blocking device->host copy on the checkpointing hot path")
def blocking_copy_in_checkpoint(ctx: FileContext):
    """Flags ``jax.device_get(...)`` — and ``np.asarray(x)`` over a
    per-iteration device-ish result — inside non-traced host loops of
    checkpoint-surface files (they import
    ``bigdl_tpu.utils.serialization`` or ``bigdl_tpu.elastic``).

    A checkpoint that fetches leaves one blocking copy at a time
    serializes the whole device->host sweep onto the step loop — the
    stall async checkpointing exists to remove. The sanctioned
    snapshot point (``elastic.checkpoint.snapshot_tree``) kicks every
    copy off with ``copy_to_host_async`` FIRST and drains them once;
    deliberate host fetches in a loop carry
    ``# bigdl: disable=blocking-copy-in-checkpoint`` so each one is
    auditable."""
    from bigdl_tpu.analysis.rules.perf import (_fresh_call_names,
                                               _imports_jax)
    if not _imports_jax(ctx) or not _checkpoint_surface(ctx):
        return
    for loop in ctx.walk(ast.For, ast.While):
        if ctx.in_traced(loop):
            continue
        body = []
        # loop.body only: a For header's iterator expression
        # (`for leaf in jax.device_get(tree):`) evaluates ONCE — a
        # legitimate up-front materialization, not a per-iteration copy
        stack = list(loop.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.For, ast.While)):
                continue  # other scopes / the inner loop's own finding
            body.append(node)
            stack.extend(ast.iter_child_nodes(node))
        fresh = _fresh_call_names(ctx, body)
        for node in body:
            if not isinstance(node, ast.Call):
                continue
            c = ctx.canon(node.func)
            if c == "jax.device_get":
                yield node, (
                    "`jax.device_get` every loop iteration is a "
                    "blocking device->host copy on the checkpoint hot "
                    "path; snapshot through "
                    "elastic.checkpoint.snapshot_tree (async D2H "
                    "sweep, background write) or mark a deliberate "
                    "fetch with "
                    "`# bigdl: disable=blocking-copy-in-checkpoint`")
            elif c == "numpy.asarray" and node.args:
                arg_names = {n.id for n in ast.walk(node.args[0])
                             if isinstance(n, ast.Name)}
                if arg_names & fresh:
                    yield node, (
                        "`np.asarray` over a per-iteration device "
                        "result blocks the host once per leaf — the "
                        "serial-fetch checkpoint stall; start every "
                        "copy with copy_to_host_async and drain once "
                        "(elastic.checkpoint.snapshot_tree), or mark "
                        "a sanctioned point with "
                        "`# bigdl: disable=blocking-copy-in-checkpoint`")


@rule("retry-no-backoff",
      "broad-except retry loop sleeping a fixed interval")
def retry_no_backoff(ctx: FileContext):
    """Flags ``except Exception`` (or broader) handlers inside a loop
    whose recovery is ``time.sleep(<fixed interval>)`` — a constant,
    an attribute like ``self.retry_interval_s``, or a name the loop
    never rebinds. Computed delays (``time.sleep(delay)`` where the
    handler assigns ``delay``) pass: that is the backoff pattern."""
    for loop in ctx.walk(ast.For, ast.While):
        loop_bound = None
        for node in ast.walk(loop):
            if not isinstance(node, ast.ExceptHandler) \
                    or not _catches_broadly(node):
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call) or not call.args:
                    continue
                if ctx.canon(call.func) != "time.sleep":
                    continue
                if loop_bound is None:
                    loop_bound = _loop_bound_names(loop)
                if _is_fixed_interval(call.args[0], loop_bound):
                    yield call, (
                        "retry loop catches Exception and sleeps a "
                        "fixed interval: structural errors replay "
                        "identically (classify and fail fast) and "
                        "synchronized retriers stampede — use "
                        "faults.retry.retry_call / backoff_delay "
                        "(exponential backoff + jitter), or mark a "
                        "deliberate fixed sleep with `# bigdl: "
                        "disable=retry-no-backoff`")
