"""Purity rules: impure ``Module.apply`` and module-global RNG state."""
from __future__ import annotations

import ast

from bigdl_tpu.analysis.lint import FileContext, rule

# the pure-functional trace surface of the Module contract: mutating self
# here is at best a silent no-op under jit (the traced python runs once)
# and at worst a leaked-tracer error
_PURE_METHODS = {"apply", "forward_fn"}

# module-global numpy RNG entry points (shared mutable state; reseeding
# races across callers and breaks reproducibility)
_GLOBAL_NP = {
    "seed", "rand", "randn", "randint", "random", "random_sample",
    "ranf", "sample", "choice", "shuffle", "permutation", "uniform",
    "normal", "standard_normal", "binomial", "poisson", "beta", "gamma",
    "exponential", "get_state", "set_state",
}
_GLOBAL_STDLIB = {
    "seed", "random", "randint", "randrange", "uniform", "choice",
    "choices", "shuffle", "sample", "gauss", "getrandbits", "betavariate",
    "normalvariate",
}


@rule("apply-mutates-self",
      "Module.apply/forward_fn mutates self (impure trace surface)")
def apply_mutates_self(ctx: FileContext):
    for cls in ctx.walk(ast.ClassDef):
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef) \
                    or fn.name not in _PURE_METHODS:
                continue
            if not fn.args.args or fn.args.args[0].arg != "self":
                continue
            for node in ast.walk(fn):
                targets = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                elif isinstance(node, ast.Delete):
                    targets = node.targets
                for t in targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        base = t.value
                        while isinstance(base, (ast.Attribute,
                                                ast.Subscript)):
                            base = base.value
                        if isinstance(base, ast.Name) \
                                and base.id == "self":
                            yield node, (
                                f"`{fn.name}` assigns to `self` — the "
                                "traced python runs ONCE at compile "
                                "time, so the mutation silently "
                                "desyncs from execution; return new "
                                "state instead")


@rule("global-rng",
      "module-global RNG state (np.random.*/random.*)")
def global_rng(ctx: FileContext):
    for node in ctx.walk(ast.Call):
        c = ctx.canon(node.func)
        if c is None:
            continue
        parts = c.split(".")
        if c.startswith("numpy.random.") and len(parts) == 3 \
                and parts[2] in _GLOBAL_NP:
            yield node, (
                f"`{c}` mutates/reads the process-global numpy RNG; "
                "use a seeded np.random.RandomState (see "
                "bigdl_tpu.tools.synthetic for synthetic data) or "
                "bigdl_tpu.utils.random.RandomGenerator")
        elif parts[0] == "random" and len(parts) == 2 \
                and parts[1] in _GLOBAL_STDLIB \
                and "random" in ctx.aliases \
                and ctx.aliases["random"] == "random":
            yield node, (
                f"`{c}` uses the global stdlib RNG; use a seeded "
                "random.Random(seed) or numpy RandomState")
