"""Rules about ``jax.jit`` call sites and host-loop dispatch churn."""
from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from bigdl_tpu.analysis.lint import FileContext, rule

# jnp constructors whose per-iteration use in a HOST loop re-dispatches
# (and, with changing shapes, re-compiles) every pass
_CONSTRUCTORS = {
    "jax.numpy." + n for n in (
        "array", "asarray", "zeros", "ones", "full", "empty", "arange",
        "linspace", "eye", "identity", "tri", "zeros_like", "ones_like",
        "full_like", "empty_like")
} | {"jax.device_put"}


def _loop_bound_names(loop: ast.AST) -> set:
    """Names that change per iteration: loop targets + names assigned in
    the body."""
    names = set()
    targets = [loop.target] if isinstance(loop, ast.For) else []
    for node in ast.walk(loop):
        if isinstance(node, ast.Assign):
            targets.extend(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets.append(node.target)
        elif isinstance(node, ast.comprehension):
            targets.append(node.target)
    for t in targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name):
                names.add(n.id)
    return names


@rule("jnp-in-host-loop",
      "loop-invariant jnp array construction in a host loop")
def jnp_in_host_loop(ctx: FileContext):
    for loop in ctx.walk(ast.For, ast.While):
        if ctx.in_traced(loop) or ctx.enclosing(
                loop, ast.FunctionDef, ast.AsyncFunctionDef) is None:
            continue  # traced loops unroll; module-level loops run once
        bound = _loop_bound_names(loop)
        stack: List[ast.AST] = list(ast.iter_child_nodes(loop))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                continue  # defined per-iteration but not necessarily run
            if isinstance(node, ast.Call):
                c = ctx.canon(node.func)
                if c in _CONSTRUCTORS:
                    # per-item constructions (args depend on the loop
                    # iteration) are intentional; only the loop-INVARIANT
                    # ones are pure per-iteration dispatch waste
                    arg_names = {
                        n.id for a in list(node.args)
                        + [kw.value for kw in node.keywords]
                        for n in ast.walk(a) if isinstance(n, ast.Name)}
                    if not (arg_names & bound):
                        yield node, (
                            f"loop-invariant `{c}` inside a host loop "
                            "dispatches to the device every iteration; "
                            "hoist it out of the loop (or move the loop "
                            "into jit/lax.scan)")
            stack.extend(ast.iter_child_nodes(node))


# --------------------------------------------------------- jit static args

def _jit_call(ctx: FileContext, node: ast.Call) -> Optional[ast.Call]:
    """The jax.jit(...) call carried by ``node`` (direct or through
    functools.partial(jax.jit, ...)); None otherwise."""
    c = ctx.canon(node.func)
    if c == "jax.jit":
        return node
    if c == "functools.partial" and node.args \
            and ctx.canon(node.args[0]) == "jax.jit":
        return node
    return None


def _literal_ints(node: ast.AST) -> Optional[List[int]]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if not (isinstance(e, ast.Constant)
                    and isinstance(e.value, int)):
                return None
            out.append(e.value)
        return out
    return None


def _positional_params(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


def _static_usage(fn: ast.AST, param: str):
    """Places where ``param`` must be a Python value: range(), string
    compares, truthiness tests — traced arguments break all three."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "range":
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id == param:
                    yield node, f"`range({param})`"
        elif isinstance(node, ast.Compare) \
                and isinstance(node.left, ast.Name) \
                and node.left.id == param \
                and any(isinstance(c, ast.Constant)
                        and isinstance(c.value, str)
                        for c in node.comparators):
            yield node, f"comparing `{param}` to a string"
        elif isinstance(node, (ast.If, ast.While)) \
                and isinstance(node.test, ast.Name) \
                and node.test.id == param:
            yield node, f"`if {param}:` truthiness"


@rule("jit-static-args",
      "missing/invalid/unhashable static arguments at a jax.jit site")
def jit_static_args(ctx: FileContext):
    defs = {}
    for fn in ctx.walk(ast.FunctionDef, ast.AsyncFunctionDef):
        defs.setdefault(fn.name, fn)

    # jitted-callable bindings: f = jax.jit(g, static_argnums=...), so
    # call sites of f can be screened for unhashable static values
    jitted_bindings = {}

    sites: List[Tuple[ast.Call, Optional[ast.AST]]] = []
    for node in ctx.walk(ast.Call):
        call = _jit_call(ctx, node)
        if call is None:
            continue
        wrapped = None
        args = call.args[1:] if ctx.canon(call.func) == "functools.partial" \
            else call.args
        if args and isinstance(args[0], ast.Name):
            wrapped = defs.get(args[0].id)
        parent = ctx.parent(node)
        if wrapped is None and isinstance(
                parent, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node in parent.decorator_list:
            wrapped = parent
        sites.append((call, wrapped))
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            jitted_bindings[parent.targets[0].id] = call

    for call, wrapped in sites:
        static_nums: Set[int] = set()
        static_names: Set[str] = set()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                ints = _literal_ints(kw.value)
                if ints is None:
                    if not isinstance(kw.value, ast.Name):
                        yield kw.value, (
                            "static_argnums must be int indices; for "
                            "names use static_argnames")
                    continue
                static_nums.update(ints)
                if wrapped is not None:
                    n = len(_positional_params(wrapped))
                    bad = [i for i in ints if i >= n or i < -n]
                    if bad:
                        yield kw.value, (
                            f"static_argnums {bad} out of range for "
                            f"`{wrapped.name}` ({n} positional args)")
            elif kw.arg == "static_argnames":
                if isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    static_names.add(kw.value.value)
                elif isinstance(kw.value, (ast.Tuple, ast.List)):
                    static_names.update(
                        e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, str))
        if wrapped is None:
            continue
        params = _positional_params(wrapped)
        for i, p in enumerate(params):
            if p in ("self", "cls") or i in static_nums \
                    or p in static_names:
                continue
            for node, how in _static_usage(wrapped, p):
                yield node, (
                    f"jitted `{wrapped.name}` uses argument `{p}` as a "
                    f"Python value ({how}) but it is not in "
                    "static_argnums/static_argnames — this raises a "
                    "TracerConversionError when called")

    # unhashable values passed at static positions of a jitted binding
    for node in ctx.walk(ast.Call):
        if not isinstance(node.func, ast.Name):
            continue
        call = jitted_bindings.get(node.func.id)
        if call is None:
            continue
        nums = set()
        for kw in call.keywords:
            if kw.arg == "static_argnums":
                nums.update(_literal_ints(kw.value) or [])
        for i in nums:
            if 0 <= i < len(node.args) and isinstance(
                    node.args[i], (ast.List, ast.Dict, ast.Set)):
                yield node.args[i], (
                    f"unhashable literal at static position {i} of "
                    f"jitted `{node.func.id}`; static arguments must "
                    "be hashable (use a tuple)")


# --------------------------------------------------------- use after donate

def _donate_bindings(ctx: FileContext):
    """``name -> set of donated positional indices`` for bindings of
    the form ``f = jax.jit(g, donate_argnums=...)`` (literal ints)."""
    out = {}
    for node in ctx.walk(ast.Call):
        call = _jit_call(ctx, node)
        if call is None:
            continue
        donated: Set[int] = set()
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                donated.update(_literal_ints(kw.value) or [])
        if not donated:
            continue
        parent = ctx.parent(node)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            out[parent.targets[0].id] = donated
    return out


def _store_lines(fn: ast.AST, name: str) -> List[int]:
    """Line numbers where ``name`` is (re)bound inside ``fn``."""
    lines = []
    for node in ast.walk(fn):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.For):
            targets = [node.target]
        elif isinstance(node, ast.withitem) and node.optional_vars:
            targets = [node.optional_vars]
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name) and n.id == name:
                    lines.append(n.lineno)
    return lines


@rule("use-after-donate",
      "a buffer donated to a jitted call is read afterwards — its "
      "memory now belongs to the program's outputs")
def use_after_donate(ctx: FileContext):
    donate_bindings = _donate_bindings(ctx)
    if not donate_bindings:
        return
    for call in ctx.walk(ast.Call):
        if not isinstance(call.func, ast.Name):
            continue
        donated = donate_bindings.get(call.func.id)
        if not donated:
            continue
        fn = ctx.enclosing(call, ast.FunctionDef, ast.AsyncFunctionDef)
        if fn is None:
            continue
        # names the call's own statement rebinds (p, o, m, loss =
        # step(p, o, m, ...)) are exonerated — the optimizer's pattern
        stmt = ctx.parent(call)
        rebound: Set[str] = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        rebound.add(n.id)
        for i in sorted(donated):
            if i >= len(call.args) \
                    or not isinstance(call.args[i], ast.Name):
                continue
            var = call.args[i].id
            if var in rebound:
                continue
            stores = _store_lines(fn, var)
            # "after the call" means past its LAST line — a wrapped
            # call's own continuation-line arguments are not reads
            call_end = getattr(call, "end_lineno", None) or call.lineno
            for node in ast.walk(fn):
                if not (isinstance(node, ast.Name) and node.id == var
                        and isinstance(node.ctx, ast.Load)
                        and node.lineno > call_end):
                    continue
                # an intervening rebind (to the call result or a fresh
                # value) makes the later read fine
                if any(call_end < s <= node.lineno for s in stores):
                    continue
                yield node, (
                    f"`{var}` was donated (donate_argnums position "
                    f"{i}) to jitted `{call.func.id}` on line "
                    f"{call.lineno} and is read here; a donated "
                    "buffer is invalidated by the call — rebind the "
                    "name to the call's result (as the Optimizer "
                    "does) or drop it from donate_argnums")
                break  # one finding per donated name per call
