"""Built-in lint rules. Importing this package registers every rule with
the :mod:`bigdl_tpu.analysis.lint` registry; third-party rules register
the same way (the ``@rule`` decorator), so the set is pluggable.

Shipped rules:

- ``host-sync`` — ``float()``/``.item()``/``np.asarray`` on traced values
- ``traced-branch`` — Python ``if``/``while`` on traced values
- ``jnp-in-host-loop`` — per-iteration array construction in host loops
- ``jit-static-args`` — missing/invalid/unhashable jit static arguments
- ``apply-mutates-self`` — impure ``Module.apply``/``forward_fn``
- ``host-state-in-trace`` — clocks / host RNG baked into traces
- ``global-rng`` — module-global ``np.random``/``random`` state
- ``bare-except`` — bare ``except:`` handlers
- ``sync-in-loop`` — per-iteration host-device sync in host step loops
- ``gather-in-step-loop`` — loop-invariant collectives in host step loops
- ``retry-no-backoff`` — broad-except retry loops with fixed sleeps
- ``unseeded-shuffle`` — data-path shuffles without a seeded Generator
- ``metric-label-cardinality`` — metric labels from loop vars / request ids
- ``raw-pallas-call`` — pallas kernels invoked outside bigdl_tpu/kernels/
"""
from bigdl_tpu.analysis.rules import (data, jit_calls, perf, purity,
                                      robust, style, telemetry, traced)

__all__ = ["data", "jit_calls", "perf", "purity", "robust", "style",
           "telemetry", "traced"]
