"""Data-path determinism rules.

The framework's exactness guarantees (seeded K=1-vs-K=8 equivalence,
bit-identical chaos recovery, checkpoint/resume continuation) all
assume the record order a data pipeline emits is a pure function of its
seeds. One unseeded shuffle anywhere in the dataset/datapipe path
silently breaks every one of them — runs stop being reproducible and
the equivalence harnesses compare different streams. ``unseeded-shuffle``
makes that a lint failure instead of a debugging session.
"""
from __future__ import annotations

import ast

from bigdl_tpu.analysis.lint import FileContext, rule

# reorder/draw entry points whose determinism matters for data feeds
_SHUFFLE_METHODS = {"shuffle", "permutation", "permuted", "choice"}

# module-level forms that are unseeded BY DEFINITION (process-global RNG)
_GLOBAL_NP_SHUFFLES = {
    "numpy.random.shuffle", "numpy.random.permutation",
    "numpy.random.choice",
}
_GLOBAL_STDLIB_SHUFFLES = {"random.shuffle", "random.sample"}

# generator constructors; a call with NO seed argument is a fresh
# OS-entropy stream — different every run
_GEN_CTORS = {
    "numpy.random.RandomState", "numpy.random.default_rng",
    "numpy.random.Generator", "numpy.random.PCG64",
    "numpy.random.Philox", "numpy.random.SFC64", "numpy.random.MT19937",
}

_FIX = ("; seed it explicitly (np.random.default_rng(seed) / "
        "RandomState(seed)) — record order must be a pure function of "
        "the seed for the K-window and resume equivalence guarantees "
        "to hold")


def _unseeded_ctor(ctx: FileContext, node) -> bool:
    """A generator construction carrying no seed: ``RandomState()``,
    ``default_rng()``, or a wrapper of one (``Generator(PCG64())``)."""
    if not isinstance(node, ast.Call):
        return False
    if ctx.canon(node.func) not in _GEN_CTORS:
        return False
    args = list(node.args) + [kw.value for kw in node.keywords]
    if not args:
        return True
    return len(args) == 1 and _unseeded_ctor(ctx, args[0])


_FN_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _is_gen_ctor(ctx: FileContext, node) -> bool:
    return isinstance(node, ast.Call) \
        and ctx.canon(node.func) in _GEN_CTORS


def _fn_scope_chain(ctx: FileContext, node):
    """Enclosing function-scope ids innermost-first, ending with the
    module scope (0)."""
    chain = []
    cur = node
    while True:
        enc = ctx.enclosing(cur, *_FN_SCOPES)
        if enc is None:
            break
        chain.append(id(enc))
        cur = enc
    chain.append(0)
    return chain


def _cls_scope(ctx: FileContext, node) -> int:
    enc = ctx.enclosing(node, ast.ClassDef)
    return id(enc) if enc is not None else 0


@rule("unseeded-shuffle",
      "shuffle/permutation without a seeded Generator (dataset "
      "determinism)")
def unseeded_shuffle(ctx: FileContext):
    # Generator-constructor bindings, SCOPED: plain names key on their
    # enclosing function (so an unseeded `rng` in one function never
    # taints a seeded `rng` in another), attributes on their enclosing
    # class. Per scope we count seeded and unseeded bindings; a name is
    # treated as unseeded only when every binding in its scope is —
    # a seeded rebinding exonerates (order analysis is out of budget
    # for a linter; when in doubt, stay quiet).
    names: dict = {}  # (scope_id, name) -> [n_unseeded, n_seeded]
    attrs: dict = {}  # (class_scope_id, attr) -> [n_unseeded, n_seeded]
    for node in ctx.walk(ast.Assign):
        if not _is_gen_ctor(ctx, node.value):
            continue
        bad = _unseeded_ctor(ctx, node.value)
        for t in node.targets:
            if isinstance(t, ast.Name):
                sid = _fn_scope_chain(ctx, node)[0]  # innermost scope
                row = names.setdefault((sid, t.id), [0, 0])
                row[0 if bad else 1] += 1
            elif isinstance(t, ast.Attribute):
                row = attrs.setdefault((_cls_scope(ctx, node), t.attr),
                                       [0, 0])
                row[0 if bad else 1] += 1

    def name_unseeded(call, ident) -> bool:
        # nearest scope holding a binding for this name decides
        for sid in _fn_scope_chain(ctx, call):
            row = names.get((sid, ident))
            if row is not None:
                return row[0] > 0 and row[1] == 0
        return False

    def attr_unseeded(call, ident) -> bool:
        row = attrs.get((_cls_scope(ctx, call), ident))
        return row is not None and row[0] > 0 and row[1] == 0

    for node in ctx.walk(ast.Call):
        c = ctx.canon(node.func)
        if c in _GLOBAL_NP_SHUFFLES:
            yield node, (f"`{c}` shuffles through the process-global "
                         "numpy RNG" + _FIX)
            continue
        if c in _GLOBAL_STDLIB_SHUFFLES and "random" in ctx.aliases \
                and ctx.aliases["random"] == "random":
            yield node, (f"`{c}` shuffles through the global stdlib RNG"
                         + _FIX)
            continue
        f = node.func
        if not isinstance(f, ast.Attribute) \
                or f.attr not in _SHUFFLE_METHODS:
            continue
        base = f.value
        if _unseeded_ctor(ctx, base):
            yield node, (f"`.{f.attr}()` on a generator constructed "
                         "without a seed" + _FIX)
        elif isinstance(base, ast.Name) and name_unseeded(node, base.id):
            yield node, (f"`{base.id}.{f.attr}()` draws from a "
                         "generator constructed without a seed" + _FIX)
        elif isinstance(base, ast.Attribute) \
                and attr_unseeded(node, base.attr):
            yield node, (f"`.{base.attr}.{f.attr}()` draws from a "
                         "generator constructed without a seed" + _FIX)
