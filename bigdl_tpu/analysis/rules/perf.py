"""Host-loop performance rules: per-step device synchronization.

A training/serving loop that synchronizes the host every iteration
(``jax.block_until_ready``, ``.item()``, ``float(loss)``) serializes
dispatch against execution — the device idles while Python does
bookkeeping, and step k+1 never overlaps the tail of step k. The
sanctioned pattern is to sync at WINDOW boundaries only
(``Optimizer.set_steps_per_sync`` / a ``lax.scan`` chunk) and mark the
remaining deliberate sync points with ``# bigdl: disable=sync-in-loop``
so they stay auditable.
"""
from __future__ import annotations

import ast

from bigdl_tpu.analysis.lint import FileContext, rule

_SYNC_ATTRS = ("item", "block_until_ready")


#: builtins whose results are host values by construction — float()
#: over them is never a device fetch
_HOST_BUILTINS = frozenset({
    "len", "range", "enumerate", "zip", "sorted", "reversed", "list",
    "tuple", "dict", "set", "str", "repr", "format", "ord", "chr", "id",
    "hash", "open", "input", "int", "bool", "next", "getattr", "vars",
})


def _device_ish_call(ctx: FileContext, call: ast.Call) -> bool:
    """Plausibly returns device values: a plain function call
    (``step(params, x)``, the step/eval idiom — minus host-only
    builtins) or a jax/jnp API call. Method calls on arbitrary objects
    (``line.split(',')``, ``m.groups()``) are host-side string/object
    work — counting those would flag pure parsing loops."""
    if isinstance(call.func, ast.Name):
        return call.func.id not in _HOST_BUILTINS
    c = ctx.canon(call.func)
    return c is not None and (c == "jax" or c.startswith(("jax.", "jnp.")))


def _fresh_call_names(ctx: FileContext, nodes):
    """Names bound from a device-ish Call result within the loop body —
    a ``float()`` over one of these fetches a freshly computed device
    value every iteration."""
    fresh = set()
    for node in nodes:
        if not isinstance(node, ast.Assign):
            continue
        if not (isinstance(node.value, ast.Call)
                and _device_ish_call(ctx, node.value)):
            continue
        for t in node.targets:
            targets = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                else [t]
            for e in targets:
                if isinstance(e, ast.Name):
                    fresh.add(e.id)
    return fresh


def _imports_jax(ctx: FileContext) -> bool:
    for node in ctx.walk(ast.Import):
        if any(a.name == "jax" or a.name.startswith("jax.")
               for a in node.names):
            return True
    for node in ctx.walk(ast.ImportFrom):
        if node.module and (node.module == "jax"
                            or node.module.startswith("jax.")):
            return True
    return False


#: canonical (alias-resolved) names of the array-growing jnp calls
_GROWING_FNS = frozenset(
    f"jax.numpy.{fn}" for fn in ("concatenate", "append", "concat",
                                 "hstack", "vstack"))


@rule("growing-concat-in-loop",
      "growing a jnp array by concatenation every loop iteration")
def growing_concat_in_loop(ctx: FileContext):
    """Flags ``x = jnp.concatenate([x, ...])`` / ``jnp.append(x, ...)``
    (and hstack/vstack/concat) where the target feeds its own
    concatenation inside a loop — the classic autoregressive-decode
    pitfall: in traced code every iteration is a NEW shape (one XLA
    compile per token), and on the host it is O(n²) copying. The
    sanctioned idiom is a preallocated buffer written in place
    (``lax.dynamic_update_slice`` — the ``bigdl_tpu.generation`` KV
    cache), with deliberate exceptions marked
    ``# bigdl: disable=growing-concat-in-loop``. Each loop is analyzed
    at its own nesting level; files that never import jax are
    skipped."""
    if not _imports_jax(ctx):
        return
    for loop in ctx.walk(ast.For, ast.While):
        body = []
        # loop.body only: the else: clause runs once, after the loop
        stack = list(loop.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.For, ast.While)):
                continue  # other scopes / the inner loop's own finding
            body.append(node)
            stack.extend(ast.iter_child_nodes(node))
        for node in body:
            if not isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                continue
            value = node.value
            if not (isinstance(value, ast.Call)
                    and ctx.canon(value.func) in _GROWING_FNS):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            target_names = {
                t.id
                for tgt in targets
                for t in (tgt.elts if isinstance(tgt, (ast.Tuple,
                                                       ast.List))
                          else [tgt])
                if isinstance(t, ast.Name)}
            arg_names = {n.id for a in value.args
                         for n in ast.walk(a)
                         if isinstance(n, ast.Name)}
            grown = sorted(target_names & arg_names)
            if grown:
                fn = ctx.canon(value.func)
                yield node, (
                    f"`{fn}` grows `{grown[0]}` every iteration: in "
                    "traced code each step is a new shape (one XLA "
                    "compile per token), on the host it is O(n²) "
                    "copying; preallocate and write in place "
                    "(`lax.dynamic_update_slice`, the KV-cache decode "
                    "idiom) or mark a deliberate small loop with "
                    "`# bigdl: disable=growing-concat-in-loop`")


#: collectives whose per-step re-execution over an UNCHANGED tree is
#: the gather-every-step-instead-of-once pitfall (ZeRO's inverse: the
#: sanctioned placement is inside the compiled window, or once before
#: the loop)
_GATHER_FNS = frozenset({"jax.lax.all_gather", "jax.lax.psum"})


@rule("gather-in-step-loop",
      "collective over a loop-invariant tree inside a host step loop")
def gather_in_step_loop(ctx: FileContext):
    """Flags ``jax.lax.all_gather`` / ``jax.lax.psum`` whose gathered
    operand never changes across iterations of a HOST-level loop — the
    classic ZeRO pitfall of re-gathering the full (loop-invariant)
    params every step instead of once before the loop, or instead of
    letting the compiled step place the collective inside the program
    where XLA overlaps it with compute (``parallel/zero.py``'s
    contract). Per-iteration operands (the updated params of a real
    train loop) are intentional and pass; traced loops are XLA's to
    schedule and are skipped; files that never import jax hold no
    collectives and are skipped. Mark a deliberate host-side gather
    with ``# bigdl: disable=gather-in-step-loop``."""
    from bigdl_tpu.analysis.rules.jit_calls import _loop_bound_names
    if not _imports_jax(ctx):
        return
    for loop in ctx.walk(ast.For, ast.While):
        if ctx.in_traced(loop):
            continue
        bound = _loop_bound_names(loop)
        stack = list(ast.iter_child_nodes(loop))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.For, ast.While)):
                continue  # other scopes / the inner loop's own finding
            if isinstance(node, ast.Call):
                c = ctx.canon(node.func)
                if c in _GATHER_FNS and node.args:
                    arg_names = {
                        n.id for a in node.args[:1]
                        for n in ast.walk(a) if isinstance(n, ast.Name)}
                    if arg_names and not (arg_names & bound):
                        yield node, (
                            f"`{c}` of a loop-invariant tree runs the "
                            "full collective every iteration; gather "
                            "once before the loop, or move the loop "
                            "into the compiled step (lax.scan / "
                            "steps_per_sync) so XLA overlaps the "
                            "collective with compute — or mark a "
                            "deliberate host-side gather with "
                            "`# bigdl: disable=gather-in-step-loop`")
            stack.extend(ast.iter_child_nodes(node))


#: spellings of an explicit float32 target in astype()/asarray(dtype=)
_F32_NAMES = frozenset({"jax.numpy.float32", "numpy.float32"})


def _is_f32_target(ctx: FileContext, node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return node.value in ("float32", "f32")
    return ctx.canon(node) in _F32_NAMES


def _precision_surface(ctx: FileContext) -> bool:
    """True when the file participates in the precision-policy surface:
    it imports ``bigdl_tpu.precision`` (policy consumers — the
    optimizer, serving loads) or defines Module-ish classes whose
    apply/forward_fn run under the policy's compute dtype (the nn
    layers and models)."""
    for node in ctx.walk(ast.Import):
        if any(a.name.startswith("bigdl_tpu.precision")
               for a in node.names):
            return True
    for node in ctx.walk(ast.ImportFrom):
        if node.module and node.module.startswith("bigdl_tpu.precision"):
            return True
    return bool(ctx._moduleish_classes())


@rule("implicit-upcast-in-trace",
      "silent float32 upcast of a traced value under a precision policy")
def implicit_upcast_in_trace(ctx: FileContext):
    """Flags ``x.astype(jnp.float32)`` / ``x.astype("float32")``,
    ``jnp.float32(x)`` and dtype-less ``jnp.asarray(x)`` over traced
    values inside traced code of files on the precision-policy surface
    (they import ``bigdl_tpu.precision`` or define Module-ish layers).

    Under a ``bf16_mixed``/``f16_mixed`` policy these quietly promote
    the whole downstream graph back to f32 — the matmuls run full-width
    again and the policy's 2x is gone, with no error anywhere. The
    SANCTIONED f32 islands (norm statistics, softmax, the loss, the
    gradient-norm accumulator, the loss scaler) stay f32 by design and
    carry ``# bigdl: disable=implicit-upcast-in-trace`` so every one is
    auditable. A dtype-less ``jnp.asarray`` is flagged only when a
    traced value flows in: over host constants it is trace-time
    folding, not an upcast."""
    if not _imports_jax(ctx) or not _precision_surface(ctx):
        return
    for node in ctx.walk(ast.Call):
        if not ctx.in_traced(node):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "astype" \
                and node.args and _is_f32_target(ctx, node.args[0]):
            yield node, (
                "`.astype(float32)` in traced layer code upcasts the "
                "value — and everything computed from it — out of the "
                "policy's compute dtype; keep the compute dtype "
                "(`x.dtype`), or mark a sanctioned f32 island "
                "(norm stats / softmax / loss / scaler) with "
                "`# bigdl: disable=implicit-upcast-in-trace`")
            continue
        c = ctx.canon(f)
        if c in _F32_NAMES and node.args:
            # jnp.float32(x) over a TRACED value upcasts it; over a
            # host literal (eps constants, scan carry inits) it is
            # trace-time constant folding — same exemption dtype-less
            # asarray gets below
            fn = ctx.enclosing(node, ast.FunctionDef,
                               ast.AsyncFunctionDef, ast.Lambda)
            known = ctx.traced_vars(fn) if fn is not None else set()
            if ctx._is_arrayish(node.args[0], known):
                yield node, (
                    f"`{c}(...)` upcasts a traced value to float32; "
                    "derive the dtype from the operand (`x.dtype`) so "
                    "the precision policy's compute dtype survives, or "
                    "mark a sanctioned f32 island with "
                    "`# bigdl: disable=implicit-upcast-in-trace`")
            continue
        if c == "jax.numpy.asarray" and node.args \
                and len(node.args) < 2 \
                and not any(kw.arg == "dtype" for kw in node.keywords):
            fn = ctx.enclosing(node, ast.FunctionDef,
                               ast.AsyncFunctionDef, ast.Lambda)
            known = ctx.traced_vars(fn) if fn is not None else set()
            if ctx._is_arrayish(node.args[0], known):
                yield node, (
                    "dtype-less `jnp.asarray` on a traced value "
                    "defaults weakly-typed operands to float32 and "
                    "silently widens the policy's compute dtype; pass "
                    "`dtype=x.dtype` (or mark a sanctioned island with "
                    "`# bigdl: disable=implicit-upcast-in-trace`)")


#: the bare-name spelling (a local alias the canonicalizer cannot see
#: through); every dotted spelling — `pl.pallas_call`,
#: `jax.experimental.pallas.pallas_call`, `from ... import pallas_call`
#: — resolves canonically and is caught by the endswith check below
_PALLAS_CALL_NAMES = frozenset({"pallas_call"})


@rule("raw-pallas-call",
      "direct pl.pallas_call outside the bigdl_tpu/kernels/ dispatch layer")
def raw_pallas_call(ctx: FileContext):
    """Flags ``pl.pallas_call(...)`` (any import spelling) in files
    outside ``bigdl_tpu/kernels/`` — every kernel must enter through
    the dispatch layer (``kernels.attention`` / ``decode_attention`` /
    ``int8_matmul``), which is what guarantees the pure-jnp fallback
    exists, the ``KernelConfig``/``BIGDL_KERNELS`` toggle works, and
    the interpret-mode equivalence tests cover the kernel body. A raw
    call site bypasses all three silently. Mark a deliberate
    exception with ``# bigdl: disable=raw-pallas-call``."""
    norm = ctx.path.replace("\\", "/")
    if "bigdl_tpu/kernels/" in norm:
        return  # the kernel layer itself is the sanctioned home
    for node in ctx.walk(ast.Call):
        c = ctx.canon(node.func)
        if c in _PALLAS_CALL_NAMES or (c is not None
                                       and c.endswith(".pallas_call")):
            yield node, (
                f"`{c}` invoked outside bigdl_tpu/kernels/: raw kernels "
                "bypass the dispatch layer's jnp fallback, the "
                "BIGDL_KERNELS toggle and the interpret-mode "
                "equivalence tests; route through bigdl_tpu.kernels "
                "(attention/decode_attention/int8_matmul) or add the "
                "kernel under bigdl_tpu/kernels/ — or mark a "
                "deliberate exception with "
                "`# bigdl: disable=raw-pallas-call`")


#: serving-surface package prefixes: files importing these (or living
#: under them) hold state at TRAFFIC rate, where a grow-only container
#: is a memory leak per request
_SERVING_PACKAGES = ("bigdl_tpu.serving", "bigdl_tpu.generation",
                     "bigdl_tpu.fleet")
_SERVING_DIRS = ("bigdl_tpu/serving/", "bigdl_tpu/generation/",
                 "bigdl_tpu/fleet/")

_GROW_METHODS = frozenset({"append", "appendleft", "add", "setdefault",
                           "insert", "extend", "update"})
_SHRINK_METHODS = frozenset({"pop", "popitem", "popleft", "clear",
                             "remove", "discard"})


def _serving_surface(ctx: FileContext) -> bool:
    norm = ctx.path.replace("\\", "/")
    if any(d in norm for d in _SERVING_DIRS):
        return True
    for node in ctx.walk(ast.Import):
        if any(a.name.startswith(_SERVING_PACKAGES) for a in node.names):
            return True
    for node in ctx.walk(ast.ImportFrom):
        if node.module and node.module.startswith(_SERVING_PACKAGES):
            return True
        if node.module == "bigdl_tpu" and any(
                f"bigdl_tpu.{a.name}".startswith(_SERVING_PACKAGES)
                for a in node.names):
            return True
    return False


def _fresh_container(node: ast.AST) -> bool:
    """A value that creates an EMPTY growable container: ``{}``,
    ``[]``, ``set()``, ``dict()``/``list()``/``OrderedDict()``/
    ``defaultdict(...)`` and maxlen-less ``deque()`` (a
    ``deque(maxlen=...)`` is bounded by construction and never a
    candidate)."""
    if isinstance(node, (ast.Dict, ast.List, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name == "deque":
            return not any(kw.arg == "maxlen" for kw in node.keywords)
        return name in ("dict", "list", "set", "OrderedDict",
                        "defaultdict")
    return False


def _self_attr(node: ast.AST):
    """``self.X`` -> ``"X"``, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _scan_container_use(nodes, attr_of):
    """Walk statements classifying container use: returns
    ``(candidates, grown, shrunk)`` where each maps attr name ->
    first relevant node. ``attr_of(expr)`` names the tracked
    container an expression refers to (self-attr or module global)."""
    candidates, grown, shrunk = {}, {}, set()
    for node in nodes:
        for n in ast.walk(node):
            if isinstance(n, ast.AugAssign):
                # `self.x += [item]` / `|= {...}` IS growth, never a
                # rebind-reset
                name = attr_of(n.target)
                if name is not None:
                    grown.setdefault(name, n)
            elif isinstance(n, (ast.Assign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                value = n.value
                for t in targets:
                    name = attr_of(t)
                    if name is not None:
                        if value is not None and _fresh_container(value):
                            if name not in candidates:
                                candidates[name] = n
                            else:
                                # re-initialized later: a reset IS the
                                # bound (epoch-style rebuild)
                                shrunk.add(name)
                        elif value is not None:
                            shrunk.add(name)  # rebound to something else
                        continue
                    # self.X[key] = ... / X[key] = ... grows the store
                    if isinstance(t, ast.Subscript):
                        name = attr_of(t.value)
                        if name is not None:
                            grown.setdefault(name, n)
            elif isinstance(n, ast.Delete):
                for t in n.targets:
                    if isinstance(t, ast.Subscript):
                        name = attr_of(t.value)
                        if name is not None:
                            shrunk.add(name)
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute):
                name = attr_of(n.func.value)
                if name is None:
                    continue
                meth = n.func.attr
                if meth in _SHRINK_METHODS or "evict" in meth:
                    shrunk.add(name)
                elif meth in _GROW_METHODS:
                    grown.setdefault(name, n)
    return candidates, grown, shrunk


@rule("unbounded-cache-growth",
      "serving-surface container attribute that only ever grows")
def unbounded_cache_growth(ctx: FileContext):
    """Flags a dict/list/set attribute (``self.X = {}`` in a class, or
    a module-level ``X = {}``) that the same class/module only ever
    GROWS (``[key] = ...``, ``.append``, ``.add``, ``.setdefault``,
    ...) with no shrink site anywhere in that scope (``.pop``,
    ``del x[...]``, ``.clear``, ``.remove``, an ``*evict*`` method
    call, a rebind, or ``deque(maxlen=...)``) — in **serving-surface**
    files (they import or live under ``bigdl_tpu.serving`` /
    ``generation`` / ``fleet``), where state accumulates at traffic
    rate and a grow-only container is a memory leak per request. The
    sanctioned pattern is the fleet prefix cache
    (``bigdl_tpu/fleet/prefix.py``): capacity-bounded, LRU-evicted,
    refcount-guarded. A deliberately request-bounded accumulator
    (e.g. one stream's own token list) carries
    ``# bigdl: disable=unbounded-cache-growth``."""
    if not _serving_surface(ctx):
        return

    def report(candidates, grown, shrunk, where):
        for name in sorted(set(candidates) & set(grown) - shrunk):
            yield grown[name], (
                f"`{name}` in {where} only ever grows — every "
                "request/entry leaks resident memory at traffic rate; "
                "bound it (capacity + LRU eviction like the fleet "
                "prefix cache, `deque(maxlen=...)`, or an explicit "
                "`pop`/`del`/`clear` lifecycle), or mark a "
                "request-bounded accumulator with "
                "`# bigdl: disable=unbounded-cache-growth`")

    for cls in ctx.walk(ast.ClassDef):
        yield from report(*_scan_container_use(cls.body, _self_attr),
                          where=f"class {cls.name}")
    # module-level containers: candidates declared at top level, grown
    # anywhere in the file outside a class's own scan
    module_candidates = {}
    for node in ctx.tree.body:
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            value = node.value
            for t in targets:
                if isinstance(t, ast.Name) and value is not None \
                        and _fresh_container(value):
                    module_candidates[t.id] = node

    def global_of(expr):
        if isinstance(expr, ast.Name) and expr.id in module_candidates:
            return expr.id
        return None

    if module_candidates:
        _, grown, shrunk = _scan_container_use(ctx.tree.body, global_of)
        yield from report(module_candidates, grown, shrunk,
                          where="module scope")


#: knobs the autotuner owns: a literal value for one of these in a
#: tool/bench file silently overrides what a sweep measured
_TUNED_NAMES = frozenset({"steps_per_sync", "length_buckets",
                          "prefix_cache_bytes"})

#: the one module where hand-picked tuned-constant literals are
#: sanctioned (they live there WITH their rationale)
_TUNED_DEFAULTS_MODULE = "bigdl_tpu/autotune/defaults"


def _literal_value(node: ast.AST) -> bool:
    """A compile-time numeric literal: constants, tuples/lists of
    them, and arithmetic over them (``256 << 20`` is still a
    hand-picked number)."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool)
    if isinstance(node, (ast.Tuple, ast.List)):
        return bool(node.elts) and all(_literal_value(e)
                                       for e in node.elts)
    if isinstance(node, ast.BinOp):
        return _literal_value(node.left) and _literal_value(node.right)
    if isinstance(node, ast.UnaryOp):
        return _literal_value(node.operand)
    return False


@rule("hardcoded-tuned-constant",
      "literal tuned-knob value in a tool/bench file outside the "
      "sanctioned defaults module")
def hardcoded_tuned_constant(ctx: FileContext):
    """Flags literal ``steps_per_sync`` / ``length_buckets`` /
    ``prefix_cache_bytes`` values — assignments, call keywords, and
    ``.set_steps_per_sync(<literal>)`` — in the TOOL and BENCH layers
    (``bigdl_tpu/tools/``, ``bench.py``, scripts), where a hand-picked
    number silently overrides whatever ``python -m
    bigdl_tpu.tools.autotune`` measured. The sanctioned homes are
    ``bigdl_tpu/autotune/defaults.py`` (hand-picked values WITH their
    rationale) and a ``tuned.json`` artifact applied via ``--config``
    / ``apply_tuned_config``; library modules (axis definitions,
    dataclass defaults) are definition sites, not choices, and are
    exempt. Mark a deliberate fixed-value site (a chaos drill's tiny
    geometry, a bench leg pinning one axis) with
    ``# bigdl: disable=hardcoded-tuned-constant``."""
    norm = ctx.path.replace("\\", "/")
    if _TUNED_DEFAULTS_MODULE in norm:
        return  # THE sanctioned home
    if "bigdl_tpu/" in norm and "bigdl_tpu/tools/" not in norm:
        return  # library modules define the knobs; tools choose values

    def msg(name: str) -> str:
        return (
            f"literal `{name}` here overrides whatever the autotuner "
            "measured; read it from bigdl_tpu.autotune.defaults, apply "
            "a tuned.json (`--config` / `apply_tuned_config`), or mark "
            "a deliberate fixed-value site with "
            "`# bigdl: disable=hardcoded-tuned-constant`")

    for node in ctx.walk(ast.Assign, ast.AnnAssign):
        # class bodies are definition sites (dataclass field defaults)
        encl = ctx.enclosing(node, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)
        if isinstance(encl, ast.ClassDef):
            continue
        value = node.value
        if value is None or not _literal_value(value):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            name = t.id if isinstance(t, ast.Name) else (
                t.attr if isinstance(t, ast.Attribute) else None)
            if name in _TUNED_NAMES:
                yield node, msg(name)
    for node in ctx.walk(ast.Call):
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "set_steps_per_sync" \
                and node.args and _literal_value(node.args[0]):
            yield node, msg("set_steps_per_sync")
            continue
        for kw in node.keywords:
            if kw.arg in _TUNED_NAMES and _literal_value(kw.value):
                # anchor on the literal so a disable tag on ITS line
                # works inside multi-line calls
                yield kw.value, msg(kw.arg)


@rule("sync-in-loop",
      "per-iteration host-device sync inside a host step loop")
def sync_in_loop(ctx: FileContext):
    """Flags ``jax.block_until_ready`` / ``.block_until_ready()`` /
    ``.item()`` and ``float()`` over per-iteration device-ish call
    results inside host loops — including module-level script loops,
    the classic home of per-step-synced training drivers. Each loop is
    analyzed at its own nesting level (a sync in an inner loop is the
    inner loop's finding); traced loops are host-sync's territory.
    Files that never import jax hold no device values and are
    skipped."""
    if not _imports_jax(ctx):
        return
    for loop in ctx.walk(ast.For, ast.While):
        if ctx.in_traced(loop):
            continue
        body = []
        stack = list(ast.iter_child_nodes(loop))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.For, ast.While)):
                continue  # other scopes / the inner loop's own finding
            body.append(node)
            stack.extend(ast.iter_child_nodes(node))
        fresh = _fresh_call_names(ctx, body)
        for node in body:
            if not isinstance(node, ast.Call):
                continue
            c = ctx.canon(node.func)
            if c == "jax.block_until_ready":
                yield node, (
                    "`jax.block_until_ready` every loop iteration "
                    "serializes dispatch against execution; fuse steps "
                    "(steps_per_sync / lax.scan) and sync at window "
                    "boundaries, or mark a deliberate sync point with "
                    "`# bigdl: disable=sync-in-loop`")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _SYNC_ATTRS and not node.args:
                yield node, (
                    f"`.{node.func.attr}()` every loop iteration blocks "
                    "the host on the device; batch the fetch (one "
                    "length-K vector per window) or mark a deliberate "
                    "sync point with `# bigdl: disable=sync-in-loop`")
            elif isinstance(node.func, ast.Name) \
                    and node.func.id == "float" and node.args:
                names = {n.id for n in ast.walk(node.args[0])
                         if isinstance(n, ast.Name)}
                if names & fresh:
                    yield node, (
                        "`float()` over a per-iteration result forces a "
                        "blocking device fetch every step; fetch once "
                        "per window (losses as a length-K vector) or "
                        "mark a deliberate sync point with "
                        "`# bigdl: disable=sync-in-loop`")
