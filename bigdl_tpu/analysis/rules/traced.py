"""Rules over *traced* code: functions JAX will trace (jitted, grad'd,
scanned, or the Module ``apply``/``forward_fn`` surface) where host-side
operations either fail at runtime, silently force a device sync, or bake
a host value in as a compile-time constant.
"""
from __future__ import annotations

import ast

from bigdl_tpu.analysis.lint import FileContext, rule

# numpy calls that materialize a tracer on the host (TracerArrayConversion
# at runtime — or a silent device round-trip when fed concrete values)
_HOST_MATERIALIZE = {"numpy.asarray", "numpy.array"}

# host clocks / host RNG: legal under trace, but evaluated ONCE at trace
# time — every compiled execution replays the same "random"/"now" value
_HOST_STATE = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.process_time", "datetime.datetime.now", "datetime.datetime.today",
    "RandomGenerator.next_key",
}
_HOST_STATE_PREFIXES = ("numpy.random.", "random.")


def _sync_attr_call(node: ast.Call) -> bool:
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr in ("item", "tolist", "block_until_ready")
            and not node.args)


@rule("host-sync",
      "host-device synchronization reachable from traced code")
def host_sync(ctx: FileContext):
    for node in ctx.walk(ast.Call):
        if not ctx.in_traced(node):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in ("float", "int", "bool") \
                and node.args:
            fn = ctx.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)
            known = ctx.traced_vars(fn) if fn is not None else set()
            if ctx._is_arrayish(node.args[0], known):
                yield node, (
                    f"`{f.id}()` on a traced value: under jit this raises "
                    "TracerConversionError; outside it forces a blocking "
                    "device sync — keep the value on device or move the "
                    "conversion out of the traced function")
            continue
        if _sync_attr_call(node):
            yield node, (
                f"`.{node.func.attr}()` in traced code forces a host "
                "sync / fails under jit; return the array instead")
            continue
        c = ctx.canon(f)
        if c == "jax.device_get":
            yield node, (
                "`jax.device_get` in traced code forces a host sync / "
                "fails under jit; return the array instead")
        elif c in _HOST_MATERIALIZE and node.args:
            # only when a traced value flows in: np.asarray over static
            # python data (shapes, config lists) is legitimate trace-time
            # constant folding
            fn = ctx.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)
            known = ctx.traced_vars(fn) if fn is not None else set()
            if ctx._is_arrayish(node.args[0], known):
                yield node, (
                    f"`{c}` materializes a traced value on host "
                    "(TracerArrayConversionError under jit); use jnp "
                    "instead")


@rule("host-state-in-trace",
      "host clock / host RNG evaluated once at trace time")
def host_state(ctx: FileContext):
    for node in ctx.walk(ast.Call):
        if not ctx.in_traced(node):
            continue
        c = ctx.canon(node.func)
        if c is None:
            continue
        if c in ("numpy.random.RandomState", "numpy.random.default_rng",
                 "random.Random"):
            continue  # constructing a seeded generator is host-side setup
        if c in _HOST_STATE or c.endswith(".RandomGenerator.next_key") \
                or any(c.startswith(p) for p in _HOST_STATE_PREFIXES):
            yield node, (
                f"`{c}` runs on the host at TRACE time: the compiled "
                "program replays one frozen value forever; thread a "
                "jax.random key / pass the value as an argument")


#: telemetry entry points that must stay host-side; under trace they run
#: once at trace time, so every compiled execution replays one frozen
#: span/count — the trace would lie forever
_TELEMETRY_FACTORIES = {"counter", "gauge", "histogram"}
_INSTRUMENT_METHODS = {"inc", "observe", "set", "add"}


def _is_telemetry_name(c: str) -> bool:
    return c.startswith("bigdl_tpu.telemetry.") \
        or c == "bigdl_tpu.telemetry" \
        or c.split(".")[0] == "telemetry"


@rule("telemetry-in-trace",
      "telemetry span/instrument call inside traced code")
def telemetry_in_trace(ctx: FileContext):
    """Spans and instruments are host-side observability: inside jit/
    grad/scan-traced code the python runs ONCE at compile time, so the
    span measures tracing (not execution) and the counter advances once
    per compile, not per step. Move the call outside the traced
    function (the optimizer's host loop is the right altitude)."""
    # names bound from telemetry instrument factories anywhere in the
    # file (module-level `_STEPS = telemetry.counter(...)` idiom): their
    # .inc/.observe/.set/.add methods are telemetry surface too
    instruments = set()
    for node in ctx.walk(ast.Assign):
        if not isinstance(node.value, ast.Call):
            continue
        c = ctx.canon(node.value.func)
        if c and _is_telemetry_name(c) \
                and c.rsplit(".", 1)[-1] in _TELEMETRY_FACTORIES:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    instruments.add(t.id)
    for node in ctx.walk(ast.Call):
        if not ctx.in_traced(node):
            continue
        c = ctx.canon(node.func)
        if c is not None and _is_telemetry_name(c):
            yield node, (
                f"`{c}` inside traced code runs once at TRACE time — "
                "the span/instrument records compilation, then never "
                "fires again; telemetry must stay on the host side of "
                "the jit boundary")
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _INSTRUMENT_METHODS \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in instruments:
            yield node, (
                f"instrument update `{node.func.value.id}."
                f"{node.func.attr}()` inside traced code advances once "
                "per COMPILE, not per execution; hoist it to the host "
                "loop")


@rule("traced-branch",
      "Python control flow branching on a traced value")
def traced_branch(ctx: FileContext):
    for node in ctx.walk(ast.If, ast.While):
        if not ctx.in_traced(node):
            continue
        fn = ctx.enclosing(node, ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda) or ctx.tree
        known = ctx.traced_vars(fn)
        if ctx._is_arrayish(node.test, known):
            kind = "if" if isinstance(node, ast.If) else "while"
            yield node, (
                f"`{kind}` on a traced value raises "
                "TracerBoolConversionError under jit; use jnp.where / "
                "lax.cond / lax.while_loop (or mark the argument static)")
