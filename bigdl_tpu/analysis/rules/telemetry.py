"""Telemetry-hygiene rules: metric series that grow without bound.

A Counter/Gauge/Histogram label set IS a time series: every distinct
label value allocates an independent series in the registry and in
every exporter downstream (Prometheus explicitly documents this as the
cardinality-explosion failure mode). A label value built from a loop
variable or a per-request id — ``reqs.inc(req=f"req-{i}")``,
``lat.observe(ms, trace=str(trace_id))`` — therefore leaks memory at
traffic rate and renders dashboards unreadable. Bounded identity
(model name, phase, fault point) belongs in labels; per-request
identity (``trace_id``) belongs in **span args**, where the ring
buffer bounds it by construction.
"""
from __future__ import annotations

import ast
import re

from bigdl_tpu.analysis.lint import FileContext, rule

#: instrument update methods whose kwargs are label values
_UPDATE_METHODS = {"inc", "set", "add", "observe"}

#: constructors whose result is an instrument (dotted-canon suffixes):
#: telemetry.counter(...), registry.gauge(...), r.histogram(...)
_INSTRUMENT_SUFFIXES = ("counter", "gauge", "histogram")

#: per-request identity names — these go in span args, never labels
_ID_NAME = re.compile(r"^(trace|request|req|span|stream|gen)_?id$")


def _imports_telemetry(ctx: FileContext) -> bool:
    return any(v.startswith("bigdl_tpu.telemetry") or v == "telemetry"
               for v in ctx.aliases.values())


def _is_instrument_ctor(ctx: FileContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    c = ctx.canon(node.func)
    return c is not None and c.split(".")[-1] in _INSTRUMENT_SUFFIXES


def _dotted(node: ast.AST):
    """``self._c_reqs`` -> "self._c_reqs" (None for non-name chains)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _instrument_bindings(ctx: FileContext) -> set:
    """Names (and ``self.attr`` chains) assigned from an instrument
    constructor anywhere in the file — the receivers whose update
    calls this rule inspects."""
    bound = set()
    for node in ctx.walk(ast.Assign):
        if not _is_instrument_ctor(ctx, node.value):
            continue
        for t in node.targets:
            d = _dotted(t)
            if d is not None:
                bound.add(d)
    return bound


def _loop_bound_names(ctx: FileContext, node: ast.AST) -> set:
    """Names bound by loops enclosing ``node`` (for targets,
    comprehension targets, while-body assignments)."""
    bound = set()
    cur = ctx.parent(node)
    while cur is not None:
        if isinstance(cur, (ast.For, ast.While, ast.comprehension,
                            ast.GeneratorExp, ast.ListComp,
                            ast.SetComp, ast.DictComp)):
            for sub in ast.walk(cur):
                if isinstance(sub, (ast.For, ast.comprehension)):
                    for e in ast.walk(sub.target):
                        if isinstance(e, ast.Name):
                            bound.add(e.id)
                elif isinstance(cur, ast.While) \
                        and isinstance(sub, (ast.Assign, ast.AugAssign)):
                    targets = sub.targets if isinstance(sub, ast.Assign) \
                        else [sub.target]
                    for t in targets:
                        for e in ast.walk(t):
                            if isinstance(e, ast.Name):
                                bound.add(e.id)
        cur = ctx.parent(cur)
    return bound


def _names_in(node: ast.AST) -> set:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _unbounded_reason(ctx: FileContext, value: ast.AST,
                      loop_bound: set):
    """Why this label value grows without bound, or None.

    Flags f-strings and ``str()``/``repr()``/``format()`` of loop
    variables or id-like names, and bare id-like names/attributes
    (``trace_id`` itself is already one series per request)."""
    if isinstance(value, ast.JoinedStr):
        inner = set()
        for part in value.values:
            if isinstance(part, ast.FormattedValue):
                inner |= _names_in(part.value)
        if inner & loop_bound:
            return "an f-string of a loop variable"
        if any(_ID_NAME.match(n) for n in inner):
            return "an f-string of a per-request id"
        return None
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Name) \
            and value.func.id in ("str", "repr", "format") and value.args:
        inner = _names_in(value.args[0])
        if inner & loop_bound:
            return f"{value.func.id}() of a loop variable"
        if any(_ID_NAME.match(n) for n in inner):
            return f"{value.func.id}() of a per-request id"
        return None
    name = None
    if isinstance(value, ast.Name):
        name = value.id
    elif isinstance(value, ast.Attribute):
        name = value.attr
    if name is not None and _ID_NAME.match(name):
        return "a per-request id"
    return None


@rule("metric-label-cardinality",
      "metric label values built from loop variables / request ids")
def metric_label_cardinality(ctx: FileContext):
    """Flags ``inc``/``set``/``add``/``observe`` calls on telemetry
    instruments whose label kwargs are built from f-strings/``str()``
    of loop variables or per-request ids (``trace_id`` & co.): each
    distinct value is a new series, so the registry and every exporter
    grow at traffic rate. Receivers are tracked from instrument
    constructor assignments (``x = telemetry.counter(...)``,
    ``self._c = r.gauge(...)``) or direct constructor chains, so
    ``set.add``/dict ``.set`` calls never false-positive."""
    if not _imports_telemetry(ctx):
        return
    instruments = None
    for call in ctx.walk(ast.Call):
        if not isinstance(call.func, ast.Attribute) \
                or call.func.attr not in _UPDATE_METHODS \
                or not call.keywords:
            continue
        recv = call.func.value
        if _is_instrument_ctor(ctx, recv):
            pass  # telemetry.counter("...").inc(...)
        else:
            if instruments is None:
                instruments = _instrument_bindings(ctx)
            d = _dotted(recv)
            if d is None or d not in instruments:
                continue
        loop_bound = None
        for kw in call.keywords:
            if kw.arg is None:
                continue  # **labels forwarding: values not visible here
            if loop_bound is None:
                loop_bound = _loop_bound_names(ctx, call)
            reason = _unbounded_reason(ctx, kw.value, loop_bound)
            if reason:
                yield kw.value, (
                    f"label {kw.arg!r} is {reason}: every distinct "
                    "value allocates a new metric series (unbounded "
                    "cardinality at traffic rate) — per-request "
                    "identity belongs in span args "
                    "(telemetry.span(..., trace_id=...)), labels in a "
                    "small fixed vocabulary; a deliberate bounded use "
                    "can carry `# bigdl: "
                    "disable=metric-label-cardinality`")
