"""Robustness rules that keep failures diagnosable."""
from __future__ import annotations

import ast

from bigdl_tpu.analysis.lint import FileContext, rule


@rule("bare-except", "bare `except:` swallows KeyboardInterrupt/SystemExit")
def bare_except(ctx: FileContext):
    for node in ctx.walk(ast.ExceptHandler):
        if node.type is None:
            yield node, (
                "bare `except:` catches KeyboardInterrupt/SystemExit and "
                "hides real failures; catch `Exception` (or the concrete "
                "error) instead")
