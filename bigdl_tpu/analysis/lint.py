"""AST lint engine for JAX/TPU pitfalls.

A pluggable rule registry over Python source. Rules receive a
:class:`FileContext` (parsed AST with parent links, import-alias
resolution, traced-function analysis) and yield ``(node, message)``
findings. The engine layers suppressions, ordering and output formats on
top, so a rule is just a generator function:

    from bigdl_tpu.analysis.lint import rule

    @rule("bare-except", "bare `except:` swallows KeyboardInterrupt")
    def bare_except(ctx):
        for node in ctx.walk(ast.ExceptHandler):
            if node.type is None:
                yield node, "bare `except:`; catch a concrete type"

**Suppressions**: ``# bigdl: disable=rule1,rule2`` on (or on the line
directly above) the flagged line; ``# bigdl: disable-file=rule`` anywhere
suppresses the rule for the whole file; ``disable=all`` suppresses every
rule. Suppressed findings are kept (``Finding.suppressed``) so tooling can
audit them.

**Traced-context analysis**: a function is considered *traced* when it is
decorated with / passed by name to a JAX trace entry point (``jax.jit``,
``jax.grad``, ``lax.scan`` ...), when it is a ``Module.apply`` /
``forward_fn`` method (the framework's trace surface), or when it is
lexically nested inside a traced function. Rules about "code reachable
from jitted functions" anchor on this set.
"""
from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass
from typing import (Callable, Dict, Iterable, Iterator, List, Optional,
                    Sequence, Set, Tuple, Type)

__all__ = ["Finding", "Rule", "rule", "available_rules", "FileContext",
           "lint_source", "lint_file", "lint_paths", "format_text",
           "to_json"]


@dataclass
class Finding:
    """One lint finding: rule id, location, message, suppression state."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}]" \
               f"{tag} {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "suppressed": self.suppressed}


@dataclass
class Rule:
    """A registered lint rule: ``fn(ctx)`` yields (node, message)."""

    name: str
    description: str
    fn: Callable[["FileContext"], Iterator[Tuple[ast.AST, str]]]


_RULES: Dict[str, Rule] = {}


def rule(name: str, description: str):
    """Decorator registering a rule function under ``name``."""
    def deco(fn):
        if name in _RULES:
            raise ValueError(f"duplicate rule {name!r}")
        _RULES[name] = Rule(name, description, fn)
        return fn
    return deco


def available_rules() -> List[Rule]:
    """All registered rules, sorted by name (importing the built-ins)."""
    import bigdl_tpu.analysis.rules  # noqa: F401  registers on import
    return [_RULES[k] for k in sorted(_RULES)]


# --------------------------------------------------------------- suppression

_SUPPRESS_RE = re.compile(
    r"#\s*bigdl:\s*(disable(?:-file)?)\s*=\s*([\w\-, ]+)")


def _parse_suppressions(source: str):
    """-> (line -> rule set, file-level rule set). A suppression comment on
    a line that holds ONLY the comment also covers the next line."""
    line_map: Dict[int, Set[str]] = {}
    file_set: Set[str] = set()
    lines = source.splitlines()
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return line_map, file_set
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
        if m.group(1) == "disable-file":
            file_set |= rules
            continue
        lineno = tok.start[0]
        line_map.setdefault(lineno, set()).update(rules)
        text = lines[lineno - 1] if lineno <= len(lines) else ""
        if text.lstrip().startswith("#"):  # standalone: covers next line
            line_map.setdefault(lineno + 1, set()).update(rules)
    return line_map, file_set


# -------------------------------------------------------------- file context

# canonical dotted names that start a trace (the function argument /
# decorated function is traced by JAX)
TRACE_ENTRIES = {
    "jax.jit", "jax.pmap", "jax.vmap", "jax.grad", "jax.value_and_grad",
    "jax.jacfwd", "jax.jacrev", "jax.hessian", "jax.vjp", "jax.jvp",
    "jax.linearize", "jax.checkpoint", "jax.remat", "jax.eval_shape",
    "jax.make_jaxpr", "jax.named_call", "jax.shard_map",
    "jax.experimental.shard_map.shard_map",
    "jax.lax.scan", "jax.lax.cond", "jax.lax.while_loop",
    "jax.lax.fori_loop", "jax.lax.map", "jax.lax.switch",
    "jax.lax.associative_scan", "jax.custom_jvp", "jax.custom_vjp",
}

# method names that are the framework's trace surface — but only on
# Module-ish classes (dataset Transformers also have an `apply`, which is
# a host-side generator contract): see FileContext._moduleish_classes
TRACED_METHODS = {"apply", "forward_fn", "init", "initial_state"}

# base-class names that mark a class as part of the Module trace surface;
# within-file inheritance chains are resolved transitively
MODULEISH_BASES = {"Module", "Container", "Criterion", "Cell", "Graph"}

# attribute reads that are static at trace time (never a traced value)
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
                "weak_type"}

# calls whose results are static / python-level even on traced operands
STATIC_CALLS = {"isinstance", "hasattr", "getattr", "len", "callable",
                "type", "id", "repr"}

# jax entry points that return python values (backend topology queries),
# not traced arrays
STATIC_JAX_CALLS = {
    "jax.default_backend", "jax.devices", "jax.local_devices",
    "jax.device_count", "jax.local_device_count", "jax.process_count",
    "jax.process_index",
}


class FileContext:
    """Parsed source + the shared analyses rules build on."""

    def __init__(self, source: str, path: str = "<string>"):
        self.source = source
        self.path = path
        self.tree = ast.parse(source)
        self._parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent
        self.line_disables, self.file_disables = _parse_suppressions(source)
        self.aliases = self._import_aliases()
        self.traced = self._traced_functions()
        self._traced_vars: Dict[int, Set[str]] = {}

    # ---- generic helpers -------------------------------------------------
    def walk(self, *types: Type[ast.AST]) -> Iterator[ast.AST]:
        for node in ast.walk(self.tree):
            if not types or isinstance(node, types):
                yield node

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def enclosing(self, node: ast.AST,
                  *types: Type[ast.AST]) -> Optional[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            if isinstance(cur, types):
                return cur
            cur = self.parent(cur)
        return None

    def in_traced(self, node: ast.AST) -> bool:
        """True when ``node`` sits inside a traced function (directly or
        through lexical nesting)."""
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)) and id(cur) in self.traced:
                return True
            cur = self.parent(cur)
        return False

    # ---- name resolution -------------------------------------------------
    def _import_aliases(self) -> Dict[str, str]:
        aliases: Dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def canon(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, resolving
        import aliases: with ``import jax.numpy as jnp``, ``jnp.zeros``
        -> ``jax.numpy.zeros``."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self.aliases.get(cur.id, cur.id)
        return ".".join([root] + list(reversed(parts)))

    # ---- traced-function analysis ----------------------------------------
    def _decorator_traces(self, dec: ast.AST) -> bool:
        if isinstance(dec, ast.Call):
            c = self.canon(dec.func)
            if c == "functools.partial" and dec.args:
                return self.canon(dec.args[0]) in TRACE_ENTRIES
            return c in TRACE_ENTRIES
        return self.canon(dec) in TRACE_ENTRIES

    def _moduleish_classes(self) -> Set[str]:
        """Class names in this file that (transitively) extend a Module-ish
        base — their apply/forward_fn/init methods are trace surface."""
        bases: Dict[str, List[str]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.ClassDef):
                names = []
                for b in node.bases:
                    c = self.canon(b)
                    if c:
                        names.append(c.split(".")[-1])
                bases[node.name] = names
        moduleish = {name for name, bs in bases.items()
                     if MODULEISH_BASES & set(bs)}
        changed = True
        while changed:
            changed = False
            for name, bs in bases.items():
                if name not in moduleish and moduleish & set(bs):
                    moduleish.add(name)
                    changed = True
        return moduleish

    def _traced_functions(self) -> Set[int]:
        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        moduleish = self._moduleish_classes()
        traced: Set[int] = set()
        for group in defs.values():
            for fn in group:
                parent = self.parent(fn)
                if any(self._decorator_traces(d) for d in fn.decorator_list):
                    traced.add(id(fn))
                elif fn.name in TRACED_METHODS \
                        and isinstance(parent, ast.ClassDef) \
                        and parent.name in moduleish:
                    traced.add(id(fn))
        # functions handed by name (or as a lambda) to a trace entry
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if self.canon(node.func) not in TRACE_ENTRIES:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    for fn in defs.get(arg.id, []):
                        traced.add(id(fn))
                elif isinstance(arg, ast.Lambda):
                    traced.add(id(arg))
        # propagation fixpoint:
        # (a) lexical nesting — anything defined inside a traced fn
        # (b) intra-class helpers — `self._helper(...)` called from a
        #     traced method of the same class is traced too
        changed = True
        while changed:
            changed = False
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)) \
                        and id(node) not in traced:
                    cur = self.parent(node)
                    while cur is not None:
                        if id(cur) in traced and isinstance(
                                cur, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                            traced.add(id(node))
                            changed = True
                            break
                        cur = self.parent(cur)
            for cls in ast.walk(self.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                methods = {m.name: m for m in cls.body
                           if isinstance(m, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))}
                for m in methods.values():
                    if id(m) not in traced:
                        continue
                    for call in ast.walk(m):
                        if isinstance(call, ast.Call) \
                                and isinstance(call.func, ast.Attribute) \
                                and isinstance(call.func.value, ast.Name) \
                                and call.func.value.id == "self":
                            callee = methods.get(call.func.attr)
                            if callee is not None \
                                    and id(callee) not in traced:
                                traced.add(id(callee))
                                changed = True
        return traced

    # ---- traced-value dataflow (per function, cached) --------------------
    def _is_arrayish(self, expr: ast.AST, known: Set[str]) -> bool:
        """Heuristic: does ``expr`` produce a traced array? True for calls
        into jnp/lax/jax namespaces and for expressions over known traced
        names; attribute reads of STATIC_ATTRS never count."""
        if isinstance(expr, ast.Attribute) and expr.attr in STATIC_ATTRS:
            return False
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Name) and f.id in STATIC_CALLS:
                return False
            c = self.canon(f)
            if c in STATIC_JAX_CALLS:
                return False
            if c and (c.startswith("jax.") or c == "jax"):
                return True
            return any(self._is_arrayish(a, known) for a in expr.args)
        if isinstance(expr, ast.Name):
            return expr.id in known
        if isinstance(expr, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
                   for op in expr.ops):
                # identity tests are python-level; membership tests are
                # overwhelmingly host-container lookups, not array ops
                return False
            return self._is_arrayish(expr.left, known) or any(
                self._is_arrayish(c, known) for c in expr.comparators)
        if isinstance(expr, (ast.BinOp, ast.UnaryOp, ast.BoolOp,
                             ast.Subscript, ast.IfExp, ast.Tuple, ast.List)):
            return any(self._is_arrayish(c, known)
                       for c in ast.iter_child_nodes(expr)
                       if isinstance(c, ast.expr))
        return False

    def traced_vars(self, fn: ast.AST) -> Set[str]:
        """Names inside ``fn`` bound (transitively) to jnp/lax/jax results.
        Parameters are deliberately NOT included — statically we cannot
        tell an array argument from a python flag like ``training``."""
        cached = self._traced_vars.get(id(fn))
        if cached is not None:
            return cached
        known: Set[str] = set()
        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                targets: List[ast.AST] = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AnnAssign) and node.value:
                    targets, value = [node.target], node.value
                if value is None or not self._is_arrayish(value, known):
                    continue
                for t in targets:
                    # only plain names (and unpacked name tuples) become
                    # traced; `container[key] = arr` does NOT make the
                    # container a traced value
                    if isinstance(t, (ast.Tuple, ast.List)):
                        names = [e for e in t.elts
                                 if isinstance(e, ast.Name)]
                    elif isinstance(t, ast.Name):
                        names = [t]
                    else:
                        names = []
                    for n in names:
                        if n.id not in known:
                            known.add(n.id)
                            changed = True
        self._traced_vars[id(fn)] = known
        return known


# ------------------------------------------------------------------ running

DEFAULT_EXCLUDE_DIRS = {"native", "__pycache__"}


def lint_source(source: str, path: str = "<string>",
                rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one source string; returns findings (suppressed ones flagged,
    not dropped)."""
    import bigdl_tpu.analysis.rules  # noqa: F401  registers built-ins
    try:
        ctx = FileContext(source, path)
    except SyntaxError as e:
        return [Finding("parse-error", path, e.lineno or 1, 0,
                        f"could not parse: {e.msg}")]
    selected = [_RULES[r] for r in rules] if rules else \
        [_RULES[k] for k in sorted(_RULES)]
    findings: List[Finding] = []
    seen = set()
    for r in selected:
        for node, message in r.fn(ctx):
            line = getattr(node, "lineno", 1)
            col = getattr(node, "col_offset", 0)
            key = (r.name, line, col, message)
            if key in seen:  # e.g. one def wrapped at two jit sites
                continue
            seen.add(key)
            on_line = ctx.line_disables.get(line, set())
            suppressed = (r.name in ctx.file_disables
                          or "all" in ctx.file_disables
                          or r.name in on_line or "all" in on_line)
            findings.append(Finding(r.name, path, line, col, message,
                                    suppressed))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def lint_file(path: str,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one file on disk."""
    with open(path, encoding="utf-8") as f:
        return lint_source(f.read(), path, rules)


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/directories into .py files, skipping native/ caches."""
    for p in paths:
        if os.path.isfile(p):
            yield p
            continue
        for root, dirs, files in os.walk(p):
            dirs[:] = sorted(d for d in dirs
                             if d not in DEFAULT_EXCLUDE_DIRS)
            for fn in sorted(files):
                if fn.endswith(".py"):
                    yield os.path.join(root, fn)


def lint_paths(paths: Iterable[str],
               rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint every .py file under ``paths`` (files or directories)."""
    findings: List[Finding] = []
    for fp in iter_python_files(paths):
        findings.extend(lint_file(fp, rules))
    return findings


def format_text(findings: Sequence[Finding],
                show_suppressed: bool = False) -> str:
    """Human-readable report; suppressed findings shown only on request."""
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    lines = [f.format() for f in shown]
    active = sum(1 for f in findings if not f.suppressed)
    muted = len(findings) - active
    lines.append(f"{active} finding{'s' if active != 1 else ''}"
                 f" ({muted} suppressed)")
    return "\n".join(lines)


def to_json(findings: Sequence[Finding]) -> str:
    """Machine-readable report (stable keys; includes suppressed)."""
    return json.dumps([f.to_dict() for f in findings], indent=2)
