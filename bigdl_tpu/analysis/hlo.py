"""Structural parser + static check engine for lowered/compiled XLA
programs.

The compiled-program half of ``bigdl_tpu.analysis``: the AST linter
checks *Python source* before tracing; this module checks the **HLO
text** of a lowered or compiled program before anything executes. The
invariants the repository used to assert with one-off string greps
(donated buffers actually aliased, zero collectives at the windowed
dispatch boundary, f32 islands staying inside the precision policy,
programs fitting HBM) become pluggable, named checks with findings,
severities and suppressions — the same shape as the lint engine, so
``python -m bigdl_tpu.tools.check --programs`` reports them the same
way.

Three layers, all free of jax imports (pure text analysis):

- **Parser** (:func:`parse_hlo`): ``lowered.as_text(dialect="hlo")`` /
  ``compiled.as_text()`` -> :class:`HloModule` — computations (with the
  ENTRY marked), per-op result shapes/dtypes, operands with def-use
  resolution, shardings, metadata, while/cond/fusion sub-computation
  links, and the module-header input/output aliasing + buffer-donor
  tables. Tuple-typed async ``-start`` collectives (the form real TPU
  schedules emit) parse like any other op.
- **Checks** (:func:`hlo_check` registry, built-ins under
  :mod:`bigdl_tpu.analysis.checks`): generator functions over a
  :class:`ProgramSpec` yielding ``(severity, message)``.
- **Runner** (:func:`run_checks`): findings with lint-style
  suppressions (``ProgramSpec.suppress`` names checks sanctioned for
  that program; suppressed findings are retained, not dropped).

:func:`collective_counts` here is the ONE implementation the repo uses;
``parallel.zero.collective_counts`` / ``window_collectives`` are kept
as deprecated shims over it.
"""
from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import (Callable, Dict, Iterator, List, Optional, Sequence,
                    Set, Tuple)

__all__ = [
    "HloOp", "HloComputation", "HloModule", "parse_hlo",
    "collective_counts", "reduce_scatter_evidence", "COLLECTIVE_OPS",
    "ProgramSpec", "ProgramFinding", "HloCheck", "hlo_check",
    "available_checks", "run_checks", "format_findings",
    "findings_to_json", "hbm_fit",
]

# ------------------------------------------------------------------ shapes

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "f16": 2, "bf16": 2,
    "s64": 8, "u64": 8, "s32": 4, "u32": 4, "s16": 2, "u16": 2,
    "s8": 1, "u8": 1, "s4": 1, "u4": 1, "pred": 1,
    "c64": 8, "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
}

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([\d,]*)\]")


def _parse_shapes(type_text: str) -> Tuple[Tuple[str, Tuple[int, ...]], ...]:
    """Every ``dtype[dims]`` leaf in a (possibly tuple) HLO type."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(type_text):
        if dtype not in _DTYPE_BYTES:
            continue
        shape = tuple(int(d) for d in dims.split(",") if d)
        out.append((dtype, shape))
    return tuple(out)


def _shape_bytes(dtype: str, dims: Tuple[int, ...]) -> int:
    n = 1
    for d in dims:
        n *= d
    return n * _DTYPE_BYTES.get(dtype, 4)


def _balanced(text: str, start: int, open_ch: str = "{",
              close_ch: str = "}") -> str:
    """The balanced ``{...}`` (content only) starting at ``start``."""
    depth = 0
    for i in range(start, len(text)):
        if text[i] == open_ch:
            depth += 1
        elif text[i] == close_ch:
            depth -= 1
            if depth == 0:
                return text[start + 1:i]
    return text[start + 1:]


# ------------------------------------------------------------------ the IR

class HloOp:
    """One HLO instruction: result name/type, opcode, operands,
    attributes of interest. Shapes cover tuple-typed results (async
    ``-start`` collectives) — ``shapes`` is a tuple of
    ``(dtype, dims)`` leaves, ``dtype``/``dims`` the first leaf."""

    __slots__ = ("name", "opcode", "result_type", "shapes", "operands",
                 "attrs", "sharding", "metadata", "is_root",
                 "parameter_index", "called", "lineno")

    def __init__(self, name, opcode, result_type, shapes, operands,
                 attrs, sharding, metadata, is_root, parameter_index,
                 called, lineno):
        self.name = name
        self.opcode = opcode
        self.result_type = result_type
        self.shapes = shapes
        self.operands = operands    # operand NAMES (def-use edges)
        self.attrs = attrs          # raw attribute text after operands
        self.sharding = sharding    # raw sharding={...} content or None
        self.metadata = metadata    # {"op_name":..., "source_file":...,
        #                             "source_line":...} (present keys)
        self.is_root = is_root
        self.parameter_index = parameter_index  # int for parameter ops
        self.called = called        # {"body"/"condition"/"calls"/
        #                             "to_apply": computation name}
        self.lineno = lineno

    @property
    def dtype(self) -> Optional[str]:
        return self.shapes[0][0] if self.shapes else None

    @property
    def dims(self) -> Tuple[int, ...]:
        return self.shapes[0][1] if self.shapes else ()

    def result_bytes(self) -> int:
        return sum(_shape_bytes(d, s) for d, s in self.shapes)

    def result_elements(self) -> int:
        total = 0
        for _, dims in self.shapes:
            n = 1
            for d in dims:
                n *= d
            total += n
        return total

    @property
    def replicated(self) -> bool:
        """True when the op carries an explicit ``sharding={replicated}``
        annotation OR no sharding at all (nothing pinned a layout)."""
        return self.sharding is None or self.sharding == "replicated"

    def __repr__(self) -> str:
        return (f"HloOp({self.name!r} = {self.result_type} "
                f"{self.opcode}({', '.join(self.operands)}))")


class HloComputation:
    """One HLO computation (the ENTRY, a while body/condition, a fused
    computation, a reducer)."""

    def __init__(self, name: str, is_entry: bool):
        self.name = name
        self.is_entry = is_entry
        self.ops: List[HloOp] = []
        self.by_name: Dict[str, HloOp] = {}

    def add(self, op: HloOp) -> None:
        self.ops.append(op)
        self.by_name[op.name] = op

    def op(self, name: str) -> Optional[HloOp]:
        return self.by_name.get(name)

    def operand_op(self, op: HloOp, i: int) -> Optional[HloOp]:
        """The defining op of ``op``'s i-th operand (def-use edge within
        this computation), or None for literals/unknown names."""
        if i >= len(op.operands):
            return None
        return self.by_name.get(op.operands[i])

    def operand_dtypes(self, op: HloOp) -> List[Optional[str]]:
        """Result dtype of each operand's defining op (None when the
        operand does not resolve — e.g. a literal)."""
        return [d.dtype if (d := self.by_name.get(nm)) is not None
                else None for nm in op.operands]

    def __repr__(self) -> str:
        tag = "ENTRY " if self.is_entry else ""
        return f"HloComputation({tag}{self.name!r}, {len(self.ops)} ops)"


class HloModule:
    """A parsed HLO module: computations + the header's aliasing and
    donor tables."""

    def __init__(self, name: str, header: str):
        self.name = name
        self.header = header
        self.computations: Dict[str, HloComputation] = {}
        self.entry: Optional[HloComputation] = None
        #: entry-parameter indices the module aliases to an output
        #: (``input_output_alias``) — donation honored via aliasing
        self.aliased_params: Set[int] = set()
        #: entry-parameter indices declared donatable
        #: (``buffer_donor`` — the pre-assignment SPMD form)
        self.donor_params: Set[int] = set()
        self._parse_header(header)

    # ---- header tables ---------------------------------------------------
    def _parse_header(self, header: str) -> None:
        m = re.search(r"input_output_alias=\{", header)
        if m:
            body = _balanced(header, m.end() - 1)
            # entries: "{out,path}: (param, {param_path}[, kind])"
            for pm in re.finditer(r"\}:\s*\(\s*(\d+)", body):
                self.aliased_params.add(int(pm.group(1)))
        m = re.search(r"buffer_donor=\{", header)
        if m:
            body = _balanced(header, m.end() - 1)
            for pm in re.finditer(r"\(\s*(\d+)\s*,", body):
                self.donor_params.add(int(pm.group(1)))

    @property
    def donated_params(self) -> Set[int]:
        """Entry params whose buffers the program may reuse — the union
        of the aliasing table and the donor list."""
        return self.aliased_params | self.donor_params

    # ---- structure -------------------------------------------------------
    def add(self, comp: HloComputation) -> None:
        self.computations[comp.name] = comp
        if comp.is_entry:
            self.entry = comp

    def entry_params(self) -> List[HloOp]:
        """ENTRY ``parameter`` ops, sorted by parameter index."""
        if self.entry is None:
            return []
        params = [op for op in self.entry.ops if op.opcode == "parameter"]
        return sorted(params, key=lambda p: p.parameter_index or 0)

    def find_ops(self, opcode: Optional[str] = None,
                 entry_only: bool = False
                 ) -> Iterator[Tuple[HloComputation, HloOp]]:
        """Iterate ``(computation, op)`` over the module, optionally
        restricted to one opcode / the ENTRY computation."""
        for comp in self.computations.values():
            if entry_only and not comp.is_entry:
                continue
            for op in comp.ops:
                if opcode is None or op.opcode == opcode:
                    yield comp, op

    def while_bodies(self) -> Set[str]:
        """Names of computations used as a ``while`` body (scan/loop
        bodies — where the windowed driver's per-step work lives)."""
        return {op.called["body"] for _, op in self.find_ops("while")
                if "body" in op.called}

    def __repr__(self) -> str:
        return (f"HloModule({self.name!r}, "
                f"{len(self.computations)} computations)")


# ------------------------------------------------------------------ parser

# computation header: optional ENTRY, optional %, optional signature —
# covers scheduled ("%name (args) -> type {") and lowered ("ENTRY main.4
# {") spellings alike
_COMP_RE = re.compile(
    r"^(ENTRY\s+)?%?([\w.\-]+)\s*(?:\(.*\))?\s*(?:->\s*.+?)?\s*\{\s*$")

# instruction: "[ROOT] %name = TYPE opcode(operands...", the TYPE matched
# lazily because tuple types ("(f32[2,4]{1,0}, f32[16,4]{1,0})") contain
# spaces — the async -start collective form real TPU schedules emit
_OP_RE = re.compile(
    r"^\s+(ROOT\s+)?%?([\w.\-]+)\s*=\s*(\S.*?)\s+([a-z][a-z0-9\-]*)\((.*)$")

_NAME_RE = re.compile(r"[A-Za-z_][\w.\-]*")
_CALLED_RE = re.compile(r"\b(body|condition|calls|to_apply)=%?([\w.\-]+)")


def _split_operands(rest: str) -> Tuple[str, str]:
    """``rest`` (text after the opening paren) -> (operand segment,
    attribute text) by balanced-paren scan — operand types can be
    nested tuples (``while((s32[], f32[1]{0}) %t)``)."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i], rest[i + 1:]
    return rest, ""


def _operand_names(segment: str) -> List[str]:
    """Operand result names from an operand segment — ``%name`` refs in
    scheduled text, bare trailing names in lowered text."""
    if "%" in segment:
        return [m.group(1)
                for m in re.finditer(r"%([\w.\-]+)", segment)]
    names = []
    depth = 0
    token = []
    tokens = []
    for ch in segment:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            tokens.append("".join(token))
            token = []
        else:
            token.append(ch)
    tokens.append("".join(token))
    for tok in tokens:
        words = tok.strip().split()
        if not words:
            continue
        m = _NAME_RE.fullmatch(words[-1])
        if m:
            names.append(words[-1])
    return names


def _parse_attrs(attr_text: str):
    sharding = None
    m = re.search(r"\bsharding=\{", attr_text)
    if m:
        sharding = _balanced(attr_text, m.end() - 1).strip()
    metadata: Dict[str, object] = {}
    m = re.search(r'op_name="([^"]*)"', attr_text)
    if m:
        metadata["op_name"] = m.group(1)
    m = re.search(r'source_file="([^"]*)"', attr_text)
    if m:
        metadata["source_file"] = m.group(1)
    m = re.search(r"source_line=(\d+)", attr_text)
    if m:
        metadata["source_line"] = int(m.group(1))
    called = {k: v for k, v in _CALLED_RE.findall(attr_text)}
    return sharding, metadata, called


def parse_hlo(text: str) -> HloModule:
    """Parse HLO text (``compiled.as_text()`` or
    ``lowered.as_text(dialect="hlo")``) into an :class:`HloModule`."""
    lines = text.splitlines()
    name = "module"
    header = ""
    if lines and lines[0].startswith("HloModule"):
        header = lines[0]
        parts = header.split(None, 2)
        if len(parts) >= 2:
            name = parts[1].rstrip(",")
    module = HloModule(name, header)
    comp: Optional[HloComputation] = None
    for lineno, line in enumerate(lines, 1):
        if not line.strip() or line.startswith("HloModule") \
                or line.lstrip().startswith("//"):
            continue
        if comp is not None and line.startswith("}"):
            module.add(comp)
            comp = None
            continue
        if comp is None:
            m = _COMP_RE.match(line)
            if m and not line.startswith(" "):
                comp = HloComputation(m.group(2),
                                      is_entry=bool(m.group(1)))
            continue
        m = _OP_RE.match(line)
        if m is None:
            continue
        is_root, op_name, type_text, opcode, rest = (
            bool(m.group(1)), m.group(2), m.group(3), m.group(4),
            m.group(5))
        operand_seg, attr_text = _split_operands(rest)
        sharding, metadata, called = _parse_attrs(attr_text)
        param_idx = None
        if opcode == "parameter":
            pm = re.match(r"\s*(\d+)", operand_seg)
            if pm:
                param_idx = int(pm.group(1))
        operands = [] if opcode in ("parameter", "constant") \
            else _operand_names(operand_seg)
        comp.add(HloOp(op_name, opcode, type_text.strip(),
                       _parse_shapes(type_text), operands, attr_text,
                       sharding, metadata, is_root, param_idx, called,
                       lineno))
    if comp is not None:  # unterminated tail computation
        module.add(comp)
    return module


def _as_module(program) -> HloModule:
    """Accept an :class:`HloModule`, HLO text, or an object with
    ``as_text()`` (a compiled jit program)."""
    if isinstance(program, HloModule):
        return program
    if isinstance(program, str):
        return parse_hlo(program)
    return parse_hlo(program.as_text())


# ------------------------------------------------------------ collectives

#: ops counted by :func:`collective_counts` — ``dynamic-slice`` is not
#: itself a collective but is counted because XLA CPU lowers
#: reduce-scatter to all-reduce + dynamic-slice (the scatter evidence on
#: that backend is the pair, not the fused op)
COLLECTIVE_OPS = ("all-gather", "reduce-scatter", "all-reduce",
                  "collective-permute", "all-to-all", "dynamic-slice")

#: the subset that is genuinely cross-device communication (what the
#: entry-collective dispatch-boundary contract bans from ENTRY)
COMMUNICATION_OPS = ("all-gather", "reduce-scatter", "all-reduce",
                     "collective-permute", "all-to-all")


def collective_counts(program) -> Dict[str, Dict[str, int]]:
    """Count collective ops, split ENTRY vs everything else (scan/while
    bodies, fusions): ``{"all-gather": {"total": n, "entry": m}, ...}``.

    Async ``-start`` forms count once under their base op (the ``-done``
    twin is never counted), including the tuple-typed result spelling
    real TPU schedules emit. Accepts HLO text, a parsed
    :class:`HloModule`, or a compiled program object."""
    module = _as_module(program)
    counts = {op: {"total": 0, "entry": 0} for op in COLLECTIVE_OPS}
    for comp in module.computations.values():
        for op in comp.ops:
            base = op.opcode[:-6] if op.opcode.endswith("-start") \
                else op.opcode
            if base not in counts:
                continue
            counts[base]["total"] += 1
            if comp.is_entry:
                counts[base]["entry"] += 1
    return counts


def reduce_scatter_evidence(counts: Dict[str, Dict[str, int]]) -> bool:
    """True when the program reduce-scatters gradients: a literal
    ``reduce-scatter`` op (TPU), or the CPU lowering's
    all-reduce + dynamic-slice pair."""
    if counts["reduce-scatter"]["total"] > 0:
        return True
    return (counts["all-reduce"]["total"] > 0
            and counts["dynamic-slice"]["total"] > 0)


def hbm_fit(analysis: Dict[str, float],
            budget_bytes: Optional[int]) -> Dict[str, object]:
    """Static HBM feasibility of one program: does ``arguments +
    outputs + temps`` fit ``budget_bytes``? ``analysis`` is the dict
    :func:`bigdl_tpu.telemetry.programs.analyze_compiled` returns (or
    any mapping with ``arg_bytes``/``out_bytes``/``temp_bytes``).

    This is the API the profile-guided autotuner (ROADMAP item 4)
    calls per candidate config: lowering + ``memory_analysis`` only —
    no execution — prunes HBM-infeasible points before anything runs.
    Returns ``{fits, total_bytes, budget_bytes, breakdown}``; a None
    budget always fits (reported, never enforced)."""
    breakdown = {k: float(analysis.get(k, 0.0))
                 for k in ("arg_bytes", "out_bytes", "temp_bytes")}
    total = int(sum(breakdown.values()))
    fits = budget_bytes is None or total <= budget_bytes
    return {"fits": fits, "total_bytes": total,
            "budget_bytes": budget_bytes, "breakdown": breakdown}


# ------------------------------------------------------------ check engine

@dataclass
class ProgramSpec:
    """One program under verification + the contract context its checks
    need. ``module`` is the parsed *compiled* text (aliasing tables,
    collective placement); ``lowered`` the parsed pre-optimization HLO
    (parameter shardings, the policy's dtype intent — backends legalize
    dtypes during compilation, so precision contracts read the lowered
    form). Thresholds are per-program so fixtures and the autotuner can
    tighten them."""

    name: str
    module: Optional[HloModule] = None
    lowered: Optional[HloModule] = None
    #: expected donated leaf count (-1: no donation contract declared)
    donated: int = -1
    #: the steps_per_sync dispatch-boundary contract applies
    window: bool = False
    scan_length: int = 1
    #: a smaller-K build of the same window (scan-dispatch-ratio)
    companion: Optional["ProgramSpec"] = None
    zero_stage: int = 0
    ndev: int = 1
    #: entry-parameter indices the ZeRO config expects sharded
    sharded_params: Tuple[int, ...] = ()
    #: precision policy name compiled into the program (None = f32)
    policy: Optional[str] = None
    compute_dtype: Optional[str] = None
    #: ``memory_analysis`` numbers (arg/out/temp bytes)
    memory: Optional[Dict[str, float]] = None
    hbm_budget: Optional[int] = None
    #: replicated-large-operand threshold (bytes per parameter)
    large_bytes: int = 1 << 20
    #: precision-leak: f32 dot/conv operand threshold (elements)
    dot_elems: int = 4096
    #: precision-leak: giant f32 convert threshold (bytes)
    convert_bytes: int = 16 << 20
    #: checks sanctioned for this program (findings kept, suppressed)
    suppress: Tuple[str, ...] = ()
    #: free-form context (kind, bucket, K ...) carried into reports
    extra: Dict[str, object] = field(default_factory=dict)


@dataclass
class ProgramFinding:
    """One check finding on one program."""

    check: str
    program: str
    severity: str  # "error" | "warning"
    message: str
    suppressed: bool = False

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return (f"{self.program}: [{self.check}/{self.severity}]{tag} "
                f"{self.message}")

    def to_dict(self) -> dict:
        return {"check": self.check, "program": self.program,
                "severity": self.severity, "message": self.message,
                "suppressed": self.suppressed}


@dataclass
class HloCheck:
    """A registered program check: ``fn(spec)`` yields
    ``(severity, message)``."""

    name: str
    description: str
    fn: Callable[[ProgramSpec], Iterator[Tuple[str, str]]]


_CHECKS: Dict[str, HloCheck] = {}


def hlo_check(name: str, description: str):
    """Decorator registering a compiled-program check under ``name``
    (the HLO twin of :func:`bigdl_tpu.analysis.lint.rule`)."""
    def deco(fn):
        if name in _CHECKS:
            raise ValueError(f"duplicate hlo check {name!r}")
        _CHECKS[name] = HloCheck(name, description, fn)
        return fn
    return deco


def available_checks() -> List[HloCheck]:
    """All registered checks, sorted by name (importing the
    built-ins)."""
    import bigdl_tpu.analysis.checks  # noqa: F401  registers on import
    return [_CHECKS[k] for k in sorted(_CHECKS)]


def run_checks(specs: Sequence[ProgramSpec],
               checks: Optional[Sequence[str]] = None
               ) -> List[ProgramFinding]:
    """Run checks over every program spec; returns findings (suppressed
    ones flagged, not dropped). ``checks`` restricts to a named subset
    (unknown names raise KeyError, like the lint engine)."""
    import bigdl_tpu.analysis.checks  # noqa: F401  registers built-ins
    selected = [_CHECKS[c] for c in checks] if checks else \
        [_CHECKS[k] for k in sorted(_CHECKS)]
    findings: List[ProgramFinding] = []
    for spec in specs:
        for check in selected:
            for severity, message in check.fn(spec):
                findings.append(ProgramFinding(
                    check.name, spec.name, severity, message,
                    suppressed=check.name in spec.suppress))
    findings.sort(key=lambda f: (f.program, f.check, f.message))
    return findings


def format_findings(findings: Sequence[ProgramFinding],
                    programs: int = 0,
                    show_suppressed: bool = False) -> str:
    """Human-readable report, lint-style."""
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    lines = [f.format() for f in shown]
    active = sum(1 for f in findings if not f.suppressed)
    muted = len(findings) - active
    lines.append(
        f"{active} program finding{'s' if active != 1 else ''}"
        f" ({muted} suppressed) across {programs} programs")
    return "\n".join(lines)


def findings_to_json(findings: Sequence[ProgramFinding]) -> str:
    """Machine-readable report (stable keys; includes suppressed)."""
    return json.dumps([f.to_dict() for f in findings], indent=2)
