"""Typed, bounded configuration search spaces for the autotuner.

Every performance knob the package measured into existence — the
``steps_per_sync`` window K (PR 4), ZeRO stage (PR 8), precision preset
(PR 9), the pallas flash toggle (PR 11) for training; length-bucket
ladder, continuous-batching slots, speculation depth and prefix-cache
bytes (PRs 6/14) for serving — becomes one axis of a declared space.
Axes are **bounded at construction** (a space whose values fall outside
the documented knob ranges refuses to exist) and cross-axis validity is
expressed in :func:`enumerate_candidates` as CODE, not prose: invalid
combinations are returned with their reason, never silently dropped.

The grammar is deliberately flat — a space is a cartesian product of
small tuples minus the coded constraints — because every candidate
must be cheap to price statically (``autotune/prune``) and the sweep
must stay enumerable, deterministic and auditable.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["SpaceError", "Candidate", "TrainSpace", "ServingSpace",
           "enumerate_candidates"]

#: the precision presets ``PrecisionPolicy.named`` accepts — the ONE
#: list, mirrored here so a space typo fails at construction, not after
#: an hour of measuring
PRECISION_PRESETS = ("f32", "bf16_mixed", "f16_mixed")

#: train models the tuner knows how to build tiny twins of
TRAIN_MODELS = ("mlp", "transformer_lm")


class SpaceError(ValueError):
    """A search-space axis violated its documented bounds (typed so
    callers can distinguish a bad space from a bad candidate)."""


@dataclass(frozen=True)
class Candidate:
    """One point of a search space: an immutable ``(key, value)``
    mapping plus the regime it configures. ``cid`` is the stable
    identifier the leaderboard, the pruned-candidate log and the tuned
    artifact all key on — same values, same cid, every process."""

    regime: str  # "train" | "serving"
    items: Tuple[Tuple[str, object], ...]

    @property
    def config(self) -> Dict[str, object]:
        """The candidate's axis values as a plain dict."""
        return dict(self.items)

    @property
    def cid(self) -> str:
        """Deterministic candidate id, e.g.
        ``train:batch_size=16,steps_per_sync=8,...`` (keys sorted)."""
        parts = ",".join(f"{k}={_fmt(v)}" for k, v in sorted(self.items))
        return f"{self.regime}:{parts}"

    def to_dict(self) -> Dict[str, object]:
        """JSON-ready form (lists for tuple-valued axes)."""
        return {"regime": self.regime, "cid": self.cid,
                "config": {k: (list(v) if isinstance(v, tuple) else v)
                           for k, v in self.items}}

    def __repr__(self) -> str:
        return f"Candidate({self.cid})"


def _fmt(v) -> str:
    if isinstance(v, tuple):
        return "[" + "x".join(str(e) for e in v) + "]"
    return str(v)


def _require(cond: bool, msg: str) -> None:
    if not cond:
        raise SpaceError(msg)


@dataclass(frozen=True)
class TrainSpace:
    """The training-regime axes: ``steps_per_sync`` K x ZeRO stage x
    precision preset x flash attention on/off x batch size x
    sequence-parallel degree x blockwise long-context routing, over a
    named tiny model twin (``mlp`` | ``transformer_lm``). Bounds are
    enforced at construction; cross-axis validity (ZeRO divisibility,
    flash needs attention, SP needs shard_map + devices, blockwise
    needs flash) lives in :func:`enumerate_candidates`."""

    steps_per_sync: Tuple[int, ...] = (1, 8)
    zero_stage: Tuple[int, ...] = (0,)
    precision: Tuple[str, ...] = ("f32",)
    flash: Tuple[bool, ...] = (False,)
    batch_size: Tuple[int, ...] = (16,)
    #: sequence-parallel degree (0 = dense attention; >= 2 installs a
    #: SeqParallelConfig over a degree-wide "seq" mesh axis)
    seq_parallel: Tuple[int, ...] = (0,)
    #: blockwise long-context flash routing past the VMEM budget
    #: (KernelConfig.long_context) — only meaningful with flash=True
    long_context: Tuple[bool, ...] = (False,)
    model: str = "mlp"

    def __post_init__(self):
        for name in ("steps_per_sync", "zero_stage", "precision",
                     "flash", "batch_size", "seq_parallel",
                     "long_context"):
            _require(len(getattr(self, name)) > 0,
                     f"TrainSpace.{name} must be non-empty")
        _require(all(d == 0 or 2 <= d <= 64 for d in self.seq_parallel),
                 f"seq_parallel degrees must be 0 (off) or in [2, 64], "
                 f"got {self.seq_parallel}")
        _require(all(isinstance(b, bool) for b in self.long_context),
                 f"long_context values must be bools, got "
                 f"{self.long_context}")
        _require(all(1 <= k <= 512 for k in self.steps_per_sync),
                 f"steps_per_sync values must be in [1, 512], got "
                 f"{self.steps_per_sync}")
        _require(all(s in (0, 1, 2, 3) for s in self.zero_stage),
                 f"zero_stage values must be in 0..3, got "
                 f"{self.zero_stage}")
        _require(all(p in PRECISION_PRESETS for p in self.precision),
                 f"precision values must be from {PRECISION_PRESETS}, "
                 f"got {self.precision}")
        _require(all(isinstance(f, bool) for f in self.flash),
                 f"flash values must be bools, got {self.flash}")
        _require(all(1 <= b <= 65536 for b in self.batch_size),
                 f"batch_size values must be in [1, 65536], got "
                 f"{self.batch_size}")
        _require(self.model in TRAIN_MODELS,
                 f"model must be one of {TRAIN_MODELS}, "
                 f"got {self.model!r}")

    def axes(self) -> Dict[str, Sequence]:
        """Axis name -> value tuple, enumeration order (sorted by axis
        name so candidate order is a pure function of the space)."""
        return {"batch_size": self.batch_size, "flash": self.flash,
                "long_context": self.long_context,
                "precision": self.precision,
                "seq_parallel": self.seq_parallel,
                "steps_per_sync": self.steps_per_sync,
                "zero_stage": self.zero_stage}


@dataclass(frozen=True)
class ServingSpace:
    """The serving-regime axes: length-bucket ladder x slots x
    speculation depth k x prefix-cache bytes x chunked-prefill width,
    at a fixed ``max_len``. The GenerationService contract — the top
    ladder rung IS the cache time axis — is checked per ladder at
    construction; the chunk-divides-every-larger-rung admission rule
    per candidate in :func:`enumerate_candidates`."""

    max_len: int = 64
    length_buckets: Tuple[Tuple[int, ...], ...] = ((64,),)
    slots: Tuple[int, ...] = (4,)
    speculation_k: Tuple[int, ...] = (0,)
    prefix_cache_bytes: Tuple[int, ...] = (0,)
    #: chunked-prefill width (0 = single-shot): long prompts admit in
    #: fixed [rows, chunk] pieces — the engine's divide-every-larger-
    #: rung admission rule is coded per ladder in enumerate_candidates
    prefill_chunk: Tuple[int, ...] = (0,)

    def __post_init__(self):
        _require(1 <= self.max_len <= 131072,
                 f"max_len must be in [1, 131072], got {self.max_len}")
        _require(all(0 <= c <= self.max_len for c in self.prefill_chunk),
                 f"prefill_chunk values must be in [0, max_len="
                 f"{self.max_len}], got {self.prefill_chunk}")
        for name in ("length_buckets", "slots", "speculation_k",
                     "prefix_cache_bytes", "prefill_chunk"):
            _require(len(getattr(self, name)) > 0,
                     f"ServingSpace.{name} must be non-empty")
        for ladder in self.length_buckets:
            _require(len(ladder) > 0 and
                     all(isinstance(b, int) and b > 0 for b in ladder),
                     f"ladder {ladder} must be positive ints")
            _require(tuple(sorted(set(ladder))) == tuple(ladder),
                     f"ladder {ladder} must be strictly ascending")
            _require(ladder[-1] == self.max_len,
                     f"ladder {ladder} top rung must equal "
                     f"max_len={self.max_len} (the cache time axis)")
        _require(all(1 <= s <= 1024 for s in self.slots),
                 f"slots values must be in [1, 1024], got {self.slots}")
        _require(all(0 <= k <= 8 for k in self.speculation_k),
                 f"speculation_k values must be in [0, 8], got "
                 f"{self.speculation_k}")
        _require(all(b >= 0 for b in self.prefix_cache_bytes),
                 f"prefix_cache_bytes values must be >= 0, got "
                 f"{self.prefix_cache_bytes}")

    def axes(self) -> Dict[str, Sequence]:
        """Axis name -> value tuple, enumeration order."""
        return {"length_buckets": self.length_buckets,
                "prefill_chunk": self.prefill_chunk,
                "prefix_cache_bytes": self.prefix_cache_bytes,
                "slots": self.slots,
                "speculation_k": self.speculation_k}


def _train_constraints(cfg: Dict[str, object], space: TrainSpace,
                       ndev: int) -> Optional[str]:
    """The coded validity rules for one train candidate; returns the
    violation reason or None. These mirror REAL runtime refusals
    (``tools/perf`` exits on ZeRO/batch mismatch; flash attention has
    nothing to dispatch on an attention-free model), so an invalid
    point is rejected here instead of wasting a measurement window."""
    if cfg["zero_stage"] > 0 and cfg["batch_size"] % ndev:
        return (f"zero_stage={cfg['zero_stage']} needs batch_size "
                f"divisible by the {ndev}-device data mesh, got "
                f"{cfg['batch_size']}")
    if cfg["flash"] and space.model != "transformer_lm":
        return (f"flash=True has no attention to dispatch on "
                f"model={space.model!r} (the toggle would silently "
                f"measure the identical program twice)")
    if cfg["long_context"] and not cfg["flash"]:
        return ("long_context=True is a routing of the flash dispatch "
                "(blockwise past the VMEM budget); with flash=False "
                "it would measure the identical reference program "
                "twice")
    sp = int(cfg["seq_parallel"])
    if sp > 0:
        if space.model != "transformer_lm":
            return (f"seq_parallel={sp} has no attention to shard on "
                    f"model={space.model!r}")
        if sp > ndev:
            return (f"seq_parallel={sp} needs a {sp}-device sequence "
                    f"mesh, process has {ndev}")
        from bigdl_tpu.parallel.sequence import (
            sequence_parallel_available)
        if not sequence_parallel_available():
            return (f"seq_parallel={sp} needs jax.shard_map, absent "
                    f"in this jax build (the policy would quietly "
                    f"no-op and measure the dense program twice)")
        if cfg["zero_stage"] > 0:
            return (f"seq_parallel={sp} with zero_stage="
                    f"{cfg['zero_stage']}: the default measure "
                    f"harness builds a 1-D mesh per candidate — "
                    f"compose SP with ZeRO on a 2-D mesh via a custom "
                    f"runner=")
    return None


def _serving_constraints(cfg: Dict[str, object], space: ServingSpace
                         ) -> Optional[str]:
    """Coded validity rules for one serving candidate."""
    if cfg["speculation_k"] >= space.max_len:
        return (f"speculation_k={cfg['speculation_k']} must be < "
                f"max_len={space.max_len} (the verify forward needs "
                f"room for k proposed tokens)")
    if cfg["speculation_k"] > 0 and cfg["prefix_cache_bytes"] > 0:
        return ("speculation_k > 0 with prefix_cache_bytes > 0: the "
                "speculative decoder manages its own cache seeding and "
                "does not compose with the prefix cache in one service")
    chunk = int(cfg["prefill_chunk"])
    if chunk > 0:
        # the engine's own admission rule (DecodeEngine raises on it):
        # chunked rungs must split into an exact number of chunks
        bad = [b for b in cfg["length_buckets"] if b > chunk and b % chunk]
        if bad:
            return (f"prefill_chunk={chunk} must divide every larger "
                    f"ladder rung, fails on {bad} of "
                    f"{cfg['length_buckets']}")
        if all(b <= chunk for b in cfg["length_buckets"]):
            return (f"prefill_chunk={chunk} >= the top rung "
                    f"{cfg['length_buckets'][-1]}: no rung ever "
                    f"chunks, the candidate measures the single-shot "
                    f"program twice")
    return None


def enumerate_candidates(space, ndev: Optional[int] = None
                         ) -> Tuple[List[Candidate],
                                    List[Tuple[Candidate, str]]]:
    """Deterministically enumerate a space: the cartesian product of
    its axes (axis-name-sorted, value order as declared) split by the
    coded validity constraints into ``(valid, invalid)`` where each
    invalid entry carries its reason — nothing is silently dropped.

    ``ndev`` is the data-mesh width the ZeRO divisibility rule checks
    against (default: the process's JAX device count)."""
    if isinstance(space, TrainSpace):
        regime, check = "train", _train_constraints
    elif isinstance(space, ServingSpace):
        regime, check = "serving", _serving_constraints
    else:
        raise SpaceError(f"not a search space: {type(space).__name__}")
    if ndev is None and regime == "train":
        import jax
        ndev = len(jax.devices())
    axes = space.axes()
    names = list(axes)
    valid: List[Candidate] = []
    invalid: List[Tuple[Candidate, str]] = []
    for values in itertools.product(*(axes[n] for n in names)):
        cfg = dict(zip(names, values))
        items = dict(cfg)
        if regime == "train":
            # the model twin is per-space, not an axis, but pruning and
            # measurement are per-candidate — carry it on each point
            items["model"] = space.model
        cand = Candidate(regime, tuple(sorted(items.items())))
        reason = check(cfg, space, ndev) if regime == "train" \
            else check(cfg, space)
        if reason is None:
            valid.append(cand)
        else:
            invalid.append((cand, reason))
    return valid, invalid
