"""The sanctioned home of tuned-constant defaults and smoke spaces.

The ``hardcoded-tuned-constant`` lint rule flags literal
``steps_per_sync`` / bucket-ladder / cache-byte values in the tool and
bench layers — a hand-picked constant there silently overrides what the
autotuner measured. THIS module is the one place such literals are
sanctioned: the hand-picked defaults live here with their rationale,
the smoke search spaces are built here, and every consumer
(``tools/autotune``, ``tools/perf --config``, bench's TUNED row) reads
them through this module or through a ``tuned.json`` artifact.
"""
from __future__ import annotations

from typing import Dict

from bigdl_tpu.autotune.space import ServingSpace, TrainSpace

__all__ = ["DEFAULT_TRAIN_CONFIG", "DEFAULT_SERVING_CONFIG",
           "SMOKE_HBM_BUDGET_BYTES", "INFEASIBLE_BATCH",
           "smoke_train_space", "smoke_serving_space",
           "default_train_space", "default_serving_space"]

#: the hand-picked training defaults the tuned artifact is measured
#: against (K=1 per-step dispatch, no ZeRO, full f32, reference
#: kernels — the package's conservative out-of-the-box behavior)
DEFAULT_TRAIN_CONFIG: Dict[str, object] = {
    "steps_per_sync": 1, "zero_stage": 0, "precision": "f32",
    "flash": False, "batch_size": 16, "seq_parallel": 0,
    "long_context": False,
}

#: the hand-picked serving defaults (one full-length bucket, 4 slots,
#: no speculation, prefix cache off — GenerationConfig's own spirit at
#: smoke scale)
DEFAULT_SERVING_CONFIG: Dict[str, object] = {
    "length_buckets": (64,), "slots": 4, "speculation_k": 0,
    "prefix_cache_bytes": 0, "prefill_chunk": 0,
}

#: the CPU-smoke per-device HBM budget (1 MiB): small enough that the
#: smoke space's deliberately oversized batch is infeasible on ANY
#: host, large enough that the tiny-model candidates all fit
SMOKE_HBM_BUDGET_BYTES = 1 << 20

#: deliberately HBM-infeasible batch size for the smoke space: at
#: 65536 rows x 16 f32 features the batch alone is 4 MiB — over the
#: 1 MiB smoke budget, so the static pruner MUST reject it before
#: anything compiles (the CLI acceptance bound)
INFEASIBLE_BATCH = 65536


def smoke_train_space() -> TrainSpace:
    """The bounded CPU-smoke training space: <= 8 candidates spanning
    K, precision and batch size — including the hand-picked default
    point (so the winner's objective >= the default's by construction)
    and one deliberately HBM-infeasible batch the static pruner must
    reject with zero compiles."""
    return TrainSpace(
        steps_per_sync=(1, 4),
        zero_stage=(0,),
        precision=("f32", "bf16_mixed"),
        flash=(False,),
        batch_size=(16, INFEASIBLE_BATCH),
        model="mlp")


def default_train_space() -> TrainSpace:
    """The standard training sweep ``tools/autotune`` runs without
    ``--smoke``: K x precision x flash over the attention-bearing tiny
    twin, at the default batch (ZeRO stages need a multi-device mesh to
    change anything — sweep them where they act)."""
    return TrainSpace(
        steps_per_sync=(1, 4, 8),
        zero_stage=(0,),
        precision=("f32", "bf16_mixed"),
        flash=(False, True),
        batch_size=(16,),
        model="transformer_lm")


def default_serving_space() -> ServingSpace:
    """The standard serving sweep: ladder shape x slots x prefix-cache
    budget x chunked-prefill width at a 64-token smoke horizon (chunk
    16 divides every rung of both ladders; 0 is single-shot)."""
    return ServingSpace(
        max_len=64,
        length_buckets=((64,), (16, 32, 64)),
        slots=(2, 4),
        speculation_k=(0,),
        prefix_cache_bytes=(0, 1 << 20),
        prefill_chunk=(0, 16))


def smoke_serving_space() -> ServingSpace:
    """The bounded CPU-smoke serving space: <= 4 candidates over the
    ladder and prefix-cache axes, including the hand-picked default
    point."""
    return ServingSpace(
        max_len=64,
        length_buckets=((64,), (32, 64)),
        slots=(4,),
        speculation_k=(0,),
        prefix_cache_bytes=(0, 1 << 20))
