"""The tuned-config artifact: versioned, fingerprinted ``tuned.json``.

The sweep's output is CONFIGURATION, so it gets the same rigor as a
checkpoint: a schema version that readers validate, the full
leaderboard (not just the winner — a later session can audit why), the
pruned-candidate log, and an **environment fingerprint** (device kind,
platform, device count, mesh shape, package version). A consumer —
``tools/perf --config``, bench's TUNED row, the serving facade's
:func:`~bigdl_tpu.generation.service.apply_tuned_config` — refuses an
artifact whose fingerprint mismatches the running environment with a
typed :class:`FingerprintMismatchError`: a config tuned for one
machine silently misapplied to another is worse than no tuning.

Serialization is canonical (sorted keys, fixed indent, trailing
newline) so the same seed produces byte-identical artifacts — the
property the determinism tests pin.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["TUNED_SCHEMA_VERSION", "TunedConfigError",
           "FingerprintMismatchError", "Fingerprint", "TunedConfig",
           "save_tuned", "load_tuned", "apply_to_perf_args",
           "apply_tuned_optimizer"]

#: bump when the artifact layout changes; readers refuse unknown
#: versions instead of guessing
TUNED_SCHEMA_VERSION = 1


class TunedConfigError(ValueError):
    """A tuned.json artifact is malformed or has an unknown schema."""


class FingerprintMismatchError(TunedConfigError):
    """The artifact was tuned on a different environment than the one
    trying to apply it. Carries the per-field differences."""

    def __init__(self, mismatches: Dict[str, Tuple[object, object]]):
        self.mismatches = dict(mismatches)
        detail = "; ".join(
            f"{k}: artifact={a!r} vs running={b!r}"
            for k, (a, b) in sorted(self.mismatches.items()))
        super().__init__(
            f"tuned.json fingerprint mismatch ({detail}) — re-run "
            f"`python -m bigdl_tpu.tools.autotune` on this environment "
            f"or pass allow_mismatch=True to inspect anyway")


@dataclass(frozen=True)
class Fingerprint:
    """The environment a tuned artifact is valid for."""

    device_kind: str
    platform: str
    device_count: int
    mesh_shape: Tuple[int, ...]
    package_version: str

    @classmethod
    def current(cls) -> "Fingerprint":
        """Fingerprint of the running process (JAX devices + package
        version; mesh shape is the flat device count until a mesh is
        explicitly configured)."""
        import jax

        import bigdl_tpu

        devs = jax.devices()
        return cls(device_kind=devs[0].device_kind,
                   platform=devs[0].platform,
                   device_count=len(devs),
                   mesh_shape=(len(devs),),
                   package_version=bigdl_tpu.__version__)

    def to_dict(self) -> Dict[str, object]:
        """JSON-ready form."""
        return {"device_kind": self.device_kind,
                "platform": self.platform,
                "device_count": self.device_count,
                "mesh_shape": list(self.mesh_shape),
                "package_version": self.package_version}

    @classmethod
    def from_dict(cls, d: Dict[str, object]) -> "Fingerprint":
        """Parse; raises :class:`TunedConfigError` on missing keys."""
        try:
            return cls(device_kind=str(d["device_kind"]),
                       platform=str(d["platform"]),
                       device_count=int(d["device_count"]),
                       mesh_shape=tuple(int(x)
                                        for x in d["mesh_shape"]),
                       package_version=str(d["package_version"]))
        except (KeyError, TypeError, ValueError) as e:
            raise TunedConfigError(
                f"invalid fingerprint block: {e!r}") from e

    def mismatches(self, other: "Fingerprint"
                   ) -> Dict[str, Tuple[object, object]]:
        """Field-by-field differences vs ``other`` (empty = match)."""
        out: Dict[str, Tuple[object, object]] = {}
        for k in ("device_kind", "platform", "device_count",
                  "mesh_shape", "package_version"):
            a, b = getattr(self, k), getattr(other, k)
            if a != b:
                out[k] = (a, b)
        return out


@dataclass
class TunedConfig:
    """One sweep's result: winners per regime, the full leaderboard,
    the pruned log, the fingerprint and the seed that produced it."""

    fingerprint: Fingerprint
    seed: int
    #: regime -> winning config dict (axis name -> value)
    winners: Dict[str, Dict[str, object]] = field(default_factory=dict)
    #: regime -> objective name ("train_steps_per_sec" / ...)
    objectives: Dict[str, str] = field(default_factory=dict)
    #: every measured candidate: {cid, regime, config, objective, ok,
    #: error} sorted best-first per regime
    leaderboard: List[Dict[str, object]] = field(default_factory=list)
    #: every statically dropped candidate: {candidate, stage, reason}
    pruned: List[Dict[str, object]] = field(default_factory=list)
    #: recorded policy decisions, e.g. {"flash_attention": {...}}
    decisions: Dict[str, object] = field(default_factory=dict)
    schema_version: int = TUNED_SCHEMA_VERSION

    def winner(self, regime: str) -> Dict[str, object]:
        """The winning config for ``regime``; typed error if the sweep
        never measured that regime."""
        try:
            return self.winners[regime]
        except KeyError:
            raise TunedConfigError(
                f"tuned.json has no {regime!r} winner (regimes: "
                f"{sorted(self.winners) or 'none'})") from None

    def to_json(self) -> str:
        """Canonical serialization — sorted keys, indent 2, trailing
        newline — so equal sweeps are equal BYTES."""
        payload = {
            "schema_version": self.schema_version,
            "fingerprint": self.fingerprint.to_dict(),
            "seed": self.seed,
            "winners": self.winners,
            "objectives": self.objectives,
            "leaderboard": self.leaderboard,
            "pruned": self.pruned,
            "decisions": self.decisions,
        }
        return json.dumps(payload, sort_keys=True, indent=2) + "\n"


def save_tuned(cfg: TunedConfig, path: str) -> str:
    """Write the artifact atomically (tmp + rename); returns ``path``."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(cfg.to_json())
    os.replace(tmp, path)
    return path


def _tuplify(cfg: Dict[str, object]) -> Dict[str, object]:
    return {k: (tuple(v) if isinstance(v, list) else v)
            for k, v in cfg.items()}


def load_tuned(path: str, *, fingerprint: Optional[Fingerprint] = None,
               allow_mismatch: bool = False) -> TunedConfig:
    """Load + validate a ``tuned.json``: schema version must be known,
    the fingerprint block must parse, and unless ``allow_mismatch`` the
    artifact's fingerprint must equal the running environment's
    (``fingerprint`` overrides :meth:`Fingerprint.current`, for tests).
    Raises :class:`TunedConfigError` / :class:`FingerprintMismatchError`.
    """
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise TunedConfigError(f"cannot read tuned.json at "
                               f"{path!r}: {e}") from e
    if not isinstance(raw, dict):
        raise TunedConfigError("tuned.json root must be an object")
    version = raw.get("schema_version")
    if version != TUNED_SCHEMA_VERSION:
        raise TunedConfigError(
            f"unknown tuned.json schema_version {version!r} "
            f"(this build reads {TUNED_SCHEMA_VERSION})")
    for key in ("fingerprint", "seed", "winners"):
        if key not in raw:
            raise TunedConfigError(f"tuned.json missing {key!r}")
    artifact_fp = Fingerprint.from_dict(raw["fingerprint"])
    running = fingerprint or Fingerprint.current()
    diff = artifact_fp.mismatches(running)
    if diff and not allow_mismatch:
        raise FingerprintMismatchError(diff)
    winners = {r: _tuplify(dict(c))
               for r, c in dict(raw["winners"]).items()}
    return TunedConfig(
        fingerprint=artifact_fp, seed=int(raw["seed"]),
        winners=winners,
        objectives=dict(raw.get("objectives", {})),
        leaderboard=list(raw.get("leaderboard", [])),
        pruned=list(raw.get("pruned", [])),
        decisions=dict(raw.get("decisions", {})),
        schema_version=int(version))


def apply_to_perf_args(cfg: TunedConfig, args) -> List[str]:
    """Apply the train winner onto a ``tools/perf`` argparse namespace
    (in place); returns the list of fields changed. Only knobs the
    winner carries are touched — everything else keeps its CLI value."""
    winner = cfg.winner("train")
    applied: List[str] = []
    mapping = {"steps_per_sync": "steps_per_sync",
               "zero_stage": "zero", "precision": "precision",
               "batch_size": "batch_size"}
    for axis, attr in mapping.items():
        if axis in winner and hasattr(args, attr):
            setattr(args, attr, winner[axis])
            applied.append(attr)
    if "flash" in winner and hasattr(args, "kernels"):
        args.kernels = "on" if winner["flash"] else "off"
        applied.append("kernels")
    return applied


def apply_tuned_optimizer(cfg: TunedConfig, optimizer):
    """Apply the train winner onto a live ``Optimizer`` through its own
    setters (``set_steps_per_sync`` / ``set_zero`` / ``set_precision``)
    — the artifact configures, it never bypasses."""
    winner = cfg.winner("train")
    if "steps_per_sync" in winner:
        optimizer.set_steps_per_sync(int(winner["steps_per_sync"]))
    if "zero_stage" in winner:
        from bigdl_tpu.parallel import ZeroConfig

        stage = int(winner["zero_stage"])
        optimizer.set_zero(ZeroConfig(stage=stage) if stage else None)
    if "precision" in winner:
        prec = winner["precision"]
        optimizer.set_precision(None if prec == "f32" else prec)
    return optimizer
