"""Static candidate pruning: price configs before anything runs.

Two gates, both execution-free, both auditable:

1. **Footprint gate** — a per-candidate HBM lower bound from
   ``jax.eval_shape`` alone (abstract trees, zero ``backend_compile``
   calls, zero device transfers) priced through
   :func:`bigdl_tpu.analysis.hlo.hbm_fit`. The bound counts what the
   program must pin no matter how XLA schedules it — resident state,
   the batch window, a gradient-sized temp — so anything it rejects is
   truly infeasible. Candidates pruned here are NEVER compiled (the
   test suite asserts this with a ``backend_compile`` counter).
2. **Contract gate** — survivors are lowered + AOT-compiled (still
   zero executions, the ``analysis/programs`` dry-run regime) into a
   :class:`~bigdl_tpu.analysis.hlo.ProgramSpec`; the compiled
   ``memory_analysis`` re-prices HBM exactly via :func:`hbm_fit` and
   the ``check --programs`` contract checks run over the spec —
   contract violators and exact-footprint overflows are dropped with
   the finding text as the reason.

Every dropped candidate lands in :attr:`PruneReport.pruned` with its
stage and reason — the sweep never silently caps anything.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.autotune.space import Candidate

__all__ = ["PrunedCandidate", "PruneReport", "static_prune",
           "train_footprint", "serving_footprint"]


@dataclass(frozen=True)
class PrunedCandidate:
    """One rejected candidate: which gate dropped it and why."""

    candidate: Candidate
    stage: str  # "hbm" | "contract"
    reason: str

    def to_dict(self) -> Dict[str, object]:
        """JSON-ready form (the pruned-candidate log line)."""
        return {"candidate": self.candidate.to_dict(),
                "stage": self.stage, "reason": self.reason}


@dataclass
class PruneReport:
    """The pruner's full verdict: survivors, the pruned list with
    reasons, and the budget everything was priced against."""

    kept: List[Candidate] = field(default_factory=list)
    pruned: List[PrunedCandidate] = field(default_factory=list)
    budget_bytes: Optional[int] = None

    def to_dict(self) -> Dict[str, object]:
        """JSON-ready summary."""
        return {"kept": [c.cid for c in self.kept],
                "pruned": [p.to_dict() for p in self.pruned],
                "budget_bytes": self.budget_bytes}


def _tree_bytes(tree) -> int:
    import jax

    return int(sum(
        int(np.prod(leaf.shape or (1,))) * np.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(tree)))


def _tiny_train_model(name: str):
    """The tuner's tiny model twins, INITIALIZED — same builders the
    static HLO verifier enumerates (``analysis/programs``), so a
    candidate priced here prices the program family the real workload
    scales up. Contract-gate only: initialization executes, so the
    footprint gate uses :func:`_uninit_train_model` instead."""
    from bigdl_tpu.analysis.programs import _mlp, _tiny_lm

    if name == "transformer_lm":
        return _tiny_lm()
    return _mlp()


def _uninit_train_model(name: str):
    """The same twins UNCONSTRUCTED-state: module graph only, no
    ``ensure_initialized`` — pure Python, so the footprint gate stays
    at zero ``backend_compile`` calls (real init compiles the param
    samplers)."""
    if name == "transformer_lm":
        from bigdl_tpu.models import TransformerLM

        return TransformerLM(vocab_size=64, hidden_size=32,
                             num_layers=1, num_heads=4,
                             max_len=16).training()
    import bigdl_tpu.nn as nn

    return nn.Sequential().add(nn.Linear(16, 32)).add(nn.Tanh()) \
        .add(nn.Linear(32, 4)).add(nn.LogSoftMax()).training()


def _abstract_train_state(model, optim, policy):
    """(params, opt_state, mstate) as abstract trees from an
    UNINITIALIZED model — ``analysis/shapecheck``'s device-free idiom:
    ``model.init`` traced under ``jax.eval_shape`` with an abstract
    PRNG key, optimizer/policy state seeded the way
    ``analysis/programs._train_abstract`` does."""
    import jax
    import jax.numpy as jnp

    key_spec = jax.eval_shape(jax.random.PRNGKey,
                              jax.ShapeDtypeStruct((), jnp.uint32))
    params = jax.eval_shape(model.init, key_spec)
    mstate = jax.eval_shape(model.initial_state)

    def seed_state(p):
        opt = optim.init_state(p)
        if policy is not None:
            from bigdl_tpu.precision import (MASTER_KEY, SCALER_KEY,
                                             DynamicLossScaler)
            if policy.needs_master:
                opt[MASTER_KEY] = policy.cast_to_accum(p)
            if policy.needs_loss_scaling:
                opt[SCALER_KEY] = DynamicLossScaler().init_state()
        return opt

    opt_state = jax.eval_shape(seed_state, params)
    if policy is not None and policy.needs_master:
        params = jax.eval_shape(policy.cast_to_param, params)
    return params, opt_state, mstate


def _train_batch_sds(model_name: str, batch: int):
    import jax

    if model_name == "transformer_lm":
        x = jax.ShapeDtypeStruct((batch, 16), np.dtype(np.int32))
        y = jax.ShapeDtypeStruct((batch, 16), np.dtype(np.int32))
    else:
        x = jax.ShapeDtypeStruct((batch, 16), np.dtype(np.float32))
        y = jax.ShapeDtypeStruct((batch,), np.dtype(np.float32))
    return x, y


def _criterion_for(model_name: str):
    import bigdl_tpu.nn as nn

    if model_name == "transformer_lm":
        return nn.SequenceCrossEntropyCriterion()
    return nn.ClassNLLCriterion()


def _policy_for(cand: Candidate):
    from bigdl_tpu.precision import PrecisionPolicy

    name = cand.config["precision"]
    return None if name == "f32" else PrecisionPolicy.named(name)


def train_footprint(cand: Candidate, model_name: str,
                    ndev: int) -> Dict[str, float]:
    """Static per-device HBM lower bound for one train candidate, via
    ``jax.eval_shape`` only (zero compiles, zero executions): resident
    params + optimizer state + model state (ZeRO stage >= 1 shards the
    optimizer state over ``ndev``, stage 3 the params too), the K-step
    batch window, and a gradient-sized temp — the dict
    :func:`~bigdl_tpu.analysis.hlo.hbm_fit` prices."""
    from bigdl_tpu.optim import SGD

    cfg = cand.config
    model = _uninit_train_model(model_name)
    optim = SGD(learning_rate=0.1, momentum=0.9)
    params, opt_state, mstate = _abstract_train_state(
        model, optim, _policy_for(cand))
    k = int(cfg["steps_per_sync"])
    x, y = _train_batch_sds(model_name, int(cfg["batch_size"]))
    param_bytes = _tree_bytes(params)
    opt_bytes = _tree_bytes(opt_state)
    stage = int(cfg["zero_stage"])
    if stage >= 1:
        opt_bytes = opt_bytes // max(ndev, 1)
    if stage >= 3:
        param_bytes = param_bytes // max(ndev, 1)
    batch_bytes = (_tree_bytes(x) + _tree_bytes(y)) * k
    act_bytes = 0.0
    if model_name == "transformer_lm":
        # attention-activation lower bound — the term sequence
        # parallelism shards: the backward keeps the per-layer f32
        # q/k/v/out [B, S, E] tensors live, and under a degree-d SP
        # policy each chip holds S/d of them (that division is exactly
        # why an over-budget dense candidate can become feasible)
        b, s = (int(dim) for dim in x.shape)
        hidden = int(getattr(model, "hidden_size", 32))
        layers = max(int(getattr(model, "num_layers", 1)), 1)
        act_bytes = float(4 * b * s * hidden * 4 * layers)
        sp = int(cfg.get("seq_parallel", 0) or 0)
        if sp > 1:
            act_bytes /= sp
    return {"arg_bytes": float(param_bytes + opt_bytes
                               + _tree_bytes(mstate) + batch_bytes),
            # outputs alias the donated carry in every real step/window
            # program — counting them again would over-price donation
            "out_bytes": 0.0,
            # the backward pass materializes at least one gradient tree
            # plus the (possibly seq-sharded) attention activations
            "temp_bytes": float(param_bytes) + act_bytes}


def serving_footprint(cand: Candidate) -> Dict[str, float]:
    """Static HBM lower bound for one serving candidate: model params
    + the KV cache the slot/ladder geometry implies
    (:meth:`KVCache.spec_for_model` — ShapeDtypeStructs, nothing
    touches a device) + the candidate's prefix-cache budget."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.generation.kv_cache import KVCache
    from bigdl_tpu.models import TransformerLM

    cfg = cand.config
    max_len = int(cfg["length_buckets"][-1])
    # the measure harness's own tiny twin, positional table sized to
    # the candidate's ladder top (the cache time axis) — uninitialized:
    # the cache spec and the abstract param tree need shapes only
    model = TransformerLM(vocab_size=64, hidden_size=32, num_layers=1,
                          num_heads=4, max_len=max_len).evaluate()
    key_spec = jax.eval_shape(jax.random.PRNGKey,
                              jax.ShapeDtypeStruct((), jnp.uint32))
    params = jax.eval_shape(model.init, key_spec)
    k_sds, v_sds = KVCache.spec_for_model(model, int(cfg["slots"]),
                                          max_len)
    return {"arg_bytes": float(_tree_bytes(params)
                               + _tree_bytes([k_sds, v_sds])),
            "out_bytes": 0.0,
            "temp_bytes": float(cfg["prefix_cache_bytes"])}


def _train_spec(cand: Candidate, model_name: str, budget: Optional[int]):
    """Lower + AOT-compile one train candidate's program (zero
    executions) into the ProgramSpec the contract checks consume —
    the K>1 case through ``make_host_window`` exactly like the real
    windowed driver."""
    import jax

    from bigdl_tpu.analysis.programs import (_key_struct,
                                             _train_abstract,
                                             spec_from_lowered)
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import (build_train_step,
                                           make_host_window)

    cfg = cand.config
    model = _tiny_train_model(model_name)
    optim = SGD(learning_rate=0.1, momentum=0.9)
    policy = _policy_for(cand)
    params, opt_state, mstate = _train_abstract(model, optim, policy)
    seq_cfg = None
    sp = int(cfg.get("seq_parallel", 0) or 0)
    if sp > 1:
        from bigdl_tpu.parallel import SeqParallelConfig, make_mesh
        seq_cfg = SeqParallelConfig(
            axis="seq", mesh=make_mesh([sp], ["seq"],
                                       jax.devices()[:sp]))
    step = build_train_step(model, _criterion_for(model_name), optim,
                            precision=policy, seq_parallel=seq_cfg)
    k = int(cfg["steps_per_sync"])
    x, y = _train_batch_sds(model_name, int(cfg["batch_size"]))
    key = _key_struct()
    lr = jax.ShapeDtypeStruct((), np.dtype(np.float32))
    if k > 1:
        window = make_host_window(step)
        keys = jax.ShapeDtypeStruct((k,) + key.shape, key.dtype)
        lrs = jax.ShapeDtypeStruct((k,), np.dtype(np.float32))
        xs = jax.ShapeDtypeStruct((k,) + x.shape, x.dtype)
        ys = jax.ShapeDtypeStruct((k,) + y.shape, y.dtype)
        lowered = window.lower(params, opt_state, mstate, keys, lrs,
                               xs, ys)
    else:
        lowered = step.lower(params, opt_state, mstate, key, lr, x, y)
    pol = cfg["precision"]
    return spec_from_lowered(
        f"autotune/{cand.cid}", lowered,
        window=k > 1, scan_length=k,
        policy=None if pol == "f32" else pol,
        hbm_budget=budget, extra={"kind": "autotune"})


def _contract_gate(cand: Candidate, model_name: str,
                   budget: Optional[int],
                   checks: Optional[Sequence[str]]
                   ) -> Optional[PrunedCandidate]:
    """Lower/compile the candidate and run the static contract checks
    + the exact compiled-footprint ``hbm_fit``; a verdict of None
    keeps the candidate."""
    from bigdl_tpu.analysis.hlo import hbm_fit, run_checks

    from bigdl_tpu import kernels

    try:
        if cand.regime == "train":
            if cand.config.get("flash"):
                kcfg = kernels.KernelConfig.all_on(
                    long_context=bool(
                        cand.config.get("long_context", False)))
            else:
                kcfg = kernels.KernelConfig.off()
            with kernels.use(kcfg):
                spec = _train_spec(cand, model_name, budget)
        else:
            return None  # serving contracts are covered by the
            # verifier's own generation legs; the engine compiles the
            # identical programs at measure time
    except Exception as e:
        return PrunedCandidate(cand, "contract",
                               f"lowering failed: {type(e).__name__}: "
                               f"{e}")
    if spec.memory is not None:
        fit = hbm_fit(spec.memory, budget)
        if not fit["fits"]:
            return PrunedCandidate(
                cand, "contract",
                f"compiled footprint {fit['total_bytes']} bytes over "
                f"budget {budget} ({fit['breakdown']})")
    findings = [f for f in run_checks([spec], checks)
                if not f.suppressed and f.severity == "error"]
    if findings:
        return PrunedCandidate(
            cand, "contract",
            "; ".join(f"{f.check}: {f.message}" for f in findings))
    return None


def static_prune(candidates: Sequence[Candidate], *,
                 hbm_budget: Optional[int] = None,
                 model: Optional[str] = None,
                 ndev: Optional[int] = None,
                 contract_checks: bool = True,
                 checks: Optional[Sequence[str]] = None) -> PruneReport:
    """Run both static gates over ``candidates`` (see module doc).

    ``hbm_budget`` defaults to ``analysis.programs.default_hbm_budget``
    (``BIGDL_HBM_BUDGET_GB``); ``model`` names the train-regime tiny
    twin (default: the space's natural twin, ``mlp`` unless a
    candidate asks for flash); ``contract_checks=False`` skips the
    lowering gate entirely — the footprint gate alone performs ZERO
    ``backend_compile`` calls, which is what the zero-compile test
    asserts. Returns a :class:`PruneReport`; every rejected candidate
    carries its stage and reason."""
    from bigdl_tpu.analysis.hlo import hbm_fit
    from bigdl_tpu.analysis.programs import default_hbm_budget

    budget = default_hbm_budget() if hbm_budget is None else hbm_budget
    if ndev is None:
        import jax
        ndev = len(jax.devices())
    report = PruneReport(budget_bytes=budget)
    for cand in candidates:
        mname = model or str(cand.config.get("model", "mlp"))
        if cand.regime == "train":
            footprint = train_footprint(cand, mname, ndev)
        else:
            footprint = serving_footprint(cand)
        fit = hbm_fit(footprint, budget)
        if not fit["fits"]:
            report.pruned.append(PrunedCandidate(
                cand, "hbm",
                f"static footprint {fit['total_bytes']} bytes over "
                f"budget {budget} ({fit['breakdown']})"))
            continue
        if contract_checks:
            verdict = _contract_gate(cand, mname, budget, checks)
            if verdict is not None:
                report.pruned.append(verdict)
                continue
        report.kept.append(cand)
    return report
