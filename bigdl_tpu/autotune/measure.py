"""Timed measurement of pruned-surviving candidates.

Each survivor gets a short **seeded** timed window that reuses the
package's existing measurement plumbing — the ``tools/perf`` train-step
/ fused-window programs for the train regime, a real
:class:`~bigdl_tpu.generation.service.GenerationService` burst for
serving — and the objective is read BACK from the telemetry layer's own
instruments, never re-derived on the side:

- train: the program profile registered in
  ``telemetry.programs.registry()`` (``record_rate`` →
  ``prof.rate_items_per_s``, steps/sec — the same number the
  ``train/program/*`` gauges publish);
- serving: the ``serving/generation/tokens`` counter delta over the
  window, from the service's own metrics registry.

One crashing candidate cannot kill the sweep: every window runs under
:func:`faults.retry.classify` isolation — transients get one in-place
retry (``faults.retry.retry_call``), fatals and exhausted retries
become an ``ok=False`` :class:`MeasureResult` carrying the error, and
the sweep moves on. A soft per-candidate ``timeout_s`` marks
over-budget windows failed instead of trusting their numbers.

Tests and bench inject a deterministic ``runner`` — measurement noise
lives HERE, never in the leaderboard/artifact layer above.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.autotune.space import Candidate

__all__ = ["MeasureResult", "measure_candidates", "default_runner"]

#: objective names per regime (higher is better, both)
OBJECTIVES = {"train": "train_steps_per_sec",
              "serving": "decode_tokens_per_sec"}


@dataclass
class MeasureResult:
    """One candidate's measured window (or its isolated failure)."""

    candidate: Candidate
    ok: bool
    objective: float = 0.0
    objective_name: str = ""
    elapsed_s: float = 0.0
    error: str = ""
    error_kind: str = ""  # "fatal" | "transient" | "timeout" | ""

    def to_dict(self) -> Dict[str, object]:
        """JSON-ready leaderboard entry. Wall-clock ``elapsed_s`` stays
        OFF the artifact: tuned.json is canonical (same seed + same
        runner => same bytes), and a timestamp would break that."""
        d = self.candidate.to_dict()
        d.update(ok=self.ok, objective=self.objective,
                 objective_name=self.objective_name)
        if not self.ok:
            d.update(error=self.error, error_kind=self.error_kind)
        return d


def _run_train(cand: Candidate, seed: int, iters: int) -> float:
    """One seeded train window: the tiny model twin's real
    ``build_train_step`` program (fused through ``make_host_window``
    when K > 1, i.e. the very artifact ``set_steps_per_sync``
    dispatches), AOT-compiled, warmed once, timed over ``iters``
    dispatches — under the candidate's kernel config, so the ``flash``
    axis measures the pallas path against the reference. Registers
    ``autotune/<cid>`` in the program registry and returns the
    steps/sec the registry read back."""
    from bigdl_tpu import kernels

    if cand.config.get("flash"):
        kcfg = kernels.KernelConfig.all_on(
            long_context=bool(cand.config.get("long_context", False)))
    else:
        kcfg = kernels.KernelConfig.off()
    with kernels.use(kcfg):
        return _train_window(cand, seed, iters)


def _train_window(cand: Candidate, seed: int, iters: int) -> float:
    import jax

    import bigdl_tpu.nn as nn
    from bigdl_tpu.analysis.programs import _mlp, _tiny_lm
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import (build_train_step,
                                           make_host_window)
    from bigdl_tpu.telemetry import programs as tprog
    from bigdl_tpu.utils.random import RandomGenerator

    cfg = cand.config
    k = int(cfg["steps_per_sync"])
    batch = int(cfg["batch_size"])
    use_lm = cfg.get("model") == "transformer_lm"
    RandomGenerator.set_seed(seed)
    model = _tiny_lm() if use_lm else _mlp()
    criterion = (nn.SequenceCrossEntropyCriterion() if use_lm
                 else nn.ClassNLLCriterion())
    optim = SGD(learning_rate=0.1, momentum=0.9)
    policy = None
    if cfg["precision"] != "f32":
        from bigdl_tpu.precision import PrecisionPolicy
        policy = PrecisionPolicy.named(cfg["precision"])

    params = model.get_parameters()
    opt_state = optim.init_state(params)
    mstate = model.get_state()
    if policy is not None:
        # seed the policy's opt-state keys the way
        # Optimizer.set_precision does (master copy, scaler state)
        from bigdl_tpu.precision import (MASTER_KEY, SCALER_KEY,
                                         DynamicLossScaler)
        if policy.needs_master:
            opt_state[MASTER_KEY] = params
            params = policy.cast_to_param(params)
        if policy.needs_loss_scaling:
            opt_state[SCALER_KEY] = DynamicLossScaler().init_state()

    zero_cfg = zero_mesh = seq_cfg = None
    if int(cfg["zero_stage"]) > 0:
        from bigdl_tpu.parallel import ZeroConfig, data_parallel_mesh
        zero_mesh = data_parallel_mesh()
        zero_cfg = ZeroConfig(stage=int(cfg["zero_stage"]))
    sp = int(cfg.get("seq_parallel", 0) or 0)
    if sp > 1:
        # exclusive with zero_stage>0 here (coded space constraint):
        # the harness builds ONE 1-D mesh per candidate
        from bigdl_tpu.parallel import SeqParallelConfig, make_mesh
        zero_mesh = make_mesh([sp], ["seq"], jax.devices()[:sp])
        seq_cfg = SeqParallelConfig(axis="seq", mesh=zero_mesh)
    step = build_train_step(model, criterion, optim, zero=zero_cfg,
                            mesh=zero_mesh, precision=policy,
                            seq_parallel=seq_cfg)

    rng = np.random.default_rng(seed)
    if use_lm:
        x = rng.integers(1, 63, (batch, 16)).astype(np.int32)
        y = rng.integers(1, 63, (batch, 16)).astype(np.int32)
    else:
        x = rng.standard_normal((batch, 16)).astype(np.float32)
        y = rng.integers(1, 5, (batch,)).astype(np.float32)
    x, y = jax.numpy.asarray(x), jax.numpy.asarray(y)
    key = jax.random.PRNGKey(seed)

    name = f"autotune/{cand.cid}"
    reg = tprog.registry()
    t0 = time.perf_counter()
    if k > 1:
        window = make_host_window(step)
        keys = jax.random.split(key, k)
        lrs = jax.numpy.full((k,), 0.01, np.float32)
        xs = jax.numpy.broadcast_to(x, (k,) + x.shape)
        ys = jax.numpy.broadcast_to(y, (k,) + y.shape)
        compiled = window.lower(params, opt_state, mstate, keys, lrs,
                                xs, ys).compile()
        compile_s = time.perf_counter() - t0
        reg.register(name, "train", compiled=compiled,
                     compile_s=compile_s, scan_length=k,
                     items_per_call=k)
        carry = (params, opt_state, mstate)
        out = compiled(*carry, keys, lrs, xs, ys)  # warm
        jax.block_until_ready(out[0])
        carry = out[:3]
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compiled(*carry, keys, lrs, xs, ys)
            carry = out[:3]
        jax.block_until_ready(out[0])
    else:
        compiled = step.lower(params, opt_state, mstate, key, 0.01,
                              x, y).compile()
        compile_s = time.perf_counter() - t0
        reg.register(name, "train", compiled=compiled,
                     compile_s=compile_s, items_per_call=1)
        out = compiled(params, opt_state, mstate, key, 0.01, x, y)
        jax.block_until_ready(out[0])  # warm
        p, o, m = out[:3]
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compiled(p, o, m, key, 0.01, x, y)
            p, o, m = out[:3]
        jax.block_until_ready(out[0])
    dt = max(time.perf_counter() - t0, 1e-9)
    steps_per_s = k * iters / dt
    reg.record_rate(name, steps_per_s)
    prof = reg.get(name)
    # the registry's own number; rate_items_per_s is only populated
    # when the backend exposed a flop count, so fall back to the rate
    # we just recorded rather than reporting a fake zero
    return float(prof.rate_items_per_s or steps_per_s) if prof \
        else steps_per_s


def _run_serving(cand: Candidate, seed: int, iters: int) -> float:
    """One seeded serving burst through a real GenerationService built
    from the candidate's geometry; the objective is the service's own
    ``serving/generation/tokens`` counter delta over the window."""
    from bigdl_tpu.generation import (GenerationConfig,
                                      GenerationService)
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.utils.random import RandomGenerator

    cfg = cand.config
    if int(cfg["speculation_k"]) > 0:
        raise NotImplementedError(
            "speculation_k > 0 needs a draft model the default runner "
            "does not build — pass a custom runner= to measure it")
    ladder = tuple(int(b) for b in cfg["length_buckets"])
    max_len = ladder[-1]
    slots = int(cfg["slots"])
    RandomGenerator.set_seed(seed)
    model = TransformerLM(vocab_size=64, hidden_size=32, num_layers=1,
                          num_heads=4, max_len=max_len).evaluate()
    model.ensure_initialized()
    chunk = int(cfg.get("prefill_chunk", 0) or 0)
    svc = GenerationService(config=GenerationConfig(
        slots=slots, max_len=max_len, length_buckets=ladder,
        prefill_rows=min(2, slots), max_queue=256,
        prefix_cache_bytes=int(cfg["prefix_cache_bytes"]),
        prefill_chunk=chunk if chunk > 0 else None))
    try:
        svc.load("atn", model)  # warmup compiles outside the timing
        rng = np.random.default_rng(seed)
        max_new = max(4, min(8, max_len // 4))
        n_reqs = max(2 * slots, iters)
        prompts = [rng.integers(1, 63, int(rng.integers(
            2, max(3, max_len - max_new)))).astype(np.int32)
            for _ in range(n_reqs)]
        before = svc.metrics("atn")["tokens"]
        t0 = time.perf_counter()
        streams = [svc.generate("atn", p, max_new_tokens=max_new)
                   for p in prompts]
        for s in streams:
            s.result()
        dt = max(time.perf_counter() - t0, 1e-9)
        produced = svc.metrics("atn")["tokens"] - before
        return produced / dt
    finally:
        svc.shutdown()


def default_runner(cand: Candidate, seed: int, iters: int) -> float:
    """The real timed window for one candidate (dispatch by regime);
    returns the objective value read from the telemetry layer."""
    if cand.regime == "train":
        return _run_train(cand, seed, iters)
    return _run_serving(cand, seed, iters)


def measure_candidates(candidates: Sequence[Candidate], *,
                       seed: int = 0, iters: int = 3,
                       timeout_s: Optional[float] = None,
                       runner: Optional[Callable[[Candidate, int, int],
                                                 float]] = None
                       ) -> List[MeasureResult]:
    """Measure every candidate under failure isolation (module doc).

    ``runner(candidate, seed, iters) -> objective`` defaults to
    :func:`default_runner`; inject a deterministic one in tests/bench.
    Always returns one :class:`MeasureResult` per candidate, in input
    order — failures are recorded, never raised."""
    from bigdl_tpu.faults.retry import classify, retry_call

    run = runner or default_runner
    results: List[MeasureResult] = []
    for cand in candidates:
        t0 = time.perf_counter()
        try:
            value = retry_call(run, cand, seed, iters, attempts=2,
                               base_delay_s=0.0,
                               describe=f"autotune {cand.cid}",
                               sleep=lambda _s: None)
        except Exception as e:
            results.append(MeasureResult(
                cand, ok=False, objective_name=OBJECTIVES[cand.regime],
                elapsed_s=time.perf_counter() - t0,
                error=f"{type(e).__name__}: {e}",
                error_kind=classify(e)))
            continue
        elapsed = time.perf_counter() - t0
        if timeout_s is not None and elapsed > timeout_s:
            results.append(MeasureResult(
                cand, ok=False, objective_name=OBJECTIVES[cand.regime],
                elapsed_s=elapsed,
                error=f"window took {elapsed:.2f}s > soft timeout "
                      f"{timeout_s:.2f}s — number untrusted",
                error_kind="timeout"))
            continue
        results.append(MeasureResult(
            # once per CANDIDATE (the runner already synced its timed
            # window); this is bookkeeping, not a per-step fetch
            cand, ok=True, objective=float(value),  # bigdl: disable=sync-in-loop
            objective_name=OBJECTIVES[cand.regime], elapsed_s=elapsed))
    return results
