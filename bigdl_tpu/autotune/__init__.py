"""Profile-guided configuration autotuner: static prune → timed
measure → tuned-config artifact.

The package measured everything (``telemetry``), can price programs
without running them (``analysis/hlo``'s cost + ``hbm_fit``), and
every performance knob — ``steps_per_sync`` K, ZeRO stage, precision
preset, the flash toggle, length buckets, slots, speculation depth,
prefix-cache bytes — was still hand-picked. This subsystem closes the
loop from gauges back to configuration:

1. :mod:`~bigdl_tpu.autotune.space` — typed, bounded search spaces
   with validity constraints in code;
2. :mod:`~bigdl_tpu.autotune.prune` — static HBM/contract pruning with
   ZERO executions (footprint-gate rejections never even compile);
3. :mod:`~bigdl_tpu.autotune.measure` — short seeded timed windows
   with per-candidate failure isolation, objectives read from the
   telemetry registry's own instruments;
4. :mod:`~bigdl_tpu.autotune.config` — the versioned, fingerprinted
   ``tuned.json`` artifact that ``tools/perf --config``, bench's TUNED
   row and the serving facade consume.

CLI: ``python -m bigdl_tpu.tools.autotune`` (``docs/autotune.md``).
"""
from bigdl_tpu import telemetry as _telemetry

#: sweep instruments (audited by ``tools.check --telemetry-audit``)
CANDIDATES_TOTAL = _telemetry.counter(
    "autotune/sweep/candidates_total",
    "candidates enumerated from the search space (valid + invalid)")
PRUNED_STATIC = _telemetry.counter(
    "autotune/sweep/pruned_static",
    "candidates rejected before any execution (invalid combination, "
    "static HBM footprint, compiled-program contract)")
MEASURED = _telemetry.counter(
    "autotune/sweep/measured",
    "candidates that got a timed measurement window")
BEST_OBJECTIVE = _telemetry.gauge(
    "autotune/sweep/best_objective",
    "winning objective value per regime (labels: regime, objective)")

from bigdl_tpu.autotune.config import (FingerprintMismatchError,  # noqa: E402
                                       Fingerprint, TunedConfig,
                                       TunedConfigError,
                                       apply_to_perf_args,
                                       apply_tuned_optimizer,
                                       load_tuned, save_tuned)
from bigdl_tpu.autotune.measure import (MeasureResult,  # noqa: E402
                                        measure_candidates)
from bigdl_tpu.autotune.prune import (PruneReport,  # noqa: E402
                                      PrunedCandidate, static_prune)
from bigdl_tpu.autotune.space import (Candidate, ServingSpace,  # noqa: E402
                                      SpaceError, TrainSpace,
                                      enumerate_candidates)

__all__ = [
    "CANDIDATES_TOTAL", "PRUNED_STATIC", "MEASURED", "BEST_OBJECTIVE",
    "SpaceError", "Candidate", "TrainSpace", "ServingSpace",
    "enumerate_candidates", "PrunedCandidate", "PruneReport",
    "static_prune", "MeasureResult", "measure_candidates",
    "TunedConfigError", "FingerprintMismatchError", "Fingerprint",
    "TunedConfig", "save_tuned", "load_tuned", "apply_to_perf_args",
    "apply_tuned_optimizer",
]
