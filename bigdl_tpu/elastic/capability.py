"""Environment capability probes — ONE auditable reason per exclusion.

Two long-standing tier-1 exclusions are environmental, not bugs: some
jax builds lack ``jax.shard_map`` (the sequence/pipeline-parallel
surface), and some CPU runtimes rendezvous fine but cannot EXECUTE
cross-process collectives ("Multiprocess computations aren't
implemented on the CPU backend"). Tests and the chaos host-kill leg
used to discover these by crashing; these probes discover them ONCE,
cache the verdict for the process, and hand back a precise reason
string — so a skip reads "env: <exact missing capability>" instead of
a stack trace, and a runtime that DOES support the surface runs the
real tests with no code change.
"""
from __future__ import annotations

import functools
import os
import subprocess
import sys
from typing import Tuple

#: the two-process collective probe: rendezvous + ONE jitted
#: cross-process reduction. Prints PROBE_OK only if the computation
#: actually executed — rendezvous alone is not the capability.
_PROBE_SRC = """\
import sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=sys.argv[1],
                           num_processes=2, process_id=int(sys.argv[2]))
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
import numpy as np
mesh = Mesh(np.array(jax.devices()), ("d",))
sh = NamedSharding(mesh, P("d"))
x = jax.make_array_from_process_local_data(
    sh, jnp.ones((1,), jnp.float32), (2,))
y = jax.jit(lambda a: a.sum(),
            out_shardings=NamedSharding(mesh, P()))(x)
v = float(jax.device_get(y.addressable_shards[0].data))
assert v == 2.0, v
print("PROBE_OK")
"""


def shard_map_available() -> bool:
    """Whether this jax exposes ``jax.shard_map`` (the spelling the
    ring/Ulysses/pipeline parallel layers compile through)."""
    import jax
    return hasattr(jax, "shard_map")


def shard_map_reason() -> str:
    """The precise skip reason when :func:`shard_map_available` is
    False."""
    import jax
    return (f"env: jax {jax.__version__} has no jax.shard_map "
            "(sequence/pipeline parallelism needs it)")


@functools.lru_cache(maxsize=None)
def multiprocess_cpu(timeout_s: float = 120.0) -> Tuple[bool, str]:
    """Probe (once per process) whether this runtime can EXECUTE
    cross-process collectives on the CPU backend: spawn a two-process
    gang, rendezvous, run one jitted cross-process reduction. Returns
    ``(ok, reason)`` — the reason is the auditable skip string when
    not ok. Override with ``BIGDL_ASSUME_MULTIPROCESS_CPU=1|0`` (CI
    images that already know their runtime skip the ~10s probe)."""
    forced = os.environ.get("BIGDL_ASSUME_MULTIPROCESS_CPU")
    if forced == "1":
        return True, "forced by BIGDL_ASSUME_MULTIPROCESS_CPU=1"
    if forced == "0":
        return False, ("env: multiprocess CPU collectives disabled by "
                       "BIGDL_ASSUME_MULTIPROCESS_CPU=0")
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coord = f"127.0.0.1:{port}"
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # one device per probe process
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PROBE_SRC, coord, str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout_s)
            outs.append(out or "")
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
            p.communicate()
        return False, ("env: multiprocess CPU probe timed out "
                       f"after {timeout_s:.0f}s (rendezvous or "
                       "collective never completed)")
    if all(p.returncode == 0 for p in procs) \
            and all("PROBE_OK" in o for o in outs):
        return True, "multiprocess CPU collectives available"
    tail = next((o for p, o in zip(procs, outs) if p.returncode != 0),
                outs[0] if outs else "")
    lines = [ln for ln in tail.strip().splitlines() if ln.strip()]
    detail = lines[-1][-160:] if lines else "no output"
    return False, ("env: CPU backend cannot execute cross-process "
                   f"collectives ({detail})")


__all__ = ["multiprocess_cpu", "shard_map_available", "shard_map_reason"]
