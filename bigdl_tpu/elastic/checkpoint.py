"""Async per-shard elastic checkpointing (format 3).

The legacy checkpoint path (``utils.serialization.save_checkpoint``)
all-gathers every sharded leaf to a full host copy and writes once from
process 0 — correct, but the gather is a collective on the critical
path and the write stalls the step loop for the whole serialization.
On a preemptible pod that stall is paid at exactly the moment you want
checkpoints *most frequent*. This module inverts both costs, the way
the weight-update-sharding paper treats layout metadata as the portable
contract (arXiv:2004.13336):

- **per-shard**: every process snapshots only the leaf shards it
  actually holds (``Shard.replica_id == 0`` dedupes replicated copies),
  so no gather collective runs and bytes written scale 1/n with the
  process count;
- **async**: the device->host copy is the ONLY work on the step loop
  (the ``train/checkpoint/save_s`` stall shrinks to the snapshot);
  serialization, hashing and fsync run on a background writer thread
  whose hidden tail lands in ``train/checkpoint/async_write_s``;
- **two-phase barriered commit**: each process writes its part files
  plus a ``PART-<k>.json`` naming their sha256s (each process hashes
  exactly the bytes it ships), and process 0 — after *every* part has
  landed — fsyncs a format-3 ``MANIFEST.json`` recording the merged
  digests AND the sharding metadata (mesh shape, axis names, per-leaf
  PartitionSpec, ZeRO stage, precision policy, per-process datapipe
  cursors), then atomically renames the staging dir into place. Until
  the MANIFEST lands, the checkpoint does not exist:
  ``find_latest_checkpoint`` never selects it, and a torn commit
  (PART files, no MANIFEST) is quarantinable via
  ``verify_checkpoint``.

The sharding metadata is what makes the checkpoint *elastic*:
``elastic.resume`` reassembles the global arrays from the parts using
the recorded specs and re-shards them onto whatever mesh / ZeRO stage /
process count the relaunched job runs — see ``elastic.load_for_mesh``.
"""
from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu import faults
from bigdl_tpu.utils import file_io
from bigdl_tpu.utils.serialization import (MANIFEST, _fsync, _fsync_dir,
                                           _tree_to_template, _write_json)

logger = logging.getLogger("bigdl_tpu")

#: trees a training checkpoint carries (mirrors the format-2 layout)
TREES = ("params", "opt_state", "model_state")

#: per-process part-manifest filename pattern (the phase-1 artifact)
PART_RE = re.compile(r"^PART-(\d+)\.json$")

_ASYNC_WRITE_S = telemetry.histogram(
    "train/checkpoint/async_write_s",
    "background-writer seconds per async checkpoint commit (the tail "
    "hidden off the step loop; the residual train/checkpoint/save_s "
    "stall is the device->host snapshot copy alone)")
_PRUNED = telemetry.counter(
    "train/checkpoint/pruned",
    "committed checkpoints deleted by keep_last retention")


def run_metadata(mesh=None, data_axis: str = "data", zero=None,
                 precision=None,
                 process_count: Optional[int] = None) -> Dict[str, Any]:
    """The run-level half of the format-3 sharding metadata: mesh
    shape/axes, ZeRO stage, precision policy and process count of the
    run that WROTE the checkpoint (the per-leaf specs are captured from
    the live arrays at snapshot time)."""
    return {
        "mesh_shape": {str(a): int(s)
                       for a, s in mesh.shape.items()} if mesh is not None
        else None,
        "axis_names": [str(a) for a in mesh.axis_names]
        if mesh is not None else [],
        "data_axis": data_axis,
        "zero_stage": int(zero.stage) if zero is not None else 0,
        "precision": getattr(precision, "name", None),
        "process_count": int(process_count if process_count is not None
                             else jax.process_count()),
    }


# ------------------------------------------------------------ snapshot

def _flatten_device_leaves(tree, prefix: str = "") -> Dict[str, Any]:
    """Leaf-path -> leaf, in the SAME deterministic order and key
    convention as ``serialization._flatten_leaves`` — but keeping the
    device arrays (no host materialization, no gather)."""
    from bigdl_tpu.utils.table import Table
    out: Dict[str, Any] = {}
    if isinstance(tree, Table):
        for k, v in tree.items():
            out.update(_flatten_device_leaves(v, f"{prefix}{k}/"))
    elif isinstance(tree, dict):
        for k, v in sorted(tree.items()):
            out.update(_flatten_device_leaves(v, f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = tree
    return out


def _norm_index(index, shape) -> Tuple[Tuple[int, int], ...]:
    """A ``Shard.index`` as explicit ((start, stop), ...) per dim."""
    out = []
    for sl, dim in zip(index, shape):
        out.append((int(sl.start or 0),
                    int(dim if sl.stop is None else sl.stop)))
    return tuple(out)


def _slices_key(idx: Tuple[Tuple[int, int], ...]) -> str:
    """``((0,8),(0,4))`` -> ``"0:8,0:4"``; scalars -> ``"-"``."""
    if not idx:
        return "-"
    return ",".join(f"{a}:{b}" for a, b in idx)


def parse_slices_key(text: str, shape) -> Tuple[slice, ...]:
    """Inverse of the npz slice suffix (``elastic.resume`` fill),
    validated against the leaf's recorded global ``shape`` — a
    malformed or out-of-bounds block key is corrupt metadata, not
    something to apply blindly to a freshly allocated array."""
    from bigdl_tpu.utils.serialization import CheckpointCorrupt
    if text == "-":
        if tuple(shape):
            raise CheckpointCorrupt(
                f"scalar block key on a rank-{len(shape)} leaf")
        return ()
    parts = text.split(",")
    if len(parts) != len(shape):
        raise CheckpointCorrupt(
            f"block key {text!r} has {len(parts)} dims for a "
            f"shape-{tuple(shape)} leaf")
    out = []
    for p, dim in zip(parts, shape):
        a, _, b = p.partition(":")
        a, b = int(a), int(b)
        if not 0 <= a < b <= int(dim):
            raise CheckpointCorrupt(
                f"block key {text!r} out of bounds for shape "
                f"{tuple(shape)}")
        out.append(slice(a, b))
    return tuple(out)


class TreeSnapshot:
    """One tree's host snapshot of THIS process's shards.

    ``template`` — the JSON tree structure (``_rebuild``-compatible);
    ``leaf_meta`` — leaf-path -> {spec, shape, dtype} (the per-leaf
    sharding metadata the MANIFEST records);
    ``shards`` — ``"<leaf>|<slices>"`` -> host ndarray, exactly the
    blocks this process ships.
    """

    def __init__(self, template, leaf_meta: Dict[str, dict],
                 shards: Dict[str, np.ndarray]):
        self.template = template
        self.leaf_meta = leaf_meta
        self.shards = shards


def snapshot_tree(tree, process_index: int = 0) -> TreeSnapshot:
    """Copy this process's shard of every leaf to host memory.

    This is the ONLY step-loop work of an async checkpoint: all
    device->host copies are kicked off asynchronously first
    (``copy_to_host_async``), then materialized — so the stall is one
    overlapped D2H sweep, not a serial per-leaf fetch. Replicated
    copies are deduped by ``Shard.replica_id == 0`` (exactly one shard
    per distinct index block carries replica 0, globally), so each
    byte of the global state is written by exactly one process. Host
    (non-``jax.Array``) leaves are replicated by construction and ship
    from process 0 only.
    """
    from bigdl_tpu.parallel.zero import spec_to_entries
    leaves = _flatten_device_leaves(tree)
    pending: List[Tuple[str, Any]] = []
    meta: Dict[str, dict] = {}
    shards: Dict[str, np.ndarray] = {}
    for key, leaf in leaves.items():
        if isinstance(leaf, jax.Array):
            spec = getattr(leaf.sharding, "spec", None)
            meta[key] = {"spec": spec_to_entries(spec),
                         "shape": [int(d) for d in leaf.shape],
                         "dtype": str(np.dtype(leaf.dtype))}
            for sh in leaf.addressable_shards:
                if sh.replica_id != 0:
                    continue
                idx = _norm_index(sh.index, leaf.shape)
                data = sh.data
                try:
                    data.copy_to_host_async()
                except Exception:
                    pass  # backend without async D2H: asarray blocks
                pending.append((f"{key}|{_slices_key(idx)}", data))
        else:
            arr = np.asarray(leaf)
            meta[key] = {"spec": [],
                         "shape": [int(d) for d in arr.shape],
                         "dtype": str(arr.dtype)}
            if process_index == 0:
                idx = tuple((0, int(d)) for d in arr.shape)
                shards[f"{key}|{_slices_key(idx)}"] = arr
    for nk, data in pending:
        # the sanctioned snapshot point: every copy was started above,
        # so these asarray calls drain an already-in-flight D2H sweep
        shards[nk] = np.asarray(data)  # bigdl: disable=blocking-copy-in-checkpoint
    return TreeSnapshot(_tree_to_template(tree), meta, shards)


# ------------------------------------------------------ two-phase write

def _blob(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _write_file(path: str, data: bytes) -> str:
    with open(path, "wb") as f:
        f.write(data)
        _fsync(f)
    return _blob(data)


def _await_parts(staging: str, process_count: int,
                 timeout_s: float) -> Dict[int, dict]:
    """Phase-2 barrier: process 0 blocks until every process's
    ``PART-<k>.json`` has landed in the shared staging dir (the
    cross-process form of the reference driver waiting on every
    executor). Raises ``TimeoutError`` — the checkpoint then simply
    never commits; the invisible staging dir is the failure mode, not
    a torn checkpoint."""
    deadline = time.monotonic() + timeout_s
    want = set(range(process_count))
    parts: Dict[int, dict] = {}
    while True:
        for name in os.listdir(staging):
            m = PART_RE.match(name)
            if not m or int(m.group(1)) in parts:
                continue
            if int(m.group(1)) not in want:
                continue  # stale part from a dead larger-world gang
            try:
                with open(os.path.join(staging, name)) as f:
                    part = json.load(f)
            except (OSError, ValueError):
                continue  # mid-write: picked up on the next poll
            if part.get("process_count") != process_count:
                continue  # a previous incarnation's leftover
            parts[int(m.group(1))] = part
        if want <= set(parts):
            return parts
        if time.monotonic() > deadline:
            missing = sorted(want - set(parts))
            raise TimeoutError(
                f"elastic commit barrier: processes {missing} never "
                f"landed their checkpoint parts in {staging} within "
                f"{timeout_s:.0f}s")
        time.sleep(0.02)


def _commit_rename(staging: str, path: str) -> None:
    """Atomically publish the staged dir — the ONE shared commit dance
    (``serialization.publish_checkpoint_dir``), with the elastic
    staging prefix added to the superseded-debris sweep."""
    from bigdl_tpu.utils.serialization import publish_checkpoint_dir
    publish_checkpoint_dir(staging, path,
                           debris_prefixes=(".tmp-", ".old-",
                                            ".staging-"))


def _write_and_commit(staging: str, path: str,
                      snaps: Dict[str, TreeSnapshot], host: dict,
                      run_meta: Dict[str, Any], cursor,
                      process_index: int, process_count: int,
                      neval, keep_last: Optional[int],
                      commit_timeout_s: float) -> None:
    """The background (or inline) half: serialize + hash + fsync this
    process's parts, then — process 0 only — barrier on every part and
    commit the format-3 MANIFEST."""
    os.makedirs(staging, exist_ok=True)
    digests: Dict[str, str] = {}
    for name, snap in snaps.items():
        if not snap.shards:
            continue  # nothing owned locally (all replicas live elsewhere)
        buf = io.BytesIO()
        np.savez(buf, **snap.shards)
        fname = f"{name}.part{process_index}.npz"
        digests[fname] = _write_file(os.path.join(staging, fname),
                                     buf.getvalue())
    if process_index == 0:
        for name, snap in snaps.items():
            data = json.dumps(snap.template).encode()
            digests[f"{name}.json"] = _write_file(
                os.path.join(staging, f"{name}.json"), data)
        digests["host_state.json"] = _write_file(
            os.path.join(staging, "host_state.json"),
            json.dumps(host).encode())
    part = {"format": 3, "process_index": process_index,
            "process_count": process_count, "sha256": digests,
            "cursor": cursor}
    _write_json(os.path.join(staging, f"PART-{process_index}.json"), part)
    _fsync_dir(staging)
    if process_index != 0:
        return  # phase 2 is the commit rank's

    parts = _await_parts(staging, process_count, commit_timeout_s)
    merged: Dict[str, str] = {}
    cursors: Dict[str, Any] = {}
    for k in sorted(k for k in parts if k < process_count):
        merged.update(parts[k].get("sha256") or {})
        if parts[k].get("cursor") is not None:
            cursors[str(k)] = parts[k]["cursor"]
        pname = f"PART-{k}.json"
        with open(os.path.join(staging, pname), "rb") as f:
            merged[pname] = _blob(f.read())
    sharding = dict(run_meta)
    sharding["trees"] = {name: snap.leaf_meta
                        for name, snap in snaps.items()}
    manifest = {"format": 3, "neval": neval,
                "files": sorted(merged), "sha256": merged,
                "sharding": sharding, "cursors": cursors}
    # the scripted-death site the torn-commit tests SIGKILL: after the
    # last part, before the completeness certificate
    faults.point("ckpt/write_manifest", neval=neval if neval is not None
                 else -1, path=path)
    _write_json(os.path.join(staging, MANIFEST), manifest)
    _fsync_dir(staging)
    _commit_rename(staging, path)
    logger.info("elastic checkpoint committed: %s (%d parts)", path,
                process_count)
    if keep_last:
        prune_checkpoints(os.path.dirname(path), keep_last)


class AsyncCheckpointWriter:
    """One background writer thread, one write in flight.

    ``submit`` first drains the previous write (bounded memory: at most
    one snapshot is ever held), re-raising any failure so the
    optimizer's classified retry loop sees it exactly where the sync
    path would have raised; ``flush`` is the explicit drain every
    resume/exit path calls — a commit must be visible before
    ``find_latest_checkpoint`` is consulted."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None

    @property
    def busy(self) -> bool:
        """A write is still in flight (used by the GC concurrency
        test)."""
        t = self._thread
        return t is not None and t.is_alive()

    def submit(self, fn, describe: str = "") -> None:
        """Drain the previous write, then run ``fn`` on the background
        thread under the ``checkpoint/async_write`` span +
        ``train/checkpoint/async_write_s`` histogram."""
        self.flush()

        def run():
            t0 = time.perf_counter()
            try:
                with telemetry.span("checkpoint/async_write",
                                    path=describe):
                    fn()
            except BaseException as e:  # surfaced on the next flush
                self._error = e
                logger.warning("async checkpoint write failed "
                               "(%s: %s); surfacing on next flush",
                               type(e).__name__, e)
            finally:
                _ASYNC_WRITE_S.observe(time.perf_counter() - t0)

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="bigdl-ckpt-writer")
        self._thread.start()

    def flush(self, timeout_s: Optional[float] = None) -> None:
        """Join the in-flight write; re-raise its failure (once)."""
        t = self._thread
        if t is not None:
            t.join(timeout_s)
            if t.is_alive():
                raise TimeoutError(
                    "async checkpoint write still running after "
                    f"{timeout_s}s flush timeout")
            self._thread = None
        if self._error is not None:
            e, self._error = self._error, None
            raise e


def save_checkpoint(path: str, *, params, opt_state, model_state,
                    optim_host_state: Dict[str, Any],
                    driver_state: Dict[str, Any],
                    run_meta: Optional[Dict[str, Any]] = None,
                    cursor=None, process_index: int = 0,
                    process_count: int = 1,
                    writer: Optional[AsyncCheckpointWriter] = None,
                    keep_last: Optional[int] = None,
                    commit_timeout_s: Optional[float] = None) -> None:
    """Write one format-3 elastic checkpoint.

    Every process calls this with ITS trees (the same global arrays —
    each snapshots only its own shards). With ``writer`` the step-loop
    stall is the snapshot alone (``train/checkpoint/save_s`` +
    ``checkpoint/save`` span) and the serialize/hash/commit tail runs
    on the background thread (``train/checkpoint/async_write_s`` +
    ``checkpoint/async_write`` span); without it the write is inline.
    The checkpoint only becomes visible when process 0's MANIFEST
    lands and the staging dir renames into place. Local filesystems
    only — remote object stores keep the gathered format-2 path
    (``utils.serialization.save_checkpoint``)."""
    if file_io.is_remote(path):
        raise ValueError(
            "elastic per-shard checkpointing stages + renames on a "
            "local (or shared POSIX) filesystem; use the format-2 "
            "writer for remote object stores")
    if commit_timeout_s is None:
        commit_timeout_s = float(
            os.environ.get("BIGDL_ELASTIC_COMMIT_TIMEOUT", 600.0))
    path = os.path.abspath(path)
    neval = driver_state.get("neval")
    # the staging name must be AGREED across processes without
    # communication: neval is, and so is the launcher's gang-wide
    # BIGDL_RESTART_ATTEMPT — including it makes a relaunched gang's
    # staging dir fresh, so a dead incarnation's stale parts (same
    # neval, possibly a different world size) can never race the
    # commit barrier
    incarnation = os.environ.get("BIGDL_RESTART_ATTEMPT")
    staging = f"{path}.staging-{neval}" + (
        f"-r{incarnation}" if incarnation else "")
    if process_count == 1 and os.path.exists(staging):
        shutil.rmtree(staging)  # our own earlier failed attempt
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    meta = dict(run_meta) if run_meta is not None else run_metadata(
        process_count=process_count)
    host = {"optim_host_state": optim_host_state,
            "driver_state": dict(driver_state)}
    t0 = time.perf_counter()
    with telemetry.span("checkpoint/save", path=path,
                        mode="async" if writer is not None else "sync"):
        snaps = {"params": snapshot_tree(params, process_index),
                 "opt_state": snapshot_tree(opt_state, process_index),
                 "model_state": snapshot_tree(model_state, process_index)}
        if writer is None:
            _write_and_commit(staging, path, snaps, host, meta, cursor,
                              process_index, process_count, neval,
                              keep_last, commit_timeout_s)
        else:
            # submit INSIDE the timed window: it first drains any
            # still-running previous write, and that join is REAL
            # step-loop stall — save_s must not under-report it when
            # checkpoints arrive faster than the writer commits
            writer.submit(
                lambda: _write_and_commit(staging, path, snaps, host,
                                          meta, cursor, process_index,
                                          process_count, neval,
                                          keep_last, commit_timeout_s),
                describe=path)
    telemetry.histogram("train/checkpoint/save_s").observe(
        time.perf_counter() - t0)


# ---------------------------------------------------------- GC/retention

def committed_checkpoints(directory: str) -> List[Tuple[tuple, str]]:
    """Every COMMITTED checkpoint under ``directory`` as a sorted
    ``[(recency_key, path), ...]`` (oldest first) — exactly
    ``serialization.list_complete_checkpoints``: ONE implementation of
    the completeness/recency rules, so retention GC can never disagree
    with ``find_latest_checkpoint`` about which dirs count (an
    in-flight async staging dir has no MANIFEST yet = not committed =
    not a candidate)."""
    from bigdl_tpu.utils.serialization import list_complete_checkpoints
    return list_complete_checkpoints(directory)


def prune_checkpoints(directory: str, keep_last: int) -> List[str]:
    """Delete all but the newest ``keep_last`` COMMITTED checkpoints.

    Never deletes the newest committed checkpoint (``keep_last`` is
    clamped to >= 1), never touches ``*.corrupt-*`` quarantines or an
    in-flight async staging dir (no MANIFEST yet = not committed, so
    it is simply not a candidate) — safe to run concurrently with an
    in-flight async write. Returns the deleted paths."""
    keep_last = max(1, int(keep_last))
    entries = committed_checkpoints(directory)
    doomed = entries[:-keep_last] if len(entries) > keep_last else []
    deleted = []
    for _, full in doomed:
        shutil.rmtree(full, ignore_errors=True)
        if not os.path.exists(full):
            deleted.append(full)
            logger.info("pruned checkpoint %s (keep_last=%d)", full,
                        keep_last)
    if deleted:
        _PRUNED.inc(len(deleted))
    return deleted


def is_torn_commit(path: str) -> bool:
    """True for a directory holding phase-1 part files but no MANIFEST
    — the signature of a death between the last part write and the
    manifest fsync. ``verify_checkpoint`` raises
    :class:`CheckpointCorrupt` on these so they are quarantinable."""
    if not os.path.isdir(path) or \
            os.path.exists(os.path.join(path, MANIFEST)):
        return False
    try:
        return any(PART_RE.match(n) for n in os.listdir(path))
    except OSError:
        return False


__all__ = ["AsyncCheckpointWriter", "TreeSnapshot", "TREES",
           "committed_checkpoints", "is_torn_commit", "parse_slices_key",
           "prune_checkpoints", "run_metadata", "save_checkpoint",
           "snapshot_tree"]
