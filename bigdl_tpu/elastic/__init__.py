"""Elastic, preemption-tolerant training (ROADMAP open item 1).

The failure mode TPU pods actually have is not a crashed executor the
scheduler replaces (the reference's Spark story) — it is the WHOLE pod
being preempted or resized. Surviving that needs three pieces, built
here over the faults/manifest groundwork of PR 5 and the cross-mesh
ZeRO resume seed of PR 8:

- :mod:`elastic.checkpoint` — **async per-shard checkpointing**: each
  process snapshots only the shards it holds (no gather collective),
  the write/hash/fsync tail runs on a background writer, and a
  barriered two-phase commit publishes a format-3 MANIFEST recording
  per-part sha256 digests AND full sharding metadata (mesh shape, axis
  names, per-leaf PartitionSpec, ZeRO stage, precision policy,
  per-process datapipe cursors). Plus ``keep_last`` retention GC.
- :mod:`elastic.resume` — **cross-mesh resume**: reassemble the global
  arrays from the parts using the recorded specs and re-shard onto an
  arbitrary new mesh / process count / ZeRO stage / TP rule set
  (``load_for_mesh``), with datapipe cursors re-split across the new
  world size (``resplit_cursor``).
- :mod:`elastic.preempt` — **SIGTERM grace**: flag-and-drain handler;
  the optimizer flushes an emergency checkpoint + flight-recorder
  bundle at the next step boundary and exits through :class:`Preempted`
  so the launcher's gang restart (``tools.launch``) — possibly at a
  different world size — resumes it.

End-to-end coverage lives in ``tools.chaos --hostkill`` (SIGKILL a
whole gang host mid-window, relaunch at a different world size, assert
the resumed params against the uninterrupted reference) and
``tests/test_elastic.py`` (resume matrix, torn-commit, GC, grace).
See docs/robustness.md "Elastic training".
"""
from bigdl_tpu.elastic.checkpoint import (AsyncCheckpointWriter,
                                          committed_checkpoints,
                                          is_torn_commit,
                                          prune_checkpoints, run_metadata,
                                          save_checkpoint, snapshot_tree)
from bigdl_tpu.elastic.preempt import GraceHandler, Preempted
from bigdl_tpu.elastic.resume import (checkpoint_format, load_for_mesh,
                                      load_parts, resplit_cursor)

__all__ = [
    "AsyncCheckpointWriter", "GraceHandler", "Preempted",
    "checkpoint_format", "committed_checkpoints", "is_torn_commit",
    "load_for_mesh", "load_parts", "prune_checkpoints", "resplit_cursor",
    "run_metadata", "save_checkpoint", "snapshot_tree",
]
