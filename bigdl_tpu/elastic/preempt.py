"""SIGTERM grace handling: flush an emergency checkpoint before dying.

TPU pods are preempted with a grace window: the scheduler SIGTERMs the
workload and SIGKILLs it some seconds later. A run that ignores the
SIGTERM loses everything since its last periodic checkpoint; one that
checkpoints *inside the signal handler* corrupts state (handlers
interrupt arbitrary host code mid-step). The supported shape is the
flag-and-drain pattern: :class:`GraceHandler` only sets an event; the
optimizer's step loop notices it at the next step boundary — params and
optimizer state are complete and consistent there — flushes any
in-flight async write, writes an EMERGENCY checkpoint synchronously,
dumps a flight-recorder bundle, and raises :class:`Preempted`.

``Preempted`` subclasses ``BaseException`` on purpose: the classified
retry-from-checkpoint loop catches ``Exception`` — a preemption must
escape it (retrying inside a doomed process burns the grace window),
reach the launcher as a nonzero exit, and let the GANG relaunch —
possibly at a different world size — resume from the emergency
checkpoint (``elastic.resume``).
"""
from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

import bigdl_tpu.telemetry as telemetry

logger = logging.getLogger("bigdl_tpu")

_PREEMPTIONS = telemetry.counter(
    "train/elastic/preemptions",
    "SIGTERM grace exits taken (emergency checkpoint flushed)")


class Preempted(BaseException):
    """The run was preempted (SIGTERM) and exited through the grace
    path AFTER flushing its emergency checkpoint. A ``BaseException``
    so the optimizer's retry loop never swallows it — the relaunched
    gang, not this dying process, is the recovery."""


class GraceHandler:
    """Install-once SIGTERM (by default) flag: the handler body only
    sets a ``threading.Event`` — no locks, no IO, nothing a signal
    context can deadlock on. Poll :meth:`requested` at safe points."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._prev = {}
        self._installed = False

    def _on_signal(self, signum, frame) -> None:
        self._event.set()

    def install(self) -> "GraceHandler":
        """Install the handlers (main thread only — elsewhere the
        handler is left uninstalled and :meth:`requested` simply never
        fires; the run keeps its periodic checkpoints)."""
        if self._installed:
            return self
        for s in self.signals:
            try:
                self._prev[s] = signal.signal(s, self._on_signal)
            except ValueError:
                logger.warning(
                    "cannot install signal %s handler off the main "
                    "thread; preemption grace disabled", s)
                self.uninstall()
                return self
        self._installed = True
        return self

    def uninstall(self) -> None:
        """Restore the previous handlers."""
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, TypeError):
                pass
        self._prev.clear()
        self._installed = False

    def requested(self) -> bool:
        """True once a grace signal arrived (sticky)."""
        return self._event.is_set()

    def request(self) -> None:
        """Programmatic trigger (tests / embedding schedulers)."""
        self._event.set()

    def count_preemption(self) -> None:
        """Record the preemption in telemetry + the flight ring — called
        from the DRAIN path (loop context), never the signal handler."""
        _PREEMPTIONS.inc()
        telemetry.flight.note("preempt", grace="sigterm")


__all__ = ["GraceHandler", "Preempted"]
