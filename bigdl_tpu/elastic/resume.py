"""Cross-mesh elastic resume: reassemble format-3 per-shard checkpoints.

A format-3 checkpoint is a bag of per-process shard files plus a
MANIFEST that records, for every leaf, its global shape/dtype and the
PartitionSpec it was written under. Loading therefore needs NO live
mesh: :func:`load_parts` allocates each global array on the host and
fills it block by block from the parts (every byte written exactly once
— the writer deduped by ``Shard.replica_id``), verifying full coverage.
:func:`load_for_mesh` then re-shards the reassembled trees onto an
ARBITRARY new layout — a different mesh shape, process count, ZeRO
stage or TP rule set — via the same placement engine the Optimizer
uses (``parallel.zero.place_zero_state``). This generalizes the
stage2/8dev -> stage3/4dev restore seeded in ``tests/test_zero.py``
into the supported resume surface (resume-matrix-tested in
``tests/test_elastic.py``).

The per-process datapipe cursors recorded in the MANIFEST re-split
across the new world size with :func:`resplit_cursor`: an unchanged
process count restores each stream bit-exactly; a changed one restarts
the current epoch (the shard -> process assignment changed underneath
the cursors, so positions inside the old split are meaningless — the
bounded, documented fallback, not silent replay/skip).
"""
from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

import numpy as np

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.utils.serialization import (MANIFEST, CheckpointCorrupt,
                                           _rebuild, verify_checkpoint)

from bigdl_tpu.elastic.checkpoint import parse_slices_key


def checkpoint_format(path: str) -> int:
    """The MANIFEST-declared format of a checkpoint dir (0 when no
    MANIFEST exists — the pre-integrity layout)."""
    mpath = os.path.join(path, MANIFEST)
    if not os.path.exists(mpath):
        return 0
    try:
        with open(mpath) as f:
            return int(json.load(f).get("format", 0))
    except (OSError, ValueError) as e:
        raise CheckpointCorrupt(f"{path}: unreadable MANIFEST ({e})")


def _reassemble_tree(path: str, name: str, manifest: dict):
    """One tree (params/opt_state/model_state) rebuilt from its parts:
    allocate every leaf at its recorded global shape/dtype, fill each
    part's blocks, and fail loudly on a coverage gap (a lost part file
    would otherwise resume uninitialized memory as weights)."""
    with open(os.path.join(path, f"{name}.json")) as f:
        template = json.load(f)
    leaf_meta = (manifest.get("sharding") or {}).get("trees",
                                                     {}).get(name, {})
    arrays: Dict[str, np.ndarray] = {}
    covered: Dict[str, dict] = {}
    for key, m in leaf_meta.items():
        arrays[key] = np.empty(tuple(m["shape"]), np.dtype(m["dtype"]))
        covered[key] = {}
    part_re = re.compile(rf"^{re.escape(name)}\.part\d+\.npz$")
    for fname in manifest.get("files", []):
        if not part_re.match(fname):
            continue
        try:
            ctx = np.load(os.path.join(path, fname))
        except OSError as e:
            raise CheckpointCorrupt(
                f"{path}: MANIFEST names {fname} but it cannot be "
                f"read ({e})")
        with ctx as z:
            for nk in z.files:
                key, _, sl = nk.rpartition("|")
                if key not in arrays:
                    raise CheckpointCorrupt(
                        f"{path}: {fname} carries unknown leaf {key!r}")
                block = z[nk]
                slices = parse_slices_key(sl, arrays[key].shape)
                arrays[key][slices] = block
                # coverage by UNIQUE block: a replicated block written
                # by more than one part (identical bytes by the
                # replica-0 convention) must not double-count
                covered[key][sl] = int(block.size)
    for key, arr in arrays.items():
        got = sum(covered[key].values())
        if got != int(arr.size):
            raise CheckpointCorrupt(
                f"{path}: leaf {key!r} of {name} covered "
                f"{got}/{arr.size} elements — a shard part is "
                "missing; refusing to resume from uninitialized memory")
    return _rebuild(template, arrays)


def load_parts(path: str, verify: bool = True) -> Dict[str, Any]:
    """Read one COMMITTED format-3 checkpoint into full host trees.

    Returns the same dict shape ``serialization.load_checkpoint``
    produces (``params`` / ``opt_state`` / ``model_state`` host trees +
    ``optim_host_state`` / ``driver_state``), plus the elastic extras:
    ``sharding`` (the MANIFEST's recorded metadata) and ``cursors``
    (per-writing-process datapipe cursors). Integrity-verified first
    unless ``verify=False``."""
    with telemetry.span("checkpoint/load", path=path, format=3):
        if verify:
            verify_checkpoint(path)
        with open(os.path.join(path, MANIFEST)) as f:
            manifest = json.load(f)
        if int(manifest.get("format", 0)) < 3:
            raise ValueError(
                f"{path} is a format-{manifest.get('format')} "
                "checkpoint; use serialization.load_checkpoint")
        with open(os.path.join(path, "host_state.json")) as f:
            host = json.load(f)
        out = {name: _reassemble_tree(path, name, manifest)
               for name in ("params", "opt_state", "model_state")}
        out["optim_host_state"] = host["optim_host_state"]
        out["driver_state"] = host["driver_state"]
        out["sharding"] = manifest.get("sharding") or {}
        out["cursors"] = manifest.get("cursors") or {}
        return out


def load_for_mesh(path: str, mesh=None, zero=None, rules=None,
                  verify: bool = True) -> Dict[str, Any]:
    """Cross-mesh elastic resume in one call: :func:`load_parts`, then
    re-shard params + optimizer state onto the NEW layout — whatever
    ``mesh`` / ``zero`` stage / TP ``rules`` the relaunched job runs,
    regardless of the mesh the checkpoint was written under (the
    manifest's metadata already served its purpose during reassembly).
    With ``mesh=None`` the host trees are returned unplaced (the
    single-device regime). ``model_state`` is placed replicated."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from bigdl_tpu.parallel.zero import place_zero_state
    ck = load_parts(path, verify=verify)
    if mesh is not None:
        ck["params"], ck["opt_state"] = place_zero_state(
            ck["params"], ck["opt_state"], mesh, zero, rules)
        from bigdl_tpu.parallel.tp import put_global
        repl = NamedSharding(mesh, PartitionSpec())
        ck["model_state"] = jax.tree.map(
            lambda a: put_global(a, repl), ck["model_state"])
    return ck


def resplit_cursor(cursors: Dict[str, Any], process_index: int,
                   process_count: int) -> Optional[dict]:
    """The datapipe cursor the relaunched ``process_index`` of
    ``process_count`` should restore, from the per-process cursors a
    format-3 MANIFEST recorded.

    Same process count -> the exact per-process cursor (bit-exact
    stream continuation). Different count -> the shard->process
    assignment changed underneath every recorded position, so the
    supported re-split is an epoch restart: every process resumes at
    the start of the EARLIEST in-flight epoch (seeded shard orders and
    shuffles re-derive from the epoch number, so the stream stays a
    pure function of ``(seed, epoch, position)`` — a bounded replay of
    the current epoch, never silent skip or reorder)."""
    if not cursors:
        return None
    if len(cursors) == process_count:
        c = cursors.get(str(process_index))
        return dict(c) if c is not None else None
    epochs = [int(c.get("epoch", 0)) for c in cursors.values()
              if isinstance(c, dict)]
    if not epochs:
        return None
    return {"epoch": min(epochs), "spos": 0, "offset": 0}


__all__ = ["checkpoint_format", "load_for_mesh", "load_parts",
           "resplit_cursor"]
