"""bigdl_tpu — a TPU-native deep-learning framework with BigDL's capabilities.

A from-scratch rebuild of the capability surface of BigDL (reference:
frankfzw/BigDL, Scala/Spark/MKL) as an idiomatic JAX/XLA framework:

- ``bigdl_tpu.nn``       — module/criterion library (BigDL ``nn``: layers are
  declarative objects with a pure-functional ``init``/``apply`` core; autodiff
  replaces hand-written backward passes).
- ``bigdl_tpu.optim``    — OptimMethods (SGD + LR schedules, Adam, ...),
  Triggers, ValidationMethods, Local/Distri optimizers (BigDL ``optim``).
- ``bigdl_tpu.dataset``  — DataSet/Transformer/Sample/MiniBatch data pipeline
  (BigDL ``dataset``).
- ``bigdl_tpu.parallel`` — Engine (mesh/topology config) + the distributed
  training runtime: sharded sync-SGD over a ``jax.sharding.Mesh`` with XLA
  collectives, replacing BigDL's AllReduceParameter/BlockManager PS.
- ``bigdl_tpu.models``   — model zoo (LeNet, VGG, ResNet, Inception, RNN LM,
  Autoencoder) mirroring BigDL's ``models/``.
- ``bigdl_tpu.serving``  — online inference: dynamic micro-batching, a
  shape-bucketed compile cache, and a hot-swappable multi-model registry
  (BigDL's local/distributed predictor serving story, request-level).
- ``bigdl_tpu.generation`` — autoregressive generation serving: a
  bucketed KV-cache decode engine (≤ 2K compiled prefill/decode pairs
  for K length buckets) with continuous batching, streaming token
  futures, and hot-swap under live decode (docs/serving.md).
- ``bigdl_tpu.utils``    — Table (the pytree of the system), RandomGenerator,
  DirectedGraph, File I/O, logging.
- ``bigdl_tpu.ops``      — pallas TPU kernels for ops XLA fusion can't cover
  (int8 quantized GEMM — the BigQuant equivalent) and collective primitives.
- ``bigdl_tpu.analysis`` — pre-compile static analysis: eval_shape-based
  shape/dtype checking with layer-path diagnostics (``Module.check``) and a
  pluggable JAX-pitfall linter (``python -m bigdl_tpu.tools.check``).
- ``bigdl_tpu.faults``   — deterministic fault injection (named faultpoints,
  seeded schedules) + classified backoff retry; recovery is validated
  bit-exactly by ``python -m bigdl_tpu.tools.chaos`` (docs/robustness.md).

Design notes (vs the reference, /root/reference):
- BigDL ``Tensor[T]`` (tensor/Tensor.scala:36) -> ``jax.Array``; the 104-method
  TensorMath surface is jnp/lax.
- ``AbstractModule.forward/backward`` (nn/abstractnn/AbstractModule.scala:56)
  -> pure ``apply`` + ``jax.grad``; the stateful convenience API is kept for
  parity (``module.forward(x)``, ``module.backward(x, grad)``).
- ``Engine``'s two thread pools (utils/Engine.scala:139-143) -> XLA; intra-node
  sub-model clones (DistriOptimizer.scala:116-118) -> per-chip batch dim.
- ``AllReduceParameter`` reduce-scatter/all-gather over Spark BlockManager
  (parameters/AllReduceParameter.scala) -> ``lax.psum``/``psum_scatter`` +
  ``all_gather`` over the ICI mesh, with ZeRO-1-style sharded optimizer state.
"""

from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.random import RandomGenerator
from bigdl_tpu.utils.engine import Engine
from bigdl_tpu import (nn, optim, dataset, faults, generation, parallel,
                       serving, telemetry, utils, analysis)

__version__ = "0.1.0"

__all__ = [
    "Table", "T", "RandomGenerator", "Engine",
    "analysis", "nn", "optim", "dataset", "faults", "generation",
    "parallel", "serving", "telemetry", "utils",
]
