"""MNIST autoencoder (reference: models/autoencoder/Autoencoder.scala)."""
from __future__ import annotations

import bigdl_tpu.nn as nn

ROW_N = 28
COL_N = 28
FEATURE_SIZE = ROW_N * COL_N


def Autoencoder(class_num: int = 32) -> nn.Sequential:
    """MNIST 784-classNum-784 sigmoid autoencoder
    (models/autoencoder/Autoencoder.scala:25)."""
    m = nn.Sequential()
    m.add(nn.Reshape((FEATURE_SIZE,)))
    m.add(nn.Linear(FEATURE_SIZE, class_num))
    m.add(nn.ReLU())
    m.add(nn.Linear(class_num, FEATURE_SIZE))
    m.add(nn.Sigmoid())
    return m
