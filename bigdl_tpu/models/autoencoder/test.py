"""Autoencoder MNIST evaluation — reconstruction MSE over the test split
(completes the zoo's train/test surface; the reference ships only
models/autoencoder/Train.scala, so this mirrors its objective at eval
time: MSECriterion against the input image).

    python -m bigdl_tpu.models.autoencoder.test -f /path/to/mnist --model s
    python -m bigdl_tpu.models.autoencoder.test --synthetic 64
"""
from __future__ import annotations


def main(argv=None):
    from bigdl_tpu.models._cli import (base_parser, load_model_or,
                                       mnist_arrays)

    args = base_parser("Test the MNIST autoencoder").parse_args(argv)

    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.autoencoder import Autoencoder
    from bigdl_tpu.optim import Evaluator, Loss

    bs = args.batchSize or 150
    imgs, _ = mnist_arrays(args.folder, False, args.synthetic)
    flat = imgs.reshape(len(imgs), -1).astype(np.float32)
    samples = [Sample(flat[i], flat[i]) for i in range(len(flat))]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(bs))

    model = load_model_or(args, lambda: Autoencoder(class_num=32)).evaluate()
    if args.quantize:
        model = model.quantize()
    results = Evaluator(model).test(
        ds, [Loss(nn.MSECriterion())], batch_size=bs)
    for name, r in results.items():
        print(f"{name}: {r}")
    return results


if __name__ == "__main__":
    main()
