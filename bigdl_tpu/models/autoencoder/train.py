"""Autoencoder MNIST training recipe (models/autoencoder/Train.scala —
Adagrad lr 0.01, MSE against the input image).

    python -m bigdl_tpu.models.autoencoder.train -f /path/to/mnist
    python -m bigdl_tpu.models.autoencoder.train --synthetic 256 -e 1
"""
from __future__ import annotations


def main(argv=None):
    from bigdl_tpu.models._cli import (arrays_to_dataset, base_parser,
                                       load_model_or, mnist_arrays,
                                       wire_optimizer)

    args = base_parser("Train the MNIST autoencoder").parse_args(argv)

    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    from bigdl_tpu.models.autoencoder import Autoencoder
    from bigdl_tpu.optim import Adagrad, LocalOptimizer

    bs = args.batchSize or 150
    imgs, _ = mnist_arrays(args.folder, True, args.synthetic)
    flat = imgs.reshape(len(imgs), -1).astype(np.float32)
    samples = [Sample(flat[i], flat[i]) for i in range(len(flat))]
    ds = DataSet.array(samples).transform(SampleToMiniBatch(bs))

    model = load_model_or(args, lambda: Autoencoder(class_num=32))
    optim = Adagrad(learning_rate=args.learningRate or 0.01)
    opt = LocalOptimizer(model, ds, nn.MSECriterion(), batch_size=bs)
    wire_optimizer(opt, args, optim, default_epochs=10)
    opt.optimize()
    print(f"final loss: {opt.driver_state['Loss']:.4f}")
    return model


if __name__ == "__main__":
    main()
