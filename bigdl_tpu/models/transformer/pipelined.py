"""Pipeline-parallel TransformerLM — PP as product surface, not library.

The reference's whole identity was that its parallelism was reachable
from ``Optimizer(...).optimize()`` (optim/DistriOptimizer.scala:728);
this model gives the net-new pipeline parallelism the same one-call
surface: construct :class:`PipelinedTransformerLM` on a mesh with a
``pipe`` axis, hand its :meth:`sharding_rules` to the Optimizer, and the
jitted train step runs GPipe-style microbatch pipelining over the pipe
ring (parallel/pipeline.py) — composing with data parallelism on the
batch dim and megatron tensor parallelism inside blocks, all in ONE
``jax.shard_map(axis_names={'pipe'})`` region whose other mesh axes stay
GSPMD-auto.

TPU-first design notes:
- blocks are HOMOGENEOUS and stored STACKED ([L, ...] leaves) — that is
  what lets a stage run its layers as a ``lax.scan`` and the pipeline
  ship one microbatch per ``ppermute`` hop with zero retracing;
- off the mesh (or pipe axis absent / size 1) the same params run a
  plain ``lax.scan`` over layers — identical math, so single-chip
  tests, checkpoints, and the grads≡dense assertion all share one model;
- dropout is intentionally unsupported: per-microbatch rng threading
  through the pipeline ring would make the objective depend on the
  stage count.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.attention import dot_product_attention
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils.engine import Engine


def _layernorm(x, scale, bias, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * scale + bias


class PipelinedTransformerLM(Module):
    """Decoder-only LM over int32 token ids [B, S] -> logits [B, S, V],
    with the block stack pipelined over a mesh ``pipe`` axis.

    ``num_layers`` must divide by the pipe-axis size; the global batch
    must divide by ``n_microbatches`` (which should be >= the stage
    count to keep the pipeline bubble small: bubble fraction =
    (stages-1)/(microbatches+stages-1))."""

    def __init__(self, vocab_size: int, hidden_size: int = 512,
                 num_layers: int = 8, num_heads: int = 8,
                 ffn_size: Optional[int] = None, max_len: int = 2048,
                 n_microbatches: int = 4, pipe_axis: str = "pipe",
                 mesh: Optional[jax.sharding.Mesh] = None,
                 tie_embeddings: bool = True):
        super().__init__()
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = hidden_size // num_heads
        assert hidden_size % num_heads == 0
        self.ffn_size = ffn_size or 4 * hidden_size
        self.max_len = max_len
        self.n_microbatches = n_microbatches
        self.pipe_axis = pipe_axis
        self.mesh = mesh
        self.tie_embeddings = tie_embeddings
        # stable bound-method identity: pipeline_forward's cache keys on
        # the block callable, and `self._block` creates a fresh bound
        # method on every attribute access
        self._block_fn = self._block

    # ------------------------------------------------------------ params
    def init(self, rng):
        dtype = Engine.default_dtype()
        L, E, F = self.num_layers, self.hidden_size, self.ffn_size
        keys = jax.random.split(rng, 10)
        s = 1.0 / math.sqrt(E)
        sf = 1.0 / math.sqrt(F)

        def u(k, shape, scale):
            return jax.random.uniform(k, shape, dtype, -scale, scale)

        blocks = {
            "ln1_scale": jnp.ones((L, E), dtype),
            "ln1_bias": jnp.zeros((L, E), dtype),
            "wq": u(keys[0], (L, E, E), s), "bq": jnp.zeros((L, E), dtype),
            "wk": u(keys[1], (L, E, E), s), "bk": jnp.zeros((L, E), dtype),
            "wv": u(keys[2], (L, E, E), s), "bv": jnp.zeros((L, E), dtype),
            "wo": u(keys[3], (L, E, E), s), "bo": jnp.zeros((L, E), dtype),
            "ln2_scale": jnp.ones((L, E), dtype),
            "ln2_bias": jnp.zeros((L, E), dtype),
            "w_up": u(keys[4], (L, E, F), s),
            "b_up": jnp.zeros((L, F), dtype),
            "w_down": u(keys[5], (L, F, E), sf),
            "b_down": jnp.zeros((L, E), dtype),
        }
        p = {"embed": jax.random.normal(
                 keys[6], (self.vocab_size, E), dtype) * s,
             "pos_embed": jax.random.normal(
                 keys[7], (self.max_len, E), dtype) * s,
             "ln_f_scale": jnp.ones((E,), dtype),
             "ln_f_bias": jnp.zeros((E,), dtype),
             "blocks": blocks}
        if not self.tie_embeddings:
            p["lm_head"] = jax.random.normal(
                keys[8], (E, self.vocab_size), dtype) * s
        return p

    # ------------------------------------------------------- block forward
    def _block(self, lp, h):
        """One pre-norm transformer block. lp: this layer's slice of the
        stacked params (leading L dim scanned away); h: [mb, S, E]."""
        b, s, e = h.shape
        hd, nh = self.head_dim, self.num_heads

        def split(t):
            return t.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)

        x = _layernorm(h, lp["ln1_scale"], lp["ln1_bias"])
        q = split(x @ lp["wq"] + lp["bq"])
        k = split(x @ lp["wk"] + lp["bk"])
        v = split(x @ lp["wv"] + lp["bv"])
        att = dot_product_attention(q, k, v, causal=True)
        att = att.transpose(0, 2, 1, 3).reshape(b, s, e)
        h = h + att @ lp["wo"] + lp["bo"]
        x = _layernorm(h, lp["ln2_scale"], lp["ln2_bias"])
        ffn = jax.nn.gelu(x @ lp["w_up"] + lp["b_up"]) @ lp["w_down"] \
            + lp["b_down"]
        return h + ffn

    def forward_fn(self, params, input, *, training=False, rng=None):
        from bigdl_tpu.parallel.mesh import resolve_axis_mesh
        tokens = input.astype(jnp.int32)
        b, s = tokens.shape
        x = params["embed"][tokens] + params["pos_embed"][:s][None]
        mesh = resolve_axis_mesh(self.mesh, self.pipe_axis)
        if mesh is not None:
            from bigdl_tpu.parallel.pipeline import pipeline_forward
            x = pipeline_forward(self._block_fn, params["blocks"], x,
                                 mesh, axis_name=self.pipe_axis,
                                 n_microbatches=self.n_microbatches)
        else:
            def body(h, lp):
                return self._block(lp, h), None
            x, _ = jax.lax.scan(body, x, params["blocks"])
        x = _layernorm(x, params["ln_f_scale"], params["ln_f_bias"])
        if self.tie_embeddings:
            return x @ params["embed"].T
        return x @ params["lm_head"]

    # ------------------------------------------------------------ sharding
    def sharding_rules(self, pipe_axis: Optional[str] = None,
                       model_axis: Optional[str] = None):
        """Rules for ``Optimizer(sharding_rules=...)``: stacked block
        leaves shard their layer dim over the pipe axis, and (when a
        model axis is given) megatron column/row TP on the inner dims —
        the composed DP×TP×PP layout in one table."""
        from jax.sharding import PartitionSpec as P
        pa = pipe_axis or self.pipe_axis
        ma = model_axis
        return [
            ("pos_embed", P()),
            (r"(^|/)embed$", P(ma, None) if ma else P()),
            ("lm_head", P(None, ma) if ma else P()),
            (r"blocks/w[qkv]$", P(pa, None, ma)),   # column-parallel
            (r"blocks/b[qkv]$", P(pa, ma)),
            (r"blocks/wo$", P(pa, ma, None)),       # row-parallel
            (r"blocks/bo$", P(pa, None)),
            (r"blocks/w_up$", P(pa, None, ma)),
            (r"blocks/b_up$", P(pa, ma)),
            (r"blocks/w_down$", P(pa, ma, None)),
            (r"blocks/b_down$", P(pa, None)),
            (r"blocks/ln\d_", P(pa, None)),
            ("ln_f", P()),
        ]
