"""Pipeline-parallel TransformerLM — PP as product surface, not library.

The reference's whole identity was that its parallelism was reachable
from ``Optimizer(...).optimize()`` (optim/DistriOptimizer.scala:728);
this model gives the net-new pipeline parallelism the same one-call
surface: construct :class:`PipelinedTransformerLM` on a mesh with a
``pipe`` axis, hand its :meth:`sharding_rules` to the Optimizer, and the
jitted train step runs GPipe-style microbatch pipelining over the pipe
ring (parallel/pipeline.py) — composing with data parallelism on the
batch dim, megatron tensor parallelism inside blocks, sequence
parallelism (``ring_axis=`` ring/ulysses attention, manual collectives
inside each pipeline stage), and expert parallelism (``moe_experts=``
stacked routed FFNs, expert dim GSPMD-sharded) — the full
DP×TP×PP×SP(×EP) product in ONE ``jax.shard_map`` region whose
data/model/expert axes stay GSPMD-auto.

TPU-first design notes:
- blocks are HOMOGENEOUS and stored STACKED ([L, ...] leaves) — that is
  what lets a stage run its layers as a ``lax.scan`` and the pipeline
  ship one microbatch per ``ppermute`` hop with zero retracing; with
  ``moe_experts`` EVERY block is a routed MoE (a mixed dense/MoE stack
  would break homogeneity — use the non-pipelined TransformerLM's
  ``moe_every`` for that);
- off the mesh (or pipe axis absent / size 1) the same params run a
  plain ``lax.scan`` over layers — identical math, so single-chip
  tests, checkpoints, and the grads≡dense assertion all share one
  model. With MoE the fallback loops the microbatches explicitly so the
  load-balance aux loss (per-microbatch statistics, averaged) is
  BIT-COMPARABLE to the pipelined path;
- the MoE aux statistics are ``pmean``-ed over the sequence axis when
  sequence parallelism is active, so SP-sharded routing reproduces the
  full-sequence statistics exactly (mean of equal-size shard means);
- dropout is intentionally unsupported: per-microbatch rng threading
  through the pipeline ring would make the objective depend on the
  stage count.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.nn.attention import _inside_axis, dot_product_attention
from bigdl_tpu.nn.module import AUX_LOSS_KEY, Module
from bigdl_tpu.utils.engine import Engine


def _layernorm(x, scale, bias, eps=1e-5):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * scale + bias


class PipelinedTransformerLM(Module):
    """Decoder-only LM over int32 token ids [B, S] -> logits [B, S, V],
    with the block stack pipelined over a mesh ``pipe`` axis.

    ``num_layers`` must divide by the pipe-axis size; the global batch
    must divide by ``n_microbatches`` (which should be >= the stage
    count to keep the pipeline bubble small: bubble fraction =
    (stages-1)/(microbatches+stages-1)).

    ``ring_axis``/``sp_impl`` enable sequence parallelism inside each
    stage (ring or ulysses attention over that mesh axis);
    ``moe_experts`` makes every block a top-k routed MoE whose stacked
    expert dim shards over ``sharding_rules(expert_axis=...)``."""

    def __init__(self, vocab_size: int, hidden_size: int = 512,
                 num_layers: int = 8, num_heads: int = 8,
                 ffn_size: Optional[int] = None, max_len: int = 2048,
                 n_microbatches: int = 4, pipe_axis: str = "pipe",
                 mesh: Optional[jax.sharding.Mesh] = None,
                 tie_embeddings: bool = True,
                 ring_axis: Optional[str] = None, sp_impl: str = "ring",
                 moe_experts: int = 0, moe_top_k: int = 2,
                 pp_schedule: str = "gpipe", pp_rounds: int = 2):
        super().__init__()
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = hidden_size // num_heads
        assert hidden_size % num_heads == 0
        self.ffn_size = ffn_size or 4 * hidden_size
        self.max_len = max_len
        self.n_microbatches = n_microbatches
        self.pipe_axis = pipe_axis
        self.mesh = mesh
        self.tie_embeddings = tie_embeddings
        if sp_impl not in ("ring", "ulysses"):
            raise ValueError(f"sp_impl must be ring|ulysses, got {sp_impl}")
        self.ring_axis = ring_axis
        self.sp_impl = sp_impl
        if pp_schedule not in ("gpipe", "interleaved"):
            raise ValueError(
                f"pp_schedule must be gpipe|interleaved, got {pp_schedule}")
        self.pp_schedule = pp_schedule
        self.pp_rounds = pp_rounds
        self.moe_experts = moe_experts
        self.moe_top_k = min(moe_top_k, moe_experts) if moe_experts else 0
        # stable bound-method identity: pipeline_forward's cache keys on
        # the block callable, and `self._block` creates a fresh bound
        # method on every attribute access
        self._block_fn = self._block
        self._block_aux_fn = self._block_aux

    # ------------------------------------------------------------ params
    def init(self, rng):
        dtype = Engine.default_dtype()
        L, E, F = self.num_layers, self.hidden_size, self.ffn_size
        keys = jax.random.split(rng, 10)
        s = 1.0 / math.sqrt(E)
        sf = 1.0 / math.sqrt(F)

        def u(k, shape, scale):
            return jax.random.uniform(k, shape, dtype, -scale, scale)

        blocks = {
            "ln1_scale": jnp.ones((L, E), dtype),
            "ln1_bias": jnp.zeros((L, E), dtype),
            "wq": u(keys[0], (L, E, E), s), "bq": jnp.zeros((L, E), dtype),
            "wk": u(keys[1], (L, E, E), s), "bk": jnp.zeros((L, E), dtype),
            "wv": u(keys[2], (L, E, E), s), "bv": jnp.zeros((L, E), dtype),
            "wo": u(keys[3], (L, E, E), s), "bo": jnp.zeros((L, E), dtype),
            "ln2_scale": jnp.ones((L, E), dtype),
            "ln2_bias": jnp.zeros((L, E), dtype),
        }
        if self.moe_experts:
            X = self.moe_experts
            blocks["router"] = u(keys[9], (L, E, X), s)
            blocks["w_up"] = u(keys[4], (L, X, E, F), s)
            blocks["w_down"] = u(keys[5], (L, X, F, E), sf)
        else:
            blocks["w_up"] = u(keys[4], (L, E, F), s)
            blocks["b_up"] = jnp.zeros((L, F), dtype)
            blocks["w_down"] = u(keys[5], (L, F, E), sf)
            blocks["b_down"] = jnp.zeros((L, E), dtype)
        p = {"embed": jax.random.normal(
                 keys[6], (self.vocab_size, E), dtype) * s,
             "pos_embed": jax.random.normal(
                 keys[7], (self.max_len, E), dtype) * s,
             "ln_f_scale": jnp.ones((E,), dtype),
             "ln_f_bias": jnp.zeros((E,), dtype),
             "blocks": blocks}
        if not self.tie_embeddings:
            p["lm_head"] = jax.random.normal(
                keys[8], (E, self.vocab_size), dtype) * s
        return p

    def initial_state(self):
        if self.moe_experts:
            return {AUX_LOSS_KEY: jnp.zeros((), jnp.float32)}
        return {}

    def aux_loss(self, state) -> jnp.ndarray:
        """Total MoE load-balance loss (mean over microbatches, summed
        over layers) — same contract as TransformerLM.aux_loss."""
        return state.get(AUX_LOSS_KEY, jnp.zeros((), jnp.float32))

    # ------------------------------------------------------- block forward
    def _attention(self, lp, h):
        """Self-attention sublayer; SP-aware: inside the pipeline
        shard_map the ring axis is BOUND and the kernel runs its manual
        collectives directly; in the dense fallback a mesh-resolved
        shard_map wrapper is used; no SP -> plain causal attention."""
        b, s, e = h.shape
        hd, nh = self.head_dim, self.num_heads

        def split(t):
            return t.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)

        x = _layernorm(h, lp["ln1_scale"], lp["ln1_bias"])
        q = split(x @ lp["wq"] + lp["bq"])
        k = split(x @ lp["wk"] + lp["bk"])
        v = split(x @ lp["wv"] + lp["bv"])
        att = None
        if self.ring_axis is not None:
            kern = self._sp_kernel()
            if _inside_axis(self.ring_axis):
                att = kern(q, k, v, axis_name=self.ring_axis, causal=True)
            else:
                from bigdl_tpu.parallel.mesh import (resolve_axis_mesh,
                                                     seq_sharded_attention)
                mesh = resolve_axis_mesh(self.mesh, self.ring_axis)
                if mesh is not None:
                    att = seq_sharded_attention(
                        kern, mesh, self.ring_axis, True)(q, k, v)
        if att is None:
            att = dot_product_attention(q, k, v, causal=True)
        att = att.transpose(0, 2, 1, 3).reshape(b, s, e)
        return h + att @ lp["wo"] + lp["bo"]

    def _sp_kernel(self):
        if self.sp_impl == "ulysses":
            from bigdl_tpu.parallel.ulysses import ulysses_attention
            return ulysses_attention
        from bigdl_tpu.parallel.ring_attention import ring_attention
        return ring_attention

    def _moe(self, lp, x):
        """Top-k routed stacked-expert FFN (one layer's slice; mirrors
        nn/moe.py's dense-dispatch design). Returns (out, aux). The aux
        statistics are pmean-ed over the SP axis when it is bound, so
        shard-local routing stats reproduce the full-sequence ones."""
        X, K = self.moe_experts, self.moe_top_k
        logits = x @ lp["router"]                          # [b,s,X]
        probs = jax.nn.softmax(logits, axis=-1)
        top_p, top_idx = jax.lax.top_k(probs, K)
        top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
        combine = jnp.sum(
            jax.nn.one_hot(top_idx, X, dtype=x.dtype)
            * top_p[..., None], axis=2)
        h = jnp.einsum("bsm,xmf->xbsf", x, lp["w_up"])
        h = jax.nn.gelu(h)
        y = jnp.einsum("xbsf,xfm->xbsm", h, lp["w_down"])
        out = jnp.einsum("xbsm,bsx->bsm", y, combine)
        frac = jnp.mean(jax.nn.one_hot(top_idx[..., 0], X), axis=(0, 1))
        meanp = jnp.mean(probs, axis=(0, 1))
        if self.ring_axis is not None and _inside_axis(self.ring_axis):
            frac = jax.lax.pmean(frac, self.ring_axis)
            meanp = jax.lax.pmean(meanp, self.ring_axis)
        aux = X * jnp.sum(frac * meanp)
        # aux loss is a sanctioned f32 island (summed into the loss)
        return out, aux.astype(jnp.float32)  # bigdl: disable=implicit-upcast-in-trace

    def _block_aux(self, lp, h):
        """One pre-norm transformer block returning (h, aux). lp: this
        layer's slice of the stacked params (leading L dim scanned
        away); h: [mb, S, E]."""
        h = self._attention(lp, h)
        x = _layernorm(h, lp["ln2_scale"], lp["ln2_bias"])
        if self.moe_experts:
            ffn, aux = self._moe(lp, x)
        else:
            ffn = jax.nn.gelu(x @ lp["w_up"] + lp["b_up"]) @ lp["w_down"] \
                + lp["b_down"]
            aux = jnp.zeros((), jnp.float32)
        return h + ffn, aux

    def _block(self, lp, h):
        """aux-less view of :meth:`_block_aux` (the dense-FFN pipeline
        path scans this one)."""
        out, _ = self._block_aux(lp, h)
        return out

    # ------------------------------------------------------------ forward
    def _forward_aux(self, params, input):
        """Shared forward: returns (logits, aux)."""
        from bigdl_tpu.parallel.mesh import resolve_axis_mesh
        tokens = input.astype(jnp.int32)
        b, s = tokens.shape
        x = params["embed"][tokens] + params["pos_embed"][:s][None]
        mesh = resolve_axis_mesh(self.mesh, self.pipe_axis)
        aux = jnp.zeros((), jnp.float32)
        if mesh is not None:
            from jax.sharding import PartitionSpec as P
            from bigdl_tpu.parallel.pipeline import pipeline_forward
            extra, x_spec = (), None
            if self.ring_axis is not None and \
                    resolve_axis_mesh(mesh, self.ring_axis) is not None:
                # SP inside the pipeline: activations' sequence dim is
                # manual over the ring axis so the stage-body kernels
                # run their own collectives ([M, mb, S, E])
                extra = (self.ring_axis,)
                x_spec = P(None, None, self.ring_axis, None)
            sched = dict(schedule=self.pp_schedule,
                         n_rounds=self.pp_rounds)
            if self.moe_experts:
                x, aux = pipeline_forward(
                    self._block_aux_fn, params["blocks"], x, mesh,
                    axis_name=self.pipe_axis,
                    n_microbatches=self.n_microbatches,
                    x_spec=x_spec, extra_axes=extra, with_aux=True,
                    **sched)
            else:
                x = pipeline_forward(
                    self._block_fn, params["blocks"], x, mesh,
                    axis_name=self.pipe_axis,
                    n_microbatches=self.n_microbatches,
                    x_spec=x_spec, extra_axes=extra, **sched)
        elif self.moe_experts:
            # dense fallback, microbatch-looped so the per-microbatch
            # aux statistics (then averaged) match the pipeline exactly
            m = self.n_microbatches if b % self.n_microbatches == 0 else 1
            mb = b // m
            outs, auxs = [], []
            for mi in range(m):
                h = x[mi * mb:(mi + 1) * mb]

                def body(carry, lp):
                    h, a = carry
                    h, ai = self._block_aux(lp, h)
                    return (h, a + ai), None
                (h, a), _ = jax.lax.scan(
                    body, (h, jnp.zeros((), jnp.float32)),
                    params["blocks"])
                outs.append(h)
                auxs.append(a)
            x = jnp.concatenate(outs, axis=0)
            aux = jnp.mean(jnp.stack(auxs))
        else:
            def body(h, lp):
                return self._block(lp, h), None
            x, _ = jax.lax.scan(body, x, params["blocks"])
        x = _layernorm(x, params["ln_f_scale"], params["ln_f_bias"])
        if self.tie_embeddings:
            return x @ params["embed"].T, aux
        return x @ params["lm_head"], aux

    def forward_fn(self, params, input, *, training=False, rng=None):
        logits, _ = self._forward_aux(params, input)
        return logits

    def apply(self, params, state, input, *, training=False, rng=None):
        logits, aux = self._forward_aux(params, input)
        if self.moe_experts:
            return logits, {AUX_LOSS_KEY: aux}
        return logits, {}

    # ------------------------------------------------------------ sharding
    def sharding_rules(self, pipe_axis: Optional[str] = None,
                       model_axis: Optional[str] = None,
                       expert_axis: Optional[str] = None):
        """Rules for ``Optimizer(sharding_rules=...)``: stacked block
        leaves shard their layer dim over the pipe axis, (when a model
        axis is given) megatron column/row TP on the inner dims, and
        stacked MoE experts over the expert axis — the composed
        DP×TP×PP(×EP) layout in one table. Rules are rank-matched, so
        the 4-D MoE leaves pick the expert rule and 3-D dense FFN
        leaves the megatron one."""
        from jax.sharding import PartitionSpec as P
        pa = pipe_axis or self.pipe_axis
        ma = model_axis
        ea = expert_axis or model_axis
        return [
            ("pos_embed", P()),
            (r"(^|/)embed$", P(ma, None) if ma else P()),
            ("lm_head", P(None, ma) if ma else P()),
            (r"blocks/w[qkv]$", P(pa, None, ma)),   # column-parallel
            (r"blocks/b[qkv]$", P(pa, ma)),
            (r"blocks/wo$", P(pa, ma, None)),       # row-parallel
            (r"blocks/bo$", P(pa, None)),
            (r"blocks/router$", P(pa, None, None)),
            # MoE stacked experts [L, X, ., .]: expert dim over EP axis
            (r"blocks/w_up$", P(pa, ea, None, None)),
            (r"blocks/w_down$", P(pa, ea, None, None)),
            # dense FFN [L, ., .] (megatron column/row)
            (r"blocks/w_up$", P(pa, None, ma)),
            (r"blocks/b_up$", P(pa, ma)),
            (r"blocks/w_down$", P(pa, ma, None)),
            (r"blocks/b_down$", P(pa, None)),
            (r"blocks/ln\d_", P(pa, None)),
            ("ln_f", P()),
        ]
