"""Transformer LM — the long-context/distributed flagship family (net-new
capability beyond the reference's RNN LM, models/rnn/SimpleRNN.scala; built
TPU-first so dp/tp/sp/ep shardings are part of the model definition).

``TransformerLM.sharding_rules(model_axis=..., expert_axis=...)`` returns
param-path → PartitionSpec rules (megatron-style: attention QKV
column-parallel, O row-parallel; FFN up column / down row; embeddings
vocab-parallel; MoE experts over the expert axis). Feed them to
``bigdl_tpu.parallel.shard_params`` / ``Optimizer(sharding_rules=...)`` and
XLA inserts the collectives.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.attention import MultiHeadAttention
from bigdl_tpu.nn.moe import MoE
from bigdl_tpu.nn.module import (AUX_LOSS_KEY, Module, adopt_or_init,
                                  adopt_state)
from bigdl_tpu.nn.norm import LayerNorm
from bigdl_tpu.utils.engine import Engine


class FeedForward(Module):
    def __init__(self, hidden_size: int, ffn_size: int,
                 activation: str = "gelu"):
        super().__init__()
        self.hidden_size = hidden_size
        self.ffn_size = ffn_size
        self.activation = activation

    def init(self, rng):
        dtype = Engine.default_dtype()
        k1, k2 = jax.random.split(rng)
        s1 = 1.0 / math.sqrt(self.hidden_size)
        s2 = 1.0 / math.sqrt(self.ffn_size)
        return {"w_up": jax.random.uniform(
                    k1, (self.hidden_size, self.ffn_size), dtype, -s1, s1),
                "b_up": jnp.zeros((self.ffn_size,), dtype),
                "w_down": jax.random.uniform(
                    k2, (self.ffn_size, self.hidden_size), dtype, -s2, s2),
                "b_down": jnp.zeros((self.hidden_size,), dtype)}

    def forward_fn(self, params, input, *, training=False, rng=None):
        act = jax.nn.gelu if self.activation == "gelu" else jax.nn.relu
        h = act(input @ params["w_up"] + params["b_up"])
        return h @ params["w_down"] + params["b_down"]


class TransformerBlock(Module):
    """Pre-norm block: x + MHA(LN(x)); x + FFN/MoE(LN(x))."""

    def __init__(self, hidden_size: int, num_heads: int, ffn_size: int,
                 dropout: float = 0.0, causal: bool = True,
                 ring_axis: Optional[str] = None, sp_impl: str = "ring",
                 mesh=None, moe_experts: int = 0, moe_top_k: int = 2):
        super().__init__()
        self.ln1 = LayerNorm(hidden_size)
        self.attn = MultiHeadAttention(hidden_size, num_heads,
                                       dropout=dropout, causal=causal,
                                       ring_axis=ring_axis,
                                       sp_impl=sp_impl, mesh=mesh)
        self.ln2 = LayerNorm(hidden_size)
        if moe_experts > 0:
            self.mlp = MoE(hidden_size, ffn_size, moe_experts, moe_top_k)
        else:
            self.mlp = FeedForward(hidden_size, ffn_size)
        self.moe_experts = moe_experts

    def init(self, rng):
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        return {"ln1": adopt_or_init(self.ln1, k1),
                "attn": adopt_or_init(self.attn, k2),
                "ln2": adopt_or_init(self.ln2, k3),
                "mlp": adopt_or_init(self.mlp, k4)}

    def initial_state(self):
        return {"mlp": adopt_state(self.mlp)}

    def apply(self, params, state, input, *, training=False, rng=None,
              cache=None, positions=None, attend_len=None, attn_mask=None,
              attn_segments=None):
        r1, r2 = (jax.random.split(rng) if rng is not None else (None, None))
        h = self.ln1.forward_fn(params["ln1"], input)
        if cache is None:
            h = self.attn.forward_fn(params["attn"], h, training=training,
                                     rng=r1, mask=attn_mask,
                                     segments=attn_segments)
        else:
            if attn_mask is not None or attn_segments is not None:
                raise ValueError(
                    "segment masks are not supported on the KV-cached "
                    "decode path (pack training slabs, not decode steps)")
            # incremental decode: the attention writes this block's K/V
            # rows at `positions` and returns the updated cache
            h, cache = self.attn.forward_fn(
                params["attn"], h, training=training, rng=r1,
                cache=cache, positions=positions, attend_len=attend_len)
        x = input + h
        h = self.ln2.forward_fn(params["ln2"], x)
        h, mlp_state = self.mlp.apply(params["mlp"], state.get("mlp", {}), h,
                                      training=training, rng=r2)
        if cache is None:
            return x + h, {"mlp": mlp_state}
        return x + h, {"mlp": mlp_state}, cache


class TransformerLM(Module):
    """Decoder-only LM over int32 token ids [B, S] -> logits [B, S, V].

    Also accepts the PACKED 3-plane input convention the datapipe
    produces (``bigdl_tpu.datapipe.packing``): a list/Table of
    ``[tokens, segment_ids, positions]``, each ``[B, S]`` int — rows
    hold several documents head-to-tail, attention is restricted to
    same-segment (and causal) pairs, and positional embeddings gather
    at the per-document ``positions`` (restarting at 0), so the packed
    forward is per-token exact against running each document alone.
    Segment id 0 marks padding; its logits are garbage by design (mask
    their targets with the criterion's ``ignore_index``)."""

    def __init__(self, vocab_size: int, hidden_size: int = 512,
                 num_layers: int = 6, num_heads: int = 8,
                 ffn_size: Optional[int] = None, max_len: int = 2048,
                 dropout: float = 0.0, ring_axis: Optional[str] = None,
                 sp_impl: str = "ring", mesh=None,
                 moe_experts: int = 0, moe_every: int = 2,
                 tie_embeddings: bool = True):
        super().__init__()
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.ffn_size = ffn_size or 4 * hidden_size
        self.max_len = max_len
        self.dropout = dropout
        self.ring_axis = ring_axis
        self.moe_experts = moe_experts
        self.tie_embeddings = tie_embeddings
        self.blocks = [
            TransformerBlock(
                hidden_size, num_heads, self.ffn_size, dropout=dropout,
                causal=True, ring_axis=ring_axis, sp_impl=sp_impl,
                mesh=mesh,
                moe_experts=(moe_experts if moe_experts
                             and (i % moe_every == moe_every - 1) else 0))
            for i in range(num_layers)]
        self.ln_f = LayerNorm(hidden_size)

    def init(self, rng):
        dtype = Engine.default_dtype()
        keys = jax.random.split(rng, self.num_layers + 4)
        s = 1.0 / math.sqrt(self.hidden_size)
        p = {"embed": jax.random.normal(
                 keys[0], (self.vocab_size, self.hidden_size), dtype) * s,
             "pos_embed": jax.random.normal(
                 keys[1], (self.max_len, self.hidden_size), dtype) * s,
             "ln_f": adopt_or_init(self.ln_f, keys[2])}
        for i, blk in enumerate(self.blocks):
            p[f"block_{i}"] = adopt_or_init(blk, keys[3 + i])
        if not self.tie_embeddings:
            p["lm_head"] = jax.random.normal(
                keys[-1], (self.hidden_size, self.vocab_size), dtype) * s
        return p

    def initial_state(self):
        return {f"block_{i}": adopt_state(blk)
                for i, blk in enumerate(self.blocks)}

    def apply(self, params, state, input, *, training=False, rng=None,
              cache=None, positions=None, attend_len=None):
        from bigdl_tpu.utils.table import Table
        seg = None
        packed_pos = None
        if isinstance(input, Table):
            input = [input[i] for i in range(1, input.length() + 1)]
        if isinstance(input, (list, tuple)):
            if len(input) != 3:
                raise ValueError(
                    "packed TransformerLM input must be [tokens, "
                    f"segment_ids, positions]; got {len(input)} planes")
            if cache is not None:
                raise ValueError(
                    "packed 3-plane input is a training/scoring layout; "
                    "the KV-cached decode path takes plain token ids")
            tokens, segment_ids, packed_pos = input
            # same-document attention only: the raw [B, S] plane rides
            # down as attn_segments — nn.attention derives the
            # [B, 1, Sq, Sk] equality mask for the einsum path (one
            # derivation site) and hands the plane itself to the
            # pallas flash kernel when enabled
            seg = segment_ids.astype(jnp.int32)
            tokens = tokens.astype(jnp.int32)
        else:
            tokens = input.astype(jnp.int32)
        b, s = tokens.shape
        if cache is None:
            if packed_pos is None:
                x = params["embed"][tokens] + params["pos_embed"][:s][None]
            else:
                # per-document positions (restart at 0 per segment) so a
                # packed document sees the same positional embeddings it
                # would alone in a row
                idx = jnp.clip(packed_pos.astype(jnp.int32), 0,
                               self.max_len - 1)
                x = params["embed"][tokens] + params["pos_embed"][idx]
        else:
            # incremental decode: row b's S tokens sit at absolute
            # positions positions[b] .. positions[b]+S-1 (clip keeps a
            # free-slot row's garbage offset from faulting the gather;
            # its output is never read)
            idx = jnp.clip(
                positions.astype(jnp.int32)[:, None] + jnp.arange(s)[None],
                0, self.max_len - 1)
            x = params["embed"][tokens] + params["pos_embed"][idx]
        keys = (jax.random.split(rng, self.num_layers)
                if rng is not None else [None] * self.num_layers)
        new_state = {}
        for i, blk in enumerate(self.blocks):
            if cache is None:
                # attn_segments only rides along for packed inputs:
                # the plain path keeps the bare apply signature
                # (shapecheck interceptors and custom blocks see no
                # new kwarg). The raw segment-id plane travels instead
                # of a prebuilt [B,1,S,S] mask — nn.attention derives
                # the equality mask for the einsum path and feeds the
                # plane to the pallas flash kernel when enabled.
                mask_kw = {} if seg is None \
                    else {"attn_segments": seg}
                x, st = blk.apply(params[f"block_{i}"],
                                  state.get(f"block_{i}", {}), x,
                                  training=training, rng=keys[i],
                                  **mask_kw)
            else:
                x, st, layer_cache = blk.apply(
                    params[f"block_{i}"], state.get(f"block_{i}", {}), x,
                    training=training, rng=keys[i],
                    cache={"k": cache["k"][i], "v": cache["v"][i]},
                    positions=positions, attend_len=attend_len)
                cache = {"k": cache["k"].at[i].set(layer_cache["k"]),
                         "v": cache["v"].at[i].set(layer_cache["v"])}
            new_state[f"block_{i}"] = st
        x = self.ln_f.forward_fn(params["ln_f"], x)
        if self.tie_embeddings:
            logits = x @ params["embed"].T
        else:
            logits = x @ params["lm_head"]
        if cache is None:
            return logits, new_state
        return logits, new_state, cache

    def aux_loss(self, state) -> jnp.ndarray:
        """Total MoE load-balance loss across blocks."""
        total = jnp.zeros((), jnp.float32)
        for st in state.values():
            mlp = st.get("mlp", {}) if isinstance(st, dict) else {}
            if AUX_LOSS_KEY in mlp:
                total = total + mlp[AUX_LOSS_KEY]
        return total

    # ---- sharding (megatron-style rules consumed by parallel.shard_params)
    def sharding_rules(self, model_axis: str = "model",
                       expert_axis: Optional[str] = None):
        from jax.sharding import PartitionSpec as P
        e_ax = expert_axis or model_axis
        # matched in order by parallel.shard_params; a rule only applies
        # when its spec rank matches the leaf rank, so the 3-D stacked
        # expert weights pick the expert-parallel rule and the 2-D dense
        # FFN weights the megatron one.
        return [
            # pos_embed before embed: spec_for uses re.search and an
            # unanchored "embed" would swallow "pos_embed"
            ("pos_embed", P()),
            (r"(^|/)embed$", P(model_axis, None)),   # vocab-parallel
            ("lm_head", P(None, model_axis)),
            (r"block_\d+/attn/w[qkv]", P(None, model_axis)),  # column
            (r"block_\d+/attn/b[qkv]", P(model_axis)),
            (r"block_\d+/attn/wo", P(model_axis, None)),      # row
            (r"block_\d+/attn/bo", P()),
            # MoE stacked experts [E, ., .]: shard the expert dim (EP)
            (r"block_\d+/mlp/w_up", P(e_ax, None, None)),
            (r"block_\d+/mlp/w_down", P(e_ax, None, None)),
            # dense FFN (megatron column/row)
            (r"block_\d+/mlp/w_up", P(None, model_axis)),
            (r"block_\d+/mlp/b_up", P(model_axis)),
            (r"block_\d+/mlp/w_down", P(model_axis, None)),
            (r"block_\d+/mlp/b_down", P()),
            (r"block_\d+/mlp/router", P()),
            (r"block_\d+/ln\d", P()),
            ("ln_f", P()),
        ]
