"""TransformerLM flagship training recipe — the one-call surface for
every parallelism the framework has (the role DistriOptimizer.scala:728
played for the reference: parallel training behind Optimizer.optimize()).

Parallelism is CONFIG, not code:

    # single chip
    python -m bigdl_tpu.models.transformer.train --synthetic 20000 -e 1
    # 2-way pipeline x 2-way tensor x data parallel on the rest
    python -m bigdl_tpu.models.transformer.train --synthetic 20000 \
        --pp 2 --tp 2
    # ring-attention sequence parallelism for long context
    python -m bigdl_tpu.models.transformer.train --synthetic 20000 \
        --sp ring --spSize 4 --seqLen 2048
    # Ulysses all-to-all SP instead of ring
    python -m bigdl_tpu.models.transformer.train ... --sp ulysses
    # the full product: pipeline x tensor x sequence x expert x data
    python -m bigdl_tpu.models.transformer.train --synthetic 20000 \
        --pp 2 --tp 2 --sp ring --spSize 2 --moeExperts 4

Corpus input mirrors the RNN recipe (models/rnn/Train.scala:60-133):
``-f dir`` reads ``train.txt`` through the PTB tokenizer/Dictionary.
"""
from __future__ import annotations

import os


def build_mesh_for(pp: int, tp: int, sp_size: int):
    """Carve the available devices into (data[, pipe][, model][, seq]).

    Data parallelism absorbs whatever is left: dp = n // (pp*tp*sp).
    Returns (mesh, axes_present) — mesh is None on a single device with
    no parallelism requested.
    """
    import jax

    from bigdl_tpu.parallel import make_mesh

    n = len(jax.devices())
    need = pp * tp * sp_size
    if n % need:
        raise ValueError(
            f"device count {n} not divisible by pp*tp*spSize={need}")
    dp = n // need
    sizes, names = [dp], ["data"]
    if pp > 1:
        sizes.append(pp)
        names.append("pipe")
    if tp > 1:
        sizes.append(tp)
        names.append("model")
    if sp_size > 1:
        sizes.append(sp_size)
        names.append("seq")
    if sizes == [1]:
        return None, names
    return make_mesh(sizes, names, jax.devices()[:n]), names


def _split_documents(stream, eos_index):
    """1-based token stream -> list of 0-based int32 documents split at
    ``eos_index`` (each document keeps its trailing <eos>) — the
    variable-length view the packing/bucketing input modes consume."""
    import numpy as np

    s = np.asarray(stream).astype(np.int64)
    docs, lo = [], 0
    ends = np.flatnonzero(s == eos_index)
    for e in ends:
        doc = s[lo:e + 1]
        if len(doc) >= 2:
            docs.append((doc - 1).astype(np.int32))
        lo = e + 1
    tail = s[lo:]
    if len(tail) >= 2:
        docs.append((tail - 1).astype(np.int32))
    return docs


def _packed_corpus(args, stream, eos_index):
    """The packing-path replacement for the contiguous ``ptb_arrays``
    layout: documents packed into ``[rows, seqLen]`` slabs with segment
    masks (``--inputMode packed``) or padded one-per-row to the seqLen
    bound (``--inputMode padded``). Prints the padding efficiency both
    layouts would achieve, and leaves the gauge at the chosen one."""
    from bigdl_tpu import datapipe as dp

    docs = _split_documents(stream, eos_index)
    if not docs:
        raise SystemExit("corpus has no documents after <eos> splitting")
    lengths = [min(len(d) - 1, args.seqLen) for d in docs]
    eff_padded = dp.padding_efficiency(lengths, args.seqLen)
    if args.inputMode == "padded":
        batcher = dp.LengthBucketBatcher([args.seqLen], len(docs))
        (mb,) = list(batcher(iter(docs), 0))
        toks, segs, pos = mb.input
        tgt = mb.target
        eff = batcher.efficiency
    else:
        toks, segs, pos, tgt = dp.pack_documents(docs, args.seqLen)
        eff = float((segs > 0).mean())
    print(f"input mode {args.inputMode}: padding_efficiency {eff:.3f} "
          f"(pad-to-max would be {eff_padded:.3f}) over {len(docs)} "
          f"documents, {len(toks)} rows of {args.seqLen}")
    return [toks, segs, pos], tgt


def _corpus(args):
    """(x, y) int32 0-based token windows [N, seqLen] + vocab size."""
    import numpy as np

    from bigdl_tpu.dataset import load_ptb, ptb_arrays

    if args.synthetic:
        rng = np.random.RandomState(0)
        stream = rng.randint(1, args.vocabSize + 1,
                             args.synthetic).astype(np.float32)
        vocab = args.vocabSize
        if args.inputMode != "contiguous":
            # ragged synthetic documents: mark seeded pseudo-<eos>
            # boundaries so the packed path has real length variance
            eos = args.vocabSize
            cuts = rng.randint(8, max(9, args.seqLen // 2),
                               max(1, args.synthetic // 16))
            pos = np.minimum(np.cumsum(cuts), args.synthetic - 1)
            stream[pos] = eos
            return _packed_corpus(args, stream, eos) + (vocab,)
    else:
        train_txt = args.folder if os.path.isfile(args.folder) else \
            os.path.join(args.folder, "train.txt")
        if not os.path.exists(train_txt):
            from bigdl_tpu.dataset import fetch
            try:
                train_txt = fetch.get_text_corpus(args.folder)
            except Exception as e:
                raise SystemExit(
                    f"no corpus at '{train_txt}' and auto-download "
                    f"failed ({type(e).__name__}: {e}). Pre-stage a "
                    "train.txt there, or use --synthetic N.")
        splits, d = load_ptb(train_txt, vocab_size=args.vocabSize)
        stream, vocab = splits["train"], d.vocab_size()
        if args.checkpoint:
            os.makedirs(args.checkpoint, exist_ok=True)
            d.save(os.path.join(args.checkpoint, "dictionary.json"))
        if args.inputMode != "contiguous":
            return _packed_corpus(args, stream,
                                  d.get_index("<eos>")) + (vocab,)
    bs = args.batchSize or 8
    x, y = ptb_arrays(stream, bs, args.seqLen)
    # ptb_arrays is 1-based (the torch convention); LM criterion wants
    # 0-based vocabulary ids
    return (x - 1).astype(np.int32), (y - 1).astype(np.int32), vocab


def main(argv=None):
    from bigdl_tpu.models._cli import (arrays_to_dataset, base_parser,
                                       load_model_or, wire_optimizer)

    ap = base_parser("Train the Transformer language model")
    ap.add_argument("--vocabSize", type=int, default=4000)
    ap.add_argument("--hiddenSize", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--seqLen", type=int, default=128)
    ap.add_argument("--inputMode",
                    choices=("contiguous", "packed", "padded"),
                    default="contiguous",
                    help="text layout: 'contiguous' = the classic "
                    "ptb_arrays stream windows; 'packed' = documents "
                    "packed into [B, seqLen] slabs with segment masks "
                    "(datapipe.packing — no pad FLOPs); 'padded' = one "
                    "document per row padded to seqLen (the before "
                    "number for the padding-efficiency gauge)")
    ap.add_argument("--dropout", type=float, default=0.0)
    ap.add_argument("--moeExperts", type=int, default=0)
    ap.add_argument("--pp", type=int, default=1,
                    help="pipeline stages (PipelinedTransformerLM)")
    ap.add_argument("--microbatches", type=int, default=0,
                    help="pipeline microbatches (default: 2*pp)")
    ap.add_argument("--ppSchedule", choices=("gpipe", "interleaved"),
                    default="gpipe",
                    help="pipeline schedule (interleaved shrinks the "
                    "bubble by --ppRounds virtual stages)")
    ap.add_argument("--ppRounds", type=int, default=2,
                    help="virtual chunks per stage for interleaved")
    ap.add_argument("--tp", type=int, default=1,
                    help="megatron tensor-parallel degree")
    ap.add_argument("--sp", choices=("none", "ring", "ulysses"),
                    default="none", help="sequence parallelism kernel")
    ap.add_argument("--spSize", type=int, default=1,
                    help="sequence-parallel degree (mesh 'seq' axis)")
    args = ap.parse_args(argv)

    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import PipelinedTransformerLM, TransformerLM
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import Optimizer

    if args.sp != "none" and args.spSize < 2:
        args.spSize = 2
    if args.pp > 1 and args.dropout:
        raise ValueError(
            "--pp does not support dropout (per-microbatch rng through "
            "the pipeline ring would tie the objective to the stage "
            "count); use the non-pipelined TransformerLM for dropout")
    if args.inputMode != "contiguous" and (args.pp > 1
                                           or args.sp != "none"):
        raise ValueError(
            "--inputMode packed/padded needs the dense TransformerLM "
            "(segment masks are unsupported on the pipelined and "
            "sequence-parallel paths)")

    x, y, vocab = _corpus(args)
    bs = args.batchSize or 8
    if isinstance(x, list):
        # packed/padded 3-plane layout: Samples carry [tokens,
        # segment_ids, positions]; pad/boundary targets are -1 and the
        # criterion must ignore them
        from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
        samples = [Sample([plane[i] for plane in x], y[i])
                   for i in range(len(x[0]))]
        ds = DataSet.array(samples).transform(SampleToMiniBatch(bs))
        criterion = nn.SequenceCrossEntropyCriterion(ignore_index=-1)
    else:
        ds = arrays_to_dataset(x, y, bs)
        criterion = nn.SequenceCrossEntropyCriterion()

    mesh, _ = build_mesh_for(args.pp, args.tp,
                             args.spSize if args.sp != "none" else 1)
    rules = None
    if args.pp > 1:
        mb = args.microbatches or 2 * args.pp
        build = lambda: PipelinedTransformerLM(
            vocab, hidden_size=args.hiddenSize, num_layers=args.layers,
            num_heads=args.heads, max_len=args.seqLen,
            n_microbatches=mb, mesh=mesh,
            ring_axis="seq" if args.sp != "none" else None,
            sp_impl=args.sp if args.sp != "none" else "ring",
            moe_experts=args.moeExperts,
            pp_schedule=args.ppSchedule, pp_rounds=args.ppRounds)
        model = load_model_or(args, build)
        # snapshots strip the mesh (runtime placement, not identity) —
        # reattach or a resumed run would silently fall back to the
        # dense path while the CLI still promises --pp
        model.mesh = mesh
        rules = model.sharding_rules(
            model_axis="model" if args.tp > 1 else None,
            expert_axis="model" if (args.tp > 1 and args.moeExperts)
            else None)
    else:
        build = lambda: TransformerLM(
            vocab, hidden_size=args.hiddenSize, num_layers=args.layers,
            num_heads=args.heads, max_len=args.seqLen,
            dropout=args.dropout,
            ring_axis="seq" if args.sp != "none" else None,
            sp_impl=args.sp if args.sp != "none" else "ring",
            mesh=mesh, moe_experts=args.moeExperts)
        model = load_model_or(args, build)
        # snapshots strip runtime placement; SP lives in the attention
        # modules — reattach so a resumed run keeps its parallelism
        for blk in model.blocks:
            blk.attn.mesh = mesh
        if args.tp > 1:
            rules = model.sharding_rules(model_axis="model")

    optim = SGD(learning_rate=args.learningRate or 0.1,
                learning_rate_decay=args.learningRateDecay or 0.0)
    opt = Optimizer(model, ds, criterion,
                    batch_size=bs, mesh=mesh, sharding_rules=rules)
    wire_optimizer(opt, args, optim, default_epochs=1)
    opt.optimize()
    loss = opt.driver_state["Loss"]
    print(f"final loss: {loss:.4f} perplexity: {np.exp(loss):.2f}")
    return model


if __name__ == "__main__":
    main()
