"""TransformerLM evaluation — true per-token perplexity over a held-out
corpus (the transformer counterpart of models/rnn/Test.scala:55-90's
evaluate branch; same dictionary-reload contract as the RNN test main).

    python -m bigdl_tpu.models.transformer.test -f dir --model snap
    python -m bigdl_tpu.models.transformer.test --synthetic 5000
"""
from __future__ import annotations

import os


def main(argv=None):
    from bigdl_tpu.models._cli import base_parser

    ap = base_parser("Evaluate the Transformer language model")
    ap.add_argument("--vocabSize", type=int, default=4000)
    ap.add_argument("--hiddenSize", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--seqLen", type=int, default=128)
    ap.add_argument("--dictionary", default=None,
                    help="dictionary.json saved by the train main")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.dataset import (Dictionary, load_ptb, ptb_arrays,
                                   read_words)
    from bigdl_tpu.models import TransformerLM

    if args.synthetic:
        rng = np.random.RandomState(1)
        stream = rng.randint(1, args.vocabSize + 1,
                             args.synthetic).astype(np.float32)
        vocab = args.vocabSize
    else:
        test_txt = args.folder if os.path.isfile(args.folder) else \
            os.path.join(args.folder, "test.txt")
        dict_path = args.dictionary or os.path.join(
            os.path.dirname(test_txt), "dictionary.json")
        if os.path.exists(dict_path):
            d = Dictionary.load(dict_path)
            stream = np.asarray(
                [d.get_index(w) for w in read_words(test_txt)], np.float32)
            vocab = d.vocab_size()
        else:
            splits, d = load_ptb(test_txt, vocab_size=args.vocabSize)
            stream, vocab = splits["train"], d.vocab_size()

    if args.model:
        from bigdl_tpu.utils.serialization import load_module
        model = load_module(args.model)
    else:
        model = TransformerLM(vocab, hidden_size=args.hiddenSize,
                              num_layers=args.layers,
                              num_heads=args.heads, max_len=args.seqLen)
    model.evaluate()
    model.ensure_initialized()

    bs = args.batchSize or 8
    x, y = ptb_arrays(stream, bs, args.seqLen)
    x, y = (x - 1).astype(np.int32), (y - 1).astype(np.int32)
    params, state = model.get_parameters(), model.get_state()

    @jax.jit
    def nll_sum(toks, tgts):
        logits, _ = model.apply(params, state, toks)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, tgts[..., None].astype(jnp.int32), axis=-1,
            mode="clip")[..., 0]
        return jnp.sum(nll)

    total, count = 0.0, 0
    for i in range(0, len(x), bs):
        xb, yb = x[i:i + bs], y[i:i + bs]
        if len(xb) < bs:
            break  # static shapes: drop the ragged tail
        total += float(nll_sum(xb, yb))
        count += xb.size
    ppl = np.exp(total / max(count, 1))
    print(f"tokens: {count} avg nll: {total / max(count, 1):.4f} "
          f"perplexity: {ppl:.2f}")
    return ppl


if __name__ == "__main__":
    main()
