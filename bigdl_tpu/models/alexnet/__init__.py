"""AlexNet (reference: example/loadmodel/AlexNet.scala — the Caffe-era
model the load-model example and DistriOptimizerPerf benchmark use).

``AlexNet`` is the original ILSVRC-2012 form (LRN + grouped convs);
``AlexNet_OWT`` is the one-weird-trick variant (no LRN, no groups)."""
from __future__ import annotations

import bigdl_tpu.nn as nn


def AlexNet_OWT(class_num: int = 1000, has_dropout: bool = True,
                first_layer_propagate_back: bool = False) -> nn.Sequential:
    """AlexNet.scala:23-50 (AlexNet_OWT)."""
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(
        3, 64, 11, 11, 4, 4, 2, 2, 1,
        propagate_back=first_layer_propagate_back).set_name("conv1"))
    m.add(nn.ReLU(True).set_name("relu1"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool1"))
    m.add(nn.SpatialConvolution(64, 192, 5, 5, 1, 1, 2, 2).set_name("conv2"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool2"))
    m.add(nn.SpatialConvolution(192, 384, 3, 3, 1, 1, 1, 1)
          .set_name("conv3"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1)
          .set_name("conv4"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialConvolution(256, 256, 3, 3, 1, 1, 1, 1)
          .set_name("conv5"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool5"))
    m.add(nn.View(256 * 6 * 6).set_num_input_dims(3))
    m.add(nn.Linear(256 * 6 * 6, 4096).set_name("fc6"))
    m.add(nn.ReLU(True))
    if has_dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, 4096).set_name("fc7"))
    m.add(nn.ReLU(True))
    if has_dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, class_num).set_name("fc8"))
    m.add(nn.LogSoftMax())
    return m


def AlexNet(class_num: int = 1000, has_dropout: bool = True
            ) -> nn.Sequential:
    """AlexNet.scala:84-112: the original form with cross-map LRN and
    2-group convs (the dual-GPU split baked into the weights)."""
    m = nn.Sequential()
    m.add(nn.SpatialConvolution(3, 96, 11, 11, 4, 4, 0, 0, 1,
                                propagate_back=False).set_name("conv1"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm1"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool1"))
    m.add(nn.SpatialConvolution(96, 256, 5, 5, 1, 1, 2, 2, 2)
          .set_name("conv2"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75).set_name("norm2"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool2"))
    m.add(nn.SpatialConvolution(256, 384, 3, 3, 1, 1, 1, 1)
          .set_name("conv3"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialConvolution(384, 384, 3, 3, 1, 1, 1, 1, 2)
          .set_name("conv4"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, 2)
          .set_name("conv5"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).set_name("pool5"))
    m.add(nn.View(256 * 6 * 6).set_num_input_dims(3))
    m.add(nn.Linear(256 * 6 * 6, 4096).set_name("fc6"))
    m.add(nn.ReLU(True))
    if has_dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, 4096).set_name("fc7"))
    m.add(nn.ReLU(True))
    if has_dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, class_num).set_name("fc8"))
    m.add(nn.LogSoftMax())
    return m
