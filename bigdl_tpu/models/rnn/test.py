"""RNN LM evaluation + generation (models/rnn/Test.scala:46-137).

Evaluate mode scores ``test.txt`` (or a synthetic id stream) with
Loss(TimeDistributedCriterion(CrossEntropy)) — the reference's evaluate
branch (Test.scala:55-90) — and prints perplexity. With ``--numOfWords``
it instead completes sentences by iteratively feeding back the argmax
prediction (Test.scala:91-137). The training vocabulary saved by the
train main (``dictionary.json``) is reloaded so words map to the same
indices the snapshot was trained with (Test.scala:52 ``Dictionary(
param.folder)``).

    python -m bigdl_tpu.models.rnn.test -f dir_with_test.txt --model snap
    python -m bigdl_tpu.models.rnn.test --synthetic 800 --numOfWords 5
"""
from __future__ import annotations

import os


def _test_stream(args):
    """Token-id stream + vocab size for the eval corpus. Prefers the
    dictionary persisted at training time over rebuilding one from the
    test file (which would scramble the word->index map)."""
    import numpy as np

    from bigdl_tpu.dataset import Dictionary, load_ptb, read_words

    if args.synthetic:
        rng = np.random.RandomState(1)
        return rng.randint(1, args.vocabSize + 1,
                           args.synthetic).astype(np.float32), args.vocabSize

    test_txt = args.folder if os.path.isfile(args.folder) else \
        os.path.join(args.folder, "test.txt")
    dict_path = args.dictionary or os.path.join(
        os.path.dirname(test_txt), "dictionary.json")
    if os.path.exists(dict_path):
        d = Dictionary.load(dict_path)
        stream = np.asarray([d.get_index(w) for w in read_words(test_txt)],
                            np.float32)
        return stream, d.vocab_size()
    splits, d = load_ptb(test_txt, vocab_size=args.vocabSize)
    return splits["train"], d.vocab_size()


def main(argv=None):
    from bigdl_tpu.models._cli import (arrays_to_dataset, base_parser,
                                       load_model_or)

    ap = base_parser("Test the RNN language model")
    ap.add_argument("--vocabSize", type=int, default=4000)
    ap.add_argument("--hiddenSize", type=int, default=40)
    ap.add_argument("--numSteps", type=int, default=20)
    ap.add_argument("--dictionary", default=None,
                    help="dictionary.json saved by the train main")
    ap.add_argument("--numOfWords", type=int, default=None,
                    help="generate this many words per seed sentence "
                         "instead of evaluating loss")
    args = ap.parse_args(argv)

    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import ptb_arrays
    from bigdl_tpu.models.rnn import WordRNN
    from bigdl_tpu.optim import Evaluator, Loss

    bs = args.batchSize or 8
    stream, vocab = _test_stream(args)
    x, y = ptb_arrays(stream, bs, args.numSteps)

    model = load_model_or(
        args, lambda: WordRNN(vocab, args.hiddenSize)).evaluate()
    if args.quantize:
        model = model.quantize()

    if args.numOfWords:
        # generation branch: feed back the last-step argmax N times
        cur = x[:bs].astype(np.float32)
        for _ in range(args.numOfWords):
            out = np.asarray(model.forward(cur))
            nxt = out[:, -1].argmax(-1).astype(np.float32) + 1.0
            cur = np.concatenate([cur, nxt[:, None]], axis=1)
        for row in cur[:4]:
            print(" ".join(str(int(t)) for t in row))
        return cur

    ds = arrays_to_dataset(x, y, bs)
    crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
    results = Evaluator(model).test(ds, [Loss(crit)], batch_size=bs)
    for name, r in results.items():
        print(f"{name}: {r}")
    loss = results["Loss"].result()[0]
    print(f"perplexity: {np.exp(loss):.2f}")
    return results


if __name__ == "__main__":
    main()
