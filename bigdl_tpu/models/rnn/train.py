"""RNN LM training recipe (models/rnn/Train.scala:60-133 — tokenize with
SentenceTokenizer, Dictionary(vocab 4000), SGD lr 0.1, TimeDistributed
CrossEntropy; BASELINE config 5 via the PTB path).

    python -m bigdl_tpu.models.rnn.train -f dir_with_train.txt
    python -m bigdl_tpu.models.rnn.train --synthetic 2000 -e 2
"""
from __future__ import annotations

import os


def main(argv=None):
    from bigdl_tpu.models._cli import (arrays_to_dataset, base_parser,
                                       load_model_or, wire_optimizer)

    ap = base_parser("Train the RNN language model")
    ap.add_argument("--vocabSize", type=int, default=4000)
    ap.add_argument("--hiddenSize", type=int, default=40)
    ap.add_argument("--numSteps", type=int, default=20)
    ap.add_argument("--ptb", action="store_true",
                    help="use the stacked-LSTM PTBModel instead of "
                         "SimpleRNN")
    ap.add_argument("--momentum", type=float, default=0.0)
    ap.add_argument("--weightDecay", type=float, default=0.0)
    args = ap.parse_args(argv)

    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.dataset import load_ptb, ptb_arrays
    from bigdl_tpu.models.rnn import PTBModel, WordRNN
    from bigdl_tpu.optim import LocalOptimizer, SGD

    bs = args.batchSize or 32
    if args.synthetic:
        rng = np.random.RandomState(0)
        stream = rng.randint(1, args.vocabSize + 1,
                             args.synthetic).astype(np.float32)
        vocab = args.vocabSize
    else:
        train_txt = args.folder if os.path.isfile(args.folder) else \
            os.path.join(args.folder, "train.txt")
        if not os.path.exists(train_txt):
            # recipes run from nothing on a networked host (the
            # reference's readme download step, Train.scala:60-133)
            from bigdl_tpu.dataset import fetch
            try:
                train_txt = fetch.get_text_corpus(args.folder)
            except Exception as e:
                raise SystemExit(
                    f"no corpus at '{train_txt}' and auto-download "
                    f"failed ({type(e).__name__}: {e}). Pre-stage a "
                    "train.txt there, or use --synthetic N.")
        splits, d = load_ptb(train_txt, vocab_size=args.vocabSize)
        stream, vocab = splits["train"], d.vocab_size()
        if args.checkpoint:
            # persist the training vocabulary so the test main scores
            # with the same word->index map (Train.scala:90 vocab.save)
            os.makedirs(args.checkpoint, exist_ok=True)
            d.save(os.path.join(args.checkpoint, "dictionary.json"))
    x, y = ptb_arrays(stream, bs, args.numSteps)
    ds = arrays_to_dataset(x, y, bs)

    if args.ptb:
        build = lambda: PTBModel(vocab, args.hiddenSize, vocab)
    else:
        build = lambda: WordRNN(vocab, args.hiddenSize)
    model = load_model_or(args, build)
    optim = SGD(learning_rate=args.learningRate or 0.1,
                learning_rate_decay=args.learningRateDecay or 0.0,
                weight_decay=args.weightDecay, momentum=args.momentum)
    crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
    opt = LocalOptimizer(model, ds, crit, batch_size=bs)
    wire_optimizer(opt, args, optim, default_epochs=2)
    opt.optimize()
    loss = opt.driver_state["Loss"]
    print(f"final loss: {loss:.4f} perplexity: {np.exp(loss):.2f}")
    return model


if __name__ == "__main__":
    main()
