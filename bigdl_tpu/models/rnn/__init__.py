"""RNN language models (reference: models/rnn/SimpleRNN.scala and
example/languagemodel/PTBModel.scala; BASELINE config 5)."""
from __future__ import annotations

import bigdl_tpu.nn as nn


def SimpleRNN(input_size: int, hidden_size: int, output_size: int
              ) -> nn.Sequential:
    """SimpleRNN.scala:22-34: Recurrent(RnnCell) + TimeDistributed(Linear)."""
    m = nn.Sequential()
    m.add(nn.Recurrent(nn.RnnCell(input_size, hidden_size, nn.Tanh())))
    m.add(nn.TimeDistributed(nn.Linear(hidden_size, output_size)))
    return m


def WordRNN(vocab_size: int, hidden_size: int) -> nn.Sequential:
    """The train/test recipes' model (Train.scala:104-110): embedding
    front + the SimpleRNN body, shared so both mains build the exact
    same architecture."""
    m = nn.Sequential()
    m.add(nn.LookupTable(vocab_size, hidden_size))
    m.add(nn.Recurrent(nn.RnnCell(hidden_size, hidden_size, nn.Tanh())))
    m.add(nn.TimeDistributed(nn.Linear(hidden_size, vocab_size)))
    return m


def PTBModel(input_size: int, hidden_size: int, output_size: int,
             num_layers: int = 2, keep_prob: float = 2.0) -> nn.Sequential:
    """PTBModel.scala:23-45: embedding -> (dropout) -> stacked LSTM ->
    TimeDistributed(Linear). Built as a Sequential (the traced graph is
    identical to the reference's Graph form)."""
    m = nn.Sequential()
    m.add(nn.LookupTable(input_size, hidden_size))
    if keep_prob < 1:
        m.add(nn.Dropout(keep_prob))
    for _ in range(num_layers):
        m.add(nn.Recurrent(nn.LSTM(hidden_size, hidden_size, 0.0)))
    m.add(nn.TimeDistributed(nn.Linear(hidden_size, output_size)))
    return m
