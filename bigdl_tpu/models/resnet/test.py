"""ResNet CIFAR-10 evaluation (models/resnet/Test.scala)."""
from __future__ import annotations


def main(argv=None):
    from bigdl_tpu.models._cli import (base_parser, cifar10_arrays,
                                       evaluate_cli)

    ap = base_parser("Test ResNet on CIFAR-10")
    ap.add_argument("--depth", type=int, default=20)
    args = ap.parse_args(argv)
    from bigdl_tpu.models.resnet import ResNet
    return evaluate_cli(
        args, lambda: ResNet(10, depth=args.depth, dataset="CIFAR10"),
        cifar10_arrays(args.folder, False, args.synthetic))


if __name__ == "__main__":
    main()
