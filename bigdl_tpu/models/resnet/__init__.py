"""ResNet for CIFAR-10 / ImageNet (reference: models/resnet/ResNet.scala:133).

Supports depths 20/32/44/56/110 (CIFAR) and 18/34/50/101/152/200 (ImageNet),
shortcut types A/B/C, MSRA init (ResNet.modelInit, ResNet.scala:103-131).
The reference's optnet buffer sharing (shareGradInput) is XLA's job here.
"""
from __future__ import annotations

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.initialization import MsraFiller, Zeros, Ones


class ShortcutType:
    A = "A"
    B = "B"
    C = "C"


class DatasetType:
    CIFAR10 = "CIFAR10"
    ImageNet = "ImageNet"


def _conv(cin, cout, kw, kh, sw=1, sh=1, pw=0, ph=0, propagate_back=True,
          with_bias=False):
    """MSRA-init conv (ResNet.modelInit). Every conv here feeds a
    BatchNormalization, which subtracts the per-channel mean — any conv
    bias cancels EXACTLY, so the output and every gradient except the
    bias's own (identically zero) are unchanged without it. Dropping the
    bias removes ~50 full activation-gradient reduces from the backward
    pass: measured +7.7% step throughput on v5e (2330->2511 img/s),
    closing the gap to the hand-rolled device ceiling. fb.resnet (the
    reference's upstream Torch source) ships the same :noBias();
    ``ResNet(conv_bias=True)`` restores the reference's exact parameter
    set (ResNet.scala:36 Convolution keeps bias)."""
    c = nn.SpatialConvolution(cin, cout, kw, kh, sw, sh, pw, ph,
                              propagate_back=propagate_back,
                              with_bias=with_bias)
    c.set_init_method(MsraFiller(var_in_count=False), Zeros())
    return c


def _bn(n):
    # modelInit: gamma=1, beta=0 (ResNet.scala:120-124)
    return nn.SpatialBatchNormalization(n, init_weight=Ones(),
                                        init_bias=Zeros())


class _State:
    def __init__(self):
        self.i_channels = 0


def ResNet(class_num: int, depth: int = 18,
           shortcut_type: str = ShortcutType.B,
           dataset: str = DatasetType.CIFAR10,
           conv_bias: bool = False) -> nn.Sequential:
    """ResNet for CIFAR-10 (depth 20/32/44/56/110) or ImageNet
    (depth 18-200) — models/resnet/ResNet.scala:88 (shortcut types,
    v1/v2 blocks, optimnet init)."""
    st = _State()

    import bigdl_tpu.models.resnet as _mod

    def _conv(*a, **k):
        return _mod._conv(*a, with_bias=conv_bias, **k)

    def shortcut(n_in, n_out, stride):
        use_conv = shortcut_type == ShortcutType.C or (
            shortcut_type == ShortcutType.B and n_in != n_out)
        if use_conv:
            return nn.Sequential() \
                .add(_conv(n_in, n_out, 1, 1, stride, stride)) \
                .add(_bn(n_out))
        elif n_in != n_out:
            # type A: stride subsample + zero-pad channels via Concat
            return nn.Sequential() \
                .add(nn.SpatialAveragePooling(1, 1, stride, stride)) \
                .add(nn.Concat(2)
                     .add(nn.Identity())
                     .add(nn.MulConstant(0.0)))
        return nn.Identity()

    def basic_block(n, stride):
        n_in = st.i_channels
        st.i_channels = n
        s = nn.Sequential()
        s.add(_conv(n_in, n, 3, 3, stride, stride, 1, 1))
        s.add(_bn(n))
        s.add(nn.ReLU(True))
        s.add(_conv(n, n, 3, 3, 1, 1, 1, 1))
        s.add(_bn(n))
        return nn.Sequential() \
            .add(nn.ConcatTable().add(s).add(shortcut(n_in, n, stride))) \
            .add(nn.CAddTable(True)) \
            .add(nn.ReLU(True))

    def bottleneck(n, stride):
        n_in = st.i_channels
        st.i_channels = n * 4
        s = nn.Sequential()
        s.add(_conv(n_in, n, 1, 1, 1, 1, 0, 0)) \
            .add(_bn(n)) \
            .add(nn.ReLU(True)) \
            .add(_conv(n, n, 3, 3, stride, stride, 1, 1)) \
            .add(_bn(n)) \
            .add(nn.ReLU(True)) \
            .add(_conv(n, n * 4, 1, 1, 1, 1, 0, 0)) \
            .add(_bn(n * 4))
        return nn.Sequential() \
            .add(nn.ConcatTable().add(s).add(shortcut(n_in, n * 4, stride))) \
            .add(nn.CAddTable(True)) \
            .add(nn.ReLU(True))

    def layer(block, features, count, stride=1):
        s = nn.Sequential()
        for i in range(count):
            s.add(block(features, stride if i == 0 else 1))
        return s

    model = nn.Sequential()
    if dataset == DatasetType.ImageNet:
        cfg = {18: ((2, 2, 2, 2), 512, basic_block),
               34: ((3, 4, 6, 3), 512, basic_block),
               50: ((3, 4, 6, 3), 2048, bottleneck),
               101: ((3, 4, 23, 3), 2048, bottleneck),
               152: ((3, 8, 36, 3), 2048, bottleneck),
               200: ((3, 24, 36, 3), 2048, bottleneck)}
        if depth not in cfg:
            raise ValueError(f"Invalid depth {depth}")
        loop, n_features, block = cfg[depth]
        st.i_channels = 64
        # stem conv: propagateBack=false (ResNet.scala:234) — no data grad
        model.add(_conv(3, 64, 7, 7, 2, 2, 3, 3, propagate_back=False)) \
            .add(_bn(64)) \
            .add(nn.ReLU(True)) \
            .add(nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)) \
            .add(layer(block, 64, loop[0])) \
            .add(layer(block, 128, loop[1], 2)) \
            .add(layer(block, 256, loop[2], 2)) \
            .add(layer(block, 512, loop[3], 2)) \
            .add(nn.SpatialAveragePooling(7, 7, 1, 1)) \
            .add(nn.View(n_features).set_num_input_dims(3)) \
            .add(nn.Linear(n_features, class_num,
                           init_bias=Zeros()))
    elif dataset == DatasetType.CIFAR10:
        if (depth - 2) % 6 != 0:
            raise ValueError("depth should be one of 20, 32, 44, 56, 110")
        n = (depth - 2) // 6
        st.i_channels = 16
        # stem conv: propagateBack=false (ResNet.scala:252)
        model.add(_conv(3, 16, 3, 3, 1, 1, 1, 1, propagate_back=False)) \
            .add(_bn(16)) \
            .add(nn.ReLU(True)) \
            .add(layer(basic_block, 16, n)) \
            .add(layer(basic_block, 32, n, 2)) \
            .add(layer(basic_block, 64, n, 2)) \
            .add(nn.SpatialAveragePooling(8, 8, 1, 1)) \
            .add(nn.View(64).set_num_input_dims(3)) \
            .add(nn.Linear(64, 10, init_bias=Zeros()))
    else:
        raise ValueError(f"unknown dataset {dataset}")
    return model
