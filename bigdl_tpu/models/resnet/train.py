"""ResNet CIFAR-10 training recipe (models/resnet/Train.scala:46-99 —
SGD lr 0.1, wd 1e-4, momentum 0.9, nesterov, EpochDecay(cifar10Decay:
x0.1 at epochs 81 and 122), batch 448, 165 epochs; models/resnet/README
BASELINE config 3's CIFAR variant).

    python -m bigdl_tpu.models.resnet.train -f /path/to/cifar10 --depth 20
    python -m bigdl_tpu.models.resnet.train --synthetic 256 -e 1
"""
from __future__ import annotations


def cifar10_decay(epoch: int) -> float:
    """resnet/Train.scala:34 cifar10Decay."""
    if epoch >= 122:
        return 2.0
    if epoch >= 81:
        return 1.0
    return 0.0


def imagenet_decay(epoch: int) -> float:
    """fb.resnet step schedule: x0.1 every 30 epochs."""
    return float(epoch // 30)


def _train_imagenet(args, nn, ResNet):
    """ResNet-50 ImageNet recipe: threaded ImageFolder feed with
    ColorJitter + Lighting on by default (dataset/image/ColorJitter.scala,
    Lighting.scala), SGD momentum 0.9 nesterov, x0.1 every 30 epochs."""
    from bigdl_tpu.models._cli import (arrays_to_dataset, load_model_or,
                                       wire_optimizer)
    from bigdl_tpu.optim import (EpochDecay, LocalOptimizer, SGD,
                                 Top1Accuracy, Top5Accuracy)

    bs = args.batchSize or 256
    # dataset-dependent default; an explicitly invalid depth still fails
    # fast inside ResNet()
    depth = args.depth if args.depth is not None else 50
    val_ds = None
    if args.synthetic:
        import numpy as np
        rng = np.random.RandomState(0)
        imgs = rng.rand(args.synthetic, 3, 224, 224).astype(np.float32)
        lbls = rng.randint(1, args.classNum + 1,
                           args.synthetic).astype(np.float32)
        ds = arrays_to_dataset(imgs, lbls, bs)
    else:
        from bigdl_tpu.dataset import ImageFolderDataSet
        ds = ImageFolderDataSet(args.folder, batch_size=bs, crop=224,
                                scale=256, color_jitter=args.colorJitter,
                                lighting=args.lighting)
        if args.valFolder:
            val_ds = ImageFolderDataSet(args.valFolder, batch_size=bs,
                                        crop=224, scale=256)
    model = load_model_or(
        args, lambda: ResNet(args.classNum, depth=depth,
                             dataset="ImageNet"))
    optim = SGD(learning_rate=args.learningRate or 0.1,
                learning_rate_decay=0.0, weight_decay=args.weightDecay,
                momentum=0.9, dampening=0.0, nesterov=args.nesterov,
                learning_rate_schedule=EpochDecay(imagenet_decay))
    opt = LocalOptimizer(model, ds, nn.CrossEntropyCriterion(),
                         batch_size=bs)
    wire_optimizer(opt, args, optim, val_ds=val_ds,
                   val_methods=[Top1Accuracy(), Top5Accuracy()],
                   default_epochs=90)
    opt.optimize()
    print(f"final loss: {opt.driver_state['Loss']:.4f}")
    return model


def main(argv=None):
    import argparse

    from bigdl_tpu.models._cli import (
        arrays_to_dataset, base_parser, cifar10_arrays, load_model_or,
        wire_optimizer)

    ap = base_parser("Train ResNet on CIFAR-10 / ImageNet")
    ap.add_argument("--depth", type=int, default=None,
                    help="default: 20 (cifar10) / 50 (imagenet)")
    ap.add_argument("--weightDecay", type=float, default=1e-4)
    ap.add_argument("--nesterov", action=argparse.BooleanOptionalAction,
                    default=True)
    ap.add_argument("--dataset", choices=("cifar10", "imagenet"),
                    default="cifar10")
    ap.add_argument("--classNum", type=int, default=1000)
    ap.add_argument("--colorJitter", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="ImageNet only: random b/c/s (ColorJitter.scala)")
    ap.add_argument("--lighting", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="ImageNet only: PCA noise (Lighting.scala)")
    ap.add_argument("--valFolder", default=None,
                    help="ImageNet only: val folder for per-epoch "
                         "Top1/Top5")
    args = ap.parse_args(argv)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models.resnet import ResNet
    from bigdl_tpu.optim import (EpochDecay, LocalOptimizer, Loss, SGD,
                                 Top1Accuracy, Top5Accuracy)

    if args.dataset == "imagenet":
        return _train_imagenet(args, nn, ResNet)

    bs = args.batchSize or 448
    tr = cifar10_arrays(args.folder, True, args.synthetic)
    va = cifar10_arrays(args.folder, False, args.synthetic or 0)
    model = load_model_or(
        args, lambda: ResNet(10, depth=args.depth or 20,
                             dataset="CIFAR10"))
    optim = SGD(learning_rate=args.learningRate or 0.1,
                learning_rate_decay=0.0, weight_decay=args.weightDecay,
                momentum=0.9, dampening=0.0, nesterov=args.nesterov,
                learning_rate_schedule=EpochDecay(cifar10_decay))
    opt = LocalOptimizer(model, arrays_to_dataset(*tr, bs),
                         nn.CrossEntropyCriterion(), batch_size=bs)
    wire_optimizer(opt, args, optim,
                   val_ds=arrays_to_dataset(*va, bs),
                   val_methods=[Top1Accuracy(), Top5Accuracy(), Loss()],
                   default_epochs=165)
    opt.optimize()
    print(f"final loss: {opt.driver_state['Loss']:.4f}")
    return model


if __name__ == "__main__":
    main()
