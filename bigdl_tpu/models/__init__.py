"""Model zoo (BigDL models/ — SURVEY.md §2.4)."""
from bigdl_tpu.models.lenet import LeNet5
from bigdl_tpu.models.vgg import VggForCifar10, Vgg_16, Vgg_19
from bigdl_tpu.models.resnet import ResNet
from bigdl_tpu.models.inception import (
    Inception_v1, Inception_v1_NoAuxClassifier, Inception_v2,
    Inception_v2_NoAuxClassifier)
from bigdl_tpu.models.alexnet import AlexNet, AlexNet_OWT
from bigdl_tpu.models.rnn import SimpleRNN, PTBModel
from bigdl_tpu.models.autoencoder import Autoencoder
from bigdl_tpu.models.transformer import (TransformerBlock, TransformerLM,
                                          FeedForward)
from bigdl_tpu.models.transformer.pipelined import PipelinedTransformerLM
