"""Shared plumbing for the per-model Train/Test entry points (reference:
models/*/Train.scala + models/*/Utils.scala scopt parsers).

Every model package exposes ``python -m bigdl_tpu.models.<name>.train`` and
``.test`` mains whose flags mirror the reference recipes (-f folder,
-b batchSize, -e maxEpoch, -r learningRate, --model/--state snapshots,
--checkpoint). A ``--synthetic N`` flag substitutes N random samples for
the dataset so every recipe is runnable without downloads (the role
DistriOptimizerPerf's synthetic data played, models/utils/).
"""
from __future__ import annotations

import argparse
import os
from typing import List, Optional, Tuple

import numpy as np


def base_parser(desc: str) -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(description=desc)
    ap.add_argument("-f", "--folder", default="./",
                    help="where the dataset lives")
    ap.add_argument("-b", "--batchSize", type=int, default=None)
    ap.add_argument("-e", "--maxEpoch", type=int, default=None)
    ap.add_argument("-r", "--learningRate", type=float, default=None)
    ap.add_argument("-d", "--learningRateDecay", type=float, default=None)
    ap.add_argument("--model", default=None,
                    help="model snapshot to resume/test")
    ap.add_argument("--state", default=None,
                    help="optim-method state snapshot to resume")
    ap.add_argument("--checkpoint", default=None,
                    help="directory to write checkpoints")
    ap.add_argument("--overWrite", action="store_true",
                    help="overwrite checkpoint files")
    ap.add_argument("--maxIterations", type=int, default=None,
                    help="stop after N iterations (overrides maxEpoch)")
    ap.add_argument("--synthetic", type=int, default=0, metavar="N",
                    help="train on N random samples instead of -f data")
    ap.add_argument("--quantize", action="store_true",
                    help="int8-quantize the model before evaluation "
                         "(AbstractModule.quantize :708)")
    ap.add_argument("--steps-per-sync", type=int, default=1, metavar="K",
                    help="fuse K train steps into one compiled scan and "
                    "sync the host only at window boundaries "
                    "(Optimizer.set_steps_per_sync; docs/performance.md)")
    return ap


def load_model_or(args, build):
    """--model snapshot beats the fresh builder (Train.scala pattern)."""
    if args.model:
        from bigdl_tpu.utils.serialization import load_module
        return load_module(args.model)
    return build()


def wire_optimizer(opt, args, optim_method, val_ds=None,
                   val_methods=None, default_epochs: int = 1):
    """setCheckpoint/setValidation/setEndWhen in the reference shape."""
    from bigdl_tpu.optim import every_epoch, max_epoch, max_iteration

    if args.state:
        import pickle
        with open(args.state, "rb") as f:
            optim_method.load_state(pickle.load(f))
    opt.set_optim_method(optim_method)
    if args.checkpoint:
        opt.set_checkpoint(args.checkpoint, every_epoch())
    if val_ds is not None and val_methods:
        opt.set_validation(every_epoch(), val_ds, val_methods)
    if args.maxIterations:
        opt.set_end_when(max_iteration(args.maxIterations))
    else:
        opt.set_end_when(max_epoch(args.maxEpoch or default_epochs))
    if getattr(args, "steps_per_sync", 1) != 1:
        # let set_steps_per_sync reject 0/negative values loudly rather
        # than silently training per-step on a typo
        opt.set_steps_per_sync(args.steps_per_sync)
    return opt


# ------------------------------------------------------------ dataset glue

def mnist_arrays(folder: str, train: bool,
                 synthetic: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """MNIST idx files -> normalized [N,1,28,28] + 1-based labels
    (lenet/Utils.scala train/test mean+std)."""
    if synthetic:
        from bigdl_tpu.tools.synthetic import (SEED_EVAL, SEED_TRAIN,
                                               image_batch)
        return image_batch(synthetic, (1, 28, 28), 10,
                           seed=SEED_TRAIN if train else SEED_EVAL)
    from bigdl_tpu.dataset.image import load_mnist
    prefix = "train" if train else "t10k"
    img_path = os.path.join(folder, f"{prefix}-images-idx3-ubyte")
    if not os.path.exists(img_path) and \
            not os.path.exists(img_path + ".gz"):
        # the reference's recipes materialize their corpus from nothing
        # (pyspark/bigdl/models/lenet/lenet5.py:24-30): download-if-
        # missing into -f, with a clear offline story
        from bigdl_tpu.dataset import fetch
        try:
            imgs, lbls = fetch.mnist_read_data_sets(
                folder, "train" if train else "test")
        except Exception as e:
            raise SystemExit(
                f"MNIST not found under '{folder}' and auto-download "
                f"failed ({type(e).__name__}: {e}). Pre-stage the idx "
                "files there, or use --synthetic N.")
        imgs = imgs[:, None, :, :].astype(np.float32)
        lbls = (lbls + 1).astype(np.float32)  # 1-based criterion labels
    else:
        if not os.path.exists(img_path):
            img_path += ".gz"
        lbl_path = os.path.join(folder, f"{prefix}-labels-idx1-ubyte")
        if not os.path.exists(lbl_path):
            lbl_path += ".gz"
        imgs, lbls = load_mnist(img_path, lbl_path)
    mean, std = ((0.13066047, 0.3081078) if train
                 else (0.13251461, 0.31048024))
    return ((imgs / 255.0 - mean) / std).astype(np.float32), lbls


def cifar10_arrays(folder: str, train: bool, synthetic: int = 0):
    """CIFAR-10 binary batches -> normalized [N,3,32,32] + 1-based labels
    (vgg/resnet recipes' per-channel stats)."""
    if synthetic:
        from bigdl_tpu.tools.synthetic import (SEED_EVAL, SEED_TRAIN,
                                               image_batch)
        return image_batch(synthetic, (3, 32, 32), 10,
                           seed=SEED_TRAIN if train else SEED_EVAL)
    from bigdl_tpu.dataset.image import load_cifar10
    if train:
        paths = [os.path.join(folder, f"data_batch_{i}.bin")
                 for i in range(1, 6)]
    else:
        paths = [os.path.join(folder, "test_batch.bin")]
    imgs, lbls = load_cifar10(paths)
    mean = np.array([125.3, 123.0, 113.9], np.float32).reshape(3, 1, 1)
    std = np.array([63.0, 62.1, 66.7], np.float32).reshape(3, 1, 1)
    return ((imgs - mean) / std).astype(np.float32), lbls


def arrays_to_dataset(imgs, lbls, batch_size: int):
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    samples = [Sample(imgs[i], lbls[i]) for i in range(len(imgs))]
    return DataSet.array(samples).transform(SampleToMiniBatch(batch_size))


def evaluate_cli(args, build, val_data, default_batch: int = 128):
    """Shared Test.scala main: load snapshot (or fresh), evaluate Top1."""
    from bigdl_tpu.optim import Evaluator, Top1Accuracy, Top5Accuracy

    model = load_model_or(args, build).evaluate()
    if getattr(args, "quantize", False):
        model = model.quantize()
    imgs, lbls = val_data
    bs = args.batchSize or default_batch
    ds = arrays_to_dataset(imgs, lbls, bs)
    results = Evaluator(model).test(
        ds, [Top1Accuracy(), Top5Accuracy()], batch_size=bs)
    for name, r in results.items():
        print(f"{name}: {r}")
    return results
