"""VGG-16 CIFAR-10 evaluation (models/vgg/Test.scala)."""
from __future__ import annotations


def main(argv=None):
    from bigdl_tpu.models._cli import (base_parser, cifar10_arrays,
                                       evaluate_cli)

    args = base_parser("Test VGG-16 on CIFAR-10").parse_args(argv)
    from bigdl_tpu.models.vgg import VggForCifar10
    return evaluate_cli(args, lambda: VggForCifar10(10),
                        cifar10_arrays(args.folder, False, args.synthetic))


if __name__ == "__main__":
    main()
