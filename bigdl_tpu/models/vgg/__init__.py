"""VGG models (reference: models/vgg/VggForCifar10.scala, Vgg_16/19 in
models/vgg — conv-BN-ReLU stacks; BASELINE config 2)."""
from __future__ import annotations

import bigdl_tpu.nn as nn


def VggForCifar10(class_num: int = 10, has_dropout: bool = True
                  ) -> nn.Sequential:
    """VggForCifar10.scala:24-78."""
    m = nn.Sequential()

    def conv_bn_relu(cin, cout):
        m.add(nn.SpatialConvolution(cin, cout, 3, 3, 1, 1, 1, 1))
        m.add(nn.SpatialBatchNormalization(cout, 1e-3))
        m.add(nn.ReLU(True))

    conv_bn_relu(3, 64)
    if has_dropout:
        m.add(nn.Dropout(0.3))
    conv_bn_relu(64, 64)
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(64, 128)
    if has_dropout:
        m.add(nn.Dropout(0.4))
    conv_bn_relu(128, 128)
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(128, 256)
    if has_dropout:
        m.add(nn.Dropout(0.4))
    conv_bn_relu(256, 256)
    if has_dropout:
        m.add(nn.Dropout(0.4))
    conv_bn_relu(256, 256)
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(256, 512)
    if has_dropout:
        m.add(nn.Dropout(0.4))
    conv_bn_relu(512, 512)
    if has_dropout:
        m.add(nn.Dropout(0.4))
    conv_bn_relu(512, 512)
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())

    conv_bn_relu(512, 512)
    if has_dropout:
        m.add(nn.Dropout(0.4))
    conv_bn_relu(512, 512)
    if has_dropout:
        m.add(nn.Dropout(0.4))
    conv_bn_relu(512, 512)
    m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
    m.add(nn.View(512))

    classifier = nn.Sequential()
    if has_dropout:
        classifier.add(nn.Dropout(0.5))
    classifier.add(nn.Linear(512, 512))
    classifier.add(nn.BatchNormalization(512))
    classifier.add(nn.ReLU(True))
    if has_dropout:
        classifier.add(nn.Dropout(0.5))
    classifier.add(nn.Linear(512, class_num))
    classifier.add(nn.LogSoftMax())
    m.add(classifier)
    return m


def _vgg_blocks(cfg, class_num):
    """Plain VGG-16/19 for 224x224 ImageNet (models/vgg in reference zoo /
    DistriOptimizerPerf's vgg16/vgg19)."""
    m = nn.Sequential()
    cin = 3
    for v in cfg:
        if v == "M":
            m.add(nn.SpatialMaxPooling(2, 2, 2, 2))
        else:
            m.add(nn.SpatialConvolution(cin, v, 3, 3, 1, 1, 1, 1))
            m.add(nn.ReLU(True))
            cin = v
    m.add(nn.View(512 * 7 * 7))
    m.add(nn.Linear(512 * 7 * 7, 4096))
    m.add(nn.Threshold(0, 1e-6))
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, 4096))
    m.add(nn.Threshold(0, 1e-6))
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, class_num))
    m.add(nn.LogSoftMax())
    return m


def Vgg_16(class_num: int = 1000) -> nn.Sequential:
    """VGG-16 ImageNet (models/vgg/Vgg_16.scala)."""
    return _vgg_blocks([64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
                        512, 512, 512, "M", 512, 512, 512, "M"], class_num)


def Vgg_19(class_num: int = 1000) -> nn.Sequential:
    """VGG-19 ImageNet (models/vgg/Vgg_19.scala)."""
    return _vgg_blocks([64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
                        512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
                       class_num)
