"""VGG-16 CIFAR-10 training recipe (models/vgg/Train.scala:30-80 —
SGD lr 0.01, wd 5e-4, momentum 0.9, EpochStep(25, 0.5), maxEpoch 90;
BASELINE config 2).

    python -m bigdl_tpu.models.vgg.train -f /path/to/cifar10 -b 112
    python -m bigdl_tpu.models.vgg.train --synthetic 256 -e 1
"""
from __future__ import annotations


def main(argv=None):
    from bigdl_tpu.models._cli import (
        arrays_to_dataset, base_parser, cifar10_arrays, load_model_or,
        wire_optimizer)

    ap = base_parser("Train VGG-16 on CIFAR-10")
    ap.add_argument("--weightDecay", type=float, default=5e-4)
    args = ap.parse_args(argv)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models.vgg import VggForCifar10
    from bigdl_tpu.optim import (EpochStep, LocalOptimizer, Loss, SGD,
                                 Top1Accuracy, Top5Accuracy)

    bs = args.batchSize or 112
    tr = cifar10_arrays(args.folder, True, args.synthetic)
    va = cifar10_arrays(args.folder, False, args.synthetic or 0)
    model = load_model_or(args, lambda: VggForCifar10(10))
    optim = SGD(learning_rate=args.learningRate or 0.01,
                learning_rate_decay=0.0, weight_decay=args.weightDecay,
                momentum=0.9, dampening=0.0, nesterov=False,
                learning_rate_schedule=EpochStep(25, 0.5))
    opt = LocalOptimizer(model, arrays_to_dataset(*tr, bs),
                         nn.ClassNLLCriterion(), batch_size=bs)
    wire_optimizer(opt, args, optim,
                   val_ds=arrays_to_dataset(*va, bs),
                   val_methods=[Top1Accuracy(), Top5Accuracy(), Loss()],
                   default_epochs=90)
    opt.optimize()
    print(f"final loss: {opt.driver_state['Loss']:.4f}")
    return model


if __name__ == "__main__":
    main()
