"""Inception-v1 ImageNet evaluation (models/inception/Test.scala:38-64 —
center-crop 224, Top1/Top5 over the val folder).

    python -m bigdl_tpu.models.inception.test -f /imagenet/val --model snap
    python -m bigdl_tpu.models.inception.test --synthetic 16 --classNum 10
"""
from __future__ import annotations


def main(argv=None):
    from bigdl_tpu.models._cli import base_parser, load_model_or

    ap = base_parser("Test Inception-v1 on ImageNet")
    ap.add_argument("--classNum", type=int, default=1000)
    args = ap.parse_args(argv)

    import numpy as np

    from bigdl_tpu.models.inception import Inception_v1_NoAuxClassifier
    from bigdl_tpu.optim import Evaluator, Top1Accuracy, Top5Accuracy

    build = lambda: Inception_v1_NoAuxClassifier(args.classNum)
    bs = args.batchSize or 32

    if args.synthetic:
        from bigdl_tpu.models._cli import evaluate_cli
        rng = np.random.RandomState(1)
        imgs = rng.rand(args.synthetic, 3, 224, 224).astype(np.float32)
        lbls = rng.randint(1, args.classNum + 1,
                           args.synthetic).astype(np.float32)
        return evaluate_cli(args, build, (imgs, lbls), default_batch=32)

    from bigdl_tpu.dataset import ImageFolderDataSet
    model = load_model_or(args, build).evaluate()
    if args.quantize:
        model = model.quantize()
    ds = ImageFolderDataSet(args.folder, batch_size=bs, crop=224, scale=256)
    results = Evaluator(model).test(
        ds, [Top1Accuracy(), Top5Accuracy()], batch_size=bs)
    for name, r in results.items():
        print(f"{name}: {r}")
    return results


if __name__ == "__main__":
    main()
