"""GoogLeNet / Inception v1 (reference: models/inception/Inception_v1.scala;
BASELINE config 4 loads this topology from Caffe)."""
from __future__ import annotations

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.initialization import Xavier, Zeros
from bigdl_tpu.utils.table import T


def _conv(cin, cout, kw, kh, sw=1, sh=1, pw=0, ph=0, name=None):
    c = nn.SpatialConvolution(cin, cout, kw, kh, sw, sh, pw, ph,
                              init_weight=Xavier(), init_bias=Zeros())
    if name:
        c.set_name(name)
    return c


def Inception_Layer_v1(input_size: int, config, name_prefix: str = ""
                       ) -> nn.Concat:
    """Inception block (Inception_v1.scala:26-63): 1x1 / 3x3 / 5x5 / pool-proj
    branches concatenated on the channel dim."""
    concat = nn.Concat(2)
    conv1 = nn.Sequential()
    conv1.add(_conv(input_size, config[1][1], 1, 1, name=name_prefix + "1x1"))
    conv1.add(nn.ReLU(True))
    concat.add(conv1)
    conv3 = nn.Sequential()
    conv3.add(_conv(input_size, config[2][1], 1, 1,
                    name=name_prefix + "3x3_reduce"))
    conv3.add(nn.ReLU(True))
    conv3.add(_conv(config[2][1], config[2][2], 3, 3, 1, 1, 1, 1,
                    name=name_prefix + "3x3"))
    conv3.add(nn.ReLU(True))
    concat.add(conv3)
    conv5 = nn.Sequential()
    conv5.add(_conv(input_size, config[3][1], 1, 1,
                    name=name_prefix + "5x5_reduce"))
    conv5.add(nn.ReLU(True))
    conv5.add(_conv(config[3][1], config[3][2], 5, 5, 1, 1, 2, 2,
                    name=name_prefix + "5x5"))
    conv5.add(nn.ReLU(True))
    concat.add(conv5)
    pool = nn.Sequential()
    pool.add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil())
    pool.add(_conv(input_size, config[4][1], 1, 1,
                   name=name_prefix + "pool_proj"))
    pool.add(nn.ReLU(True))
    concat.add(pool)
    concat.set_name(name_prefix + "output")
    return concat


def Inception_v1_NoAuxClassifier(class_num: int = 1000,
                                 has_dropout: bool = True) -> nn.Sequential:
    """Inception_v1.scala:97-132."""
    m = nn.Sequential()
    m.add(_conv(3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(_conv(64, 64, 1, 1, name="conv2/3x3_reduce"))
    m.add(nn.ReLU(True))
    m.add(_conv(64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(Inception_Layer_v1(192, T(T(64), T(96, 128), T(16, 32), T(32)),
                             "inception_3a/"))
    m.add(Inception_Layer_v1(256, T(T(128), T(128, 192), T(32, 96), T(64)),
                             "inception_3b/"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(Inception_Layer_v1(480, T(T(192), T(96, 208), T(16, 48), T(64)),
                             "inception_4a/"))
    m.add(Inception_Layer_v1(512, T(T(160), T(112, 224), T(24, 64), T(64)),
                             "inception_4b/"))
    m.add(Inception_Layer_v1(512, T(T(128), T(128, 256), T(24, 64), T(64)),
                             "inception_4c/"))
    m.add(Inception_Layer_v1(512, T(T(112), T(144, 288), T(32, 64), T(64)),
                             "inception_4d/"))
    m.add(Inception_Layer_v1(528, T(T(256), T(160, 320), T(32, 128), T(128)),
                             "inception_4e/"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(Inception_Layer_v1(832, T(T(256), T(160, 320), T(32, 128), T(128)),
                             "inception_5a/"))
    m.add(Inception_Layer_v1(832, T(T(384), T(192, 384), T(48, 128), T(128)),
                             "inception_5b/"))
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    if has_dropout:
        m.add(nn.Dropout(0.4))
    m.add(nn.View(1024).set_num_input_dims(3))
    m.add(nn.Linear(1024, class_num, init_weight=Xavier(),
                    init_bias=Zeros()).set_name("loss3/classifier"))
    m.add(nn.LogSoftMax())
    return m


def Inception_v1(class_num: int = 1000, has_dropout: bool = True
                 ) -> nn.Sequential:
    """Full GoogLeNet with the two auxiliary classifier heads
    (Inception_v1.scala:181-268). Output is the channel-concat of
    [main, aux2, aux1] heads like the reference's nested Concat."""
    feature1 = nn.Sequential()
    feature1.add(_conv(3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2"))
    feature1.add(nn.ReLU(True))
    feature1.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    feature1.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    feature1.add(_conv(64, 64, 1, 1, name="conv2/3x3_reduce"))
    feature1.add(nn.ReLU(True))
    feature1.add(_conv(64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3"))
    feature1.add(nn.ReLU(True))
    feature1.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    feature1.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    feature1.add(Inception_Layer_v1(
        192, T(T(64), T(96, 128), T(16, 32), T(32)), "inception_3a/"))
    feature1.add(Inception_Layer_v1(
        256, T(T(128), T(128, 192), T(32, 96), T(64)), "inception_3b/"))
    feature1.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    feature1.add(Inception_Layer_v1(
        480, T(T(192), T(96, 208), T(16, 48), T(64)), "inception_4a/"))

    output1 = nn.Sequential()
    output1.add(nn.SpatialAveragePooling(5, 5, 3, 3, ceil_mode=True))
    output1.add(_conv(512, 128, 1, 1, name="loss1/conv"))
    output1.add(nn.ReLU(True))
    output1.add(nn.View(128 * 4 * 4).set_num_input_dims(3))
    output1.add(nn.Linear(128 * 4 * 4, 1024).set_name("loss1/fc"))
    output1.add(nn.ReLU(True))
    if has_dropout:
        output1.add(nn.Dropout(0.7))
    output1.add(nn.Linear(1024, class_num).set_name("loss1/classifier"))
    output1.add(nn.LogSoftMax())

    feature2 = nn.Sequential()
    feature2.add(Inception_Layer_v1(
        512, T(T(160), T(112, 224), T(24, 64), T(64)), "inception_4b/"))
    feature2.add(Inception_Layer_v1(
        512, T(T(128), T(128, 256), T(24, 64), T(64)), "inception_4c/"))
    feature2.add(Inception_Layer_v1(
        512, T(T(112), T(144, 288), T(32, 64), T(64)), "inception_4d/"))

    output2 = nn.Sequential()
    output2.add(nn.SpatialAveragePooling(5, 5, 3, 3))
    output2.add(_conv(528, 128, 1, 1, name="loss2/conv"))
    output2.add(nn.ReLU(True))
    output2.add(nn.View(128 * 4 * 4).set_num_input_dims(3))
    output2.add(nn.Linear(128 * 4 * 4, 1024).set_name("loss2/fc"))
    output2.add(nn.ReLU(True))
    if has_dropout:
        output2.add(nn.Dropout(0.7))
    output2.add(nn.Linear(1024, class_num).set_name("loss2/classifier"))
    output2.add(nn.LogSoftMax())

    output3 = nn.Sequential()
    output3.add(Inception_Layer_v1(
        528, T(T(256), T(160, 320), T(32, 128), T(128)), "inception_4e/"))
    output3.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    output3.add(Inception_Layer_v1(
        832, T(T(256), T(160, 320), T(32, 128), T(128)), "inception_5a/"))
    output3.add(Inception_Layer_v1(
        832, T(T(384), T(192, 384), T(48, 128), T(128)), "inception_5b/"))
    output3.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    if has_dropout:
        output3.add(nn.Dropout(0.4))
    output3.add(nn.View(1024).set_num_input_dims(3))
    output3.add(nn.Linear(1024, class_num, init_weight=Xavier(),
                          init_bias=Zeros()).set_name("loss3/classifier"))
    output3.add(nn.LogSoftMax())

    split2 = nn.Concat(2).set_name("split2")
    split2.add(output3)
    split2.add(output2)

    main_branch = nn.Sequential()
    main_branch.add(feature2)
    main_branch.add(split2)

    split1 = nn.Concat(2).set_name("split1")
    split1.add(main_branch)
    split1.add(output1)

    model = nn.Sequential()
    model.add(feature1)
    model.add(split1)
    return model
