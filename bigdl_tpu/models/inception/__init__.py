"""GoogLeNet / Inception v1 (reference: models/inception/Inception_v1.scala;
BASELINE config 4 loads this topology from Caffe)."""
from __future__ import annotations

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.initialization import Xavier, Zeros
from bigdl_tpu.utils.table import T


def _conv(cin, cout, kw, kh, sw=1, sh=1, pw=0, ph=0, name=None):
    c = nn.SpatialConvolution(cin, cout, kw, kh, sw, sh, pw, ph,
                              init_weight=Xavier(), init_bias=Zeros())
    if name:
        c.set_name(name)
    return c


def Inception_Layer_v1(input_size: int, config, name_prefix: str = ""
                       ) -> nn.Concat:
    """Inception block (Inception_v1.scala:26-63): 1x1 / 3x3 / 5x5 / pool-proj
    branches concatenated on the channel dim."""
    concat = nn.Concat(2)
    conv1 = nn.Sequential()
    conv1.add(_conv(input_size, config[1][1], 1, 1, name=name_prefix + "1x1"))
    conv1.add(nn.ReLU(True))
    concat.add(conv1)
    conv3 = nn.Sequential()
    conv3.add(_conv(input_size, config[2][1], 1, 1,
                    name=name_prefix + "3x3_reduce"))
    conv3.add(nn.ReLU(True))
    conv3.add(_conv(config[2][1], config[2][2], 3, 3, 1, 1, 1, 1,
                    name=name_prefix + "3x3"))
    conv3.add(nn.ReLU(True))
    concat.add(conv3)
    conv5 = nn.Sequential()
    conv5.add(_conv(input_size, config[3][1], 1, 1,
                    name=name_prefix + "5x5_reduce"))
    conv5.add(nn.ReLU(True))
    conv5.add(_conv(config[3][1], config[3][2], 5, 5, 1, 1, 2, 2,
                    name=name_prefix + "5x5"))
    conv5.add(nn.ReLU(True))
    concat.add(conv5)
    pool = nn.Sequential()
    pool.add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil())
    pool.add(_conv(input_size, config[4][1], 1, 1,
                   name=name_prefix + "pool_proj"))
    pool.add(nn.ReLU(True))
    concat.add(pool)
    concat.set_name(name_prefix + "output")
    return concat


def Inception_v1_NoAuxClassifier(class_num: int = 1000,
                                 has_dropout: bool = True) -> nn.Sequential:
    """Inception_v1.scala:97-132."""
    m = nn.Sequential()
    m.add(_conv(3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(_conv(64, 64, 1, 1, name="conv2/3x3_reduce"))
    m.add(nn.ReLU(True))
    m.add(_conv(64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(Inception_Layer_v1(192, T(T(64), T(96, 128), T(16, 32), T(32)),
                             "inception_3a/"))
    m.add(Inception_Layer_v1(256, T(T(128), T(128, 192), T(32, 96), T(64)),
                             "inception_3b/"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(Inception_Layer_v1(480, T(T(192), T(96, 208), T(16, 48), T(64)),
                             "inception_4a/"))
    m.add(Inception_Layer_v1(512, T(T(160), T(112, 224), T(24, 64), T(64)),
                             "inception_4b/"))
    m.add(Inception_Layer_v1(512, T(T(128), T(128, 256), T(24, 64), T(64)),
                             "inception_4c/"))
    m.add(Inception_Layer_v1(512, T(T(112), T(144, 288), T(32, 64), T(64)),
                             "inception_4d/"))
    m.add(Inception_Layer_v1(528, T(T(256), T(160, 320), T(32, 128), T(128)),
                             "inception_4e/"))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(Inception_Layer_v1(832, T(T(256), T(160, 320), T(32, 128), T(128)),
                             "inception_5a/"))
    m.add(Inception_Layer_v1(832, T(T(384), T(192, 384), T(48, 128), T(128)),
                             "inception_5b/"))
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    if has_dropout:
        m.add(nn.Dropout(0.4))
    m.add(nn.View(1024).set_num_input_dims(3))
    m.add(nn.Linear(1024, class_num, init_weight=Xavier(),
                    init_bias=Zeros()).set_name("loss3/classifier"))
    m.add(nn.LogSoftMax())
    return m


def Inception_v1(class_num: int = 1000, has_dropout: bool = True
                 ) -> nn.Sequential:
    """Full GoogLeNet with the two auxiliary classifier heads
    (Inception_v1.scala:181-268). Output is the channel-concat of
    [main, aux2, aux1] heads like the reference's nested Concat."""
    feature1 = nn.Sequential()
    feature1.add(_conv(3, 64, 7, 7, 2, 2, 3, 3, name="conv1/7x7_s2"))
    feature1.add(nn.ReLU(True))
    feature1.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    feature1.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    feature1.add(_conv(64, 64, 1, 1, name="conv2/3x3_reduce"))
    feature1.add(nn.ReLU(True))
    feature1.add(_conv(64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3"))
    feature1.add(nn.ReLU(True))
    feature1.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    feature1.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    feature1.add(Inception_Layer_v1(
        192, T(T(64), T(96, 128), T(16, 32), T(32)), "inception_3a/"))
    feature1.add(Inception_Layer_v1(
        256, T(T(128), T(128, 192), T(32, 96), T(64)), "inception_3b/"))
    feature1.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    feature1.add(Inception_Layer_v1(
        480, T(T(192), T(96, 208), T(16, 48), T(64)), "inception_4a/"))

    output1 = nn.Sequential()
    output1.add(nn.SpatialAveragePooling(5, 5, 3, 3, ceil_mode=True))
    output1.add(_conv(512, 128, 1, 1, name="loss1/conv"))
    output1.add(nn.ReLU(True))
    output1.add(nn.View(128 * 4 * 4).set_num_input_dims(3))
    output1.add(nn.Linear(128 * 4 * 4, 1024).set_name("loss1/fc"))
    output1.add(nn.ReLU(True))
    if has_dropout:
        output1.add(nn.Dropout(0.7))
    output1.add(nn.Linear(1024, class_num).set_name("loss1/classifier"))
    output1.add(nn.LogSoftMax())

    feature2 = nn.Sequential()
    feature2.add(Inception_Layer_v1(
        512, T(T(160), T(112, 224), T(24, 64), T(64)), "inception_4b/"))
    feature2.add(Inception_Layer_v1(
        512, T(T(128), T(128, 256), T(24, 64), T(64)), "inception_4c/"))
    feature2.add(Inception_Layer_v1(
        512, T(T(112), T(144, 288), T(32, 64), T(64)), "inception_4d/"))

    output2 = nn.Sequential()
    output2.add(nn.SpatialAveragePooling(5, 5, 3, 3))
    output2.add(_conv(528, 128, 1, 1, name="loss2/conv"))
    output2.add(nn.ReLU(True))
    output2.add(nn.View(128 * 4 * 4).set_num_input_dims(3))
    output2.add(nn.Linear(128 * 4 * 4, 1024).set_name("loss2/fc"))
    output2.add(nn.ReLU(True))
    if has_dropout:
        output2.add(nn.Dropout(0.7))
    output2.add(nn.Linear(1024, class_num).set_name("loss2/classifier"))
    output2.add(nn.LogSoftMax())

    output3 = nn.Sequential()
    output3.add(Inception_Layer_v1(
        528, T(T(256), T(160, 320), T(32, 128), T(128)), "inception_4e/"))
    output3.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    output3.add(Inception_Layer_v1(
        832, T(T(256), T(160, 320), T(32, 128), T(128)), "inception_5a/"))
    output3.add(Inception_Layer_v1(
        832, T(T(384), T(192, 384), T(48, 128), T(128)), "inception_5b/"))
    output3.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    if has_dropout:
        output3.add(nn.Dropout(0.4))
    output3.add(nn.View(1024).set_num_input_dims(3))
    output3.add(nn.Linear(1024, class_num, init_weight=Xavier(),
                          init_bias=Zeros()).set_name("loss3/classifier"))
    output3.add(nn.LogSoftMax())

    split2 = nn.Concat(2).set_name("split2")
    split2.add(output3)
    split2.add(output2)

    main_branch = nn.Sequential()
    main_branch.add(feature2)
    main_branch.add(split2)

    split1 = nn.Concat(2).set_name("split1")
    split1.add(main_branch)
    split1.add(output1)

    model = nn.Sequential()
    model.add(feature1)
    model.add(split1)
    return model


# ---------------------------------------------------------- Inception v2

def _conv_bn(seq, cin, cout, kw, kh, sw=1, sh=1, pw=0, ph=0, name=""):
    """conv -> BN(1e-3) -> ReLU triple, the v2 building unit
    (Inception_v2.scala:31-40). Convs feeding BN are bias-free: BN's
    mean subtraction cancels the bias exactly (see models/resnet._conv)."""
    seq.add(nn.SpatialConvolution(cin, cout, kw, kh, sw, sh, pw, ph,
                                  init_weight=Xavier(), init_bias=Zeros(),
                                  with_bias=False).set_name(name))
    seq.add(nn.SpatialBatchNormalization(cout, 1e-3).set_name(name + "/bn"))
    seq.add(nn.ReLU(True))
    return seq


def Inception_Layer_v2(input_size: int, config, name_prefix: str = ""
                       ) -> nn.Concat:
    """BN-Inception block (Inception_v2.scala:27-107): optional 1x1,
    3x3, double-3x3 and pool branches; a ("max", 0) pool entry marks the
    stride-2 grid-reduction form."""
    reduce_grid = config[4][1] == "max" and config[4][2] == 0
    concat = nn.Concat(2)
    if config[1][1] != 0:
        conv1 = nn.Sequential()
        _conv_bn(conv1, input_size, config[1][1], 1, 1,
                 name=name_prefix + "1x1")
        concat.add(conv1)

    conv3 = nn.Sequential()
    _conv_bn(conv3, input_size, config[2][1], 1, 1,
             name=name_prefix + "3x3_reduce")
    s = 2 if reduce_grid else 1
    _conv_bn(conv3, config[2][1], config[2][2], 3, 3, s, s, 1, 1,
             name=name_prefix + "3x3")
    concat.add(conv3)

    conv3xx = nn.Sequential()
    _conv_bn(conv3xx, input_size, config[3][1], 1, 1,
             name=name_prefix + "double3x3_reduce")
    _conv_bn(conv3xx, config[3][1], config[3][2], 3, 3, 1, 1, 1, 1,
             name=name_prefix + "double3x3a")
    _conv_bn(conv3xx, config[3][2], config[3][2], 3, 3, s, s, 1, 1,
             name=name_prefix + "double3x3b")
    concat.add(conv3xx)

    pool = nn.Sequential()
    if config[4][1] == "max":
        if config[4][2] != 0:
            pool.add(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil())
        else:
            pool.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    elif config[4][1] == "avg":
        pool.add(nn.SpatialAveragePooling(3, 3, 1, 1, 1, 1,
                                          ceil_mode=True))
    else:
        raise ValueError(f"unknown pool kind {config[4][1]}")
    if config[4][2] != 0:
        _conv_bn(pool, input_size, config[4][2], 1, 1,
                 name=name_prefix + "pool_proj")
    concat.add(pool)
    return concat.set_name(name_prefix + "output")


def _v2_stem(m: nn.Sequential) -> nn.Sequential:
    """conv1..pool2 (Inception_v2.scala:187-197); stem conv has
    propagate_back analogue via nGroup=1,false in the reference."""
    m.add(nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3,
                                propagate_back=False,
                                init_weight=Xavier(), init_bias=Zeros(),
                                with_bias=False).set_name("conv1/7x7_s2"))
    m.add(nn.SpatialBatchNormalization(64, 1e-3).set_name("conv1/7x7_s2/bn"))
    m.add(nn.ReLU(True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    _conv_bn(m, 64, 64, 1, 1, name="conv2/3x3_reduce")
    _conv_bn(m, 64, 192, 3, 3, 1, 1, 1, 1, name="conv2/3x3")
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    return m


def Inception_v2_NoAuxClassifier(class_num: int = 1000) -> nn.Sequential:
    """BN-GoogLeNet without aux heads (Inception_v2.scala:185-219)."""
    m = _v2_stem(nn.Sequential())
    m.add(Inception_Layer_v2(192, T(T(64), T(64, 64), T(64, 96),
                                    T("avg", 32)), "inception_3a/"))
    m.add(Inception_Layer_v2(256, T(T(64), T(64, 96), T(64, 96),
                                    T("avg", 64)), "inception_3b/"))
    m.add(Inception_Layer_v2(320, T(T(0), T(128, 160), T(64, 96),
                                    T("max", 0)), "inception_3c/"))
    m.add(Inception_Layer_v2(576, T(T(224), T(64, 96), T(96, 128),
                                    T("avg", 128)), "inception_4a/"))
    m.add(Inception_Layer_v2(576, T(T(192), T(96, 128), T(96, 128),
                                    T("avg", 128)), "inception_4b/"))
    m.add(Inception_Layer_v2(576, T(T(160), T(128, 160), T(128, 160),
                                    T("avg", 96)), "inception_4c/"))
    m.add(Inception_Layer_v2(576, T(T(96), T(128, 192), T(160, 192),
                                    T("avg", 96)), "inception_4d/"))
    m.add(Inception_Layer_v2(576, T(T(0), T(128, 192), T(192, 256),
                                    T("max", 0)), "inception_4e/"))
    m.add(Inception_Layer_v2(1024, T(T(352), T(192, 320), T(160, 224),
                                     T("avg", 128)), "inception_5a/"))
    m.add(Inception_Layer_v2(1024, T(T(352), T(192, 320), T(192, 224),
                                     T("max", 128)), "inception_5b/"))
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1, ceil_mode=True))
    m.add(nn.View(1024).set_num_input_dims(3))
    m.add(nn.Linear(1024, class_num).set_name("loss3/classifier"))
    m.add(nn.LogSoftMax())
    return m


def Inception_v2(class_num: int = 1000) -> nn.Sequential:
    """Full BN-GoogLeNet with both aux classifier heads
    (Inception_v2.scala:275-364); output channel-concats
    [main, aux2, aux1] like Inception_v1."""
    features1 = _v2_stem(nn.Sequential())
    features1.add(Inception_Layer_v2(192, T(T(64), T(64, 64), T(64, 96),
                                            T("avg", 32)), "inception_3a/"))
    features1.add(Inception_Layer_v2(256, T(T(64), T(64, 96), T(64, 96),
                                            T("avg", 64)), "inception_3b/"))
    features1.add(Inception_Layer_v2(320, T(T(0), T(128, 160), T(64, 96),
                                            T("max", 0)), "inception_3c/"))

    output1 = nn.Sequential()
    output1.add(nn.SpatialAveragePooling(5, 5, 3, 3, ceil_mode=True))
    _conv_bn(output1, 576, 128, 1, 1, name="loss1/conv")
    output1.add(nn.View(128 * 4 * 4).set_num_input_dims(3))
    output1.add(nn.Linear(128 * 4 * 4, 1024).set_name("loss1/fc"))
    output1.add(nn.ReLU(True))
    output1.add(nn.Linear(1024, class_num).set_name("loss1/classifier"))
    output1.add(nn.LogSoftMax())

    features2 = nn.Sequential()
    features2.add(Inception_Layer_v2(576, T(T(224), T(64, 96), T(96, 128),
                                            T("avg", 128)), "inception_4a/"))
    features2.add(Inception_Layer_v2(576, T(T(192), T(96, 128), T(96, 128),
                                            T("avg", 128)), "inception_4b/"))
    features2.add(Inception_Layer_v2(576, T(T(160), T(128, 160),
                                            T(128, 160), T("avg", 96)),
                                     "inception_4c/"))
    features2.add(Inception_Layer_v2(576, T(T(96), T(128, 192),
                                            T(160, 192), T("avg", 96)),
                                     "inception_4d/"))
    features2.add(Inception_Layer_v2(576, T(T(0), T(128, 192),
                                            T(192, 256), T("max", 0)),
                                     "inception_4e/"))

    output2 = nn.Sequential()
    output2.add(nn.SpatialAveragePooling(5, 5, 3, 3, ceil_mode=True))
    _conv_bn(output2, 1024, 128, 1, 1, name="loss2/conv")
    output2.add(nn.View(128 * 2 * 2).set_num_input_dims(3))
    output2.add(nn.Linear(128 * 2 * 2, 1024).set_name("loss2/fc"))
    output2.add(nn.ReLU(True))
    output2.add(nn.Linear(1024, class_num).set_name("loss2/classifier"))
    output2.add(nn.LogSoftMax())

    output3 = nn.Sequential()
    output3.add(Inception_Layer_v2(1024, T(T(352), T(192, 320),
                                           T(160, 224), T("avg", 128)),
                                   "inception_5a/"))
    output3.add(Inception_Layer_v2(1024, T(T(352), T(192, 320),
                                           T(192, 224), T("max", 128)),
                                   "inception_5b/"))
    output3.add(nn.SpatialAveragePooling(7, 7, 1, 1, ceil_mode=True))
    output3.add(nn.View(1024).set_num_input_dims(3))
    output3.add(nn.Linear(1024, class_num).set_name("loss3/classifier"))
    output3.add(nn.LogSoftMax())

    split2 = nn.Concat(2).set_name("split2")
    split2.add(output3)
    split2.add(output2)

    main_branch = nn.Sequential()
    main_branch.add(features2)
    main_branch.add(split2)

    split1 = nn.Concat(2).set_name("split1")
    split1.add(main_branch)
    split1.add(output1)

    model = nn.Sequential()
    model.add(features1)
    model.add(split1)
    return model
