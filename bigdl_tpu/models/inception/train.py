"""Inception-v1 ImageNet training recipe (models/inception/Train.scala:34-120
— SGD lr 0.01, Poly(0.5, ceil(1281167/batchSize)*maxEpoch) over the
ImageFolder/SeqFile pipeline; BASELINE config 4's training side).

    python -m bigdl_tpu.models.inception.train -f /imagenet/train -b 128
    python -m bigdl_tpu.models.inception.train --synthetic 64 -e 1
"""
from __future__ import annotations

import math


def main(argv=None):
    from bigdl_tpu.models._cli import (arrays_to_dataset, base_parser,
                                       load_model_or, wire_optimizer)

    import argparse

    ap = base_parser("Train Inception-v1 on ImageNet")
    ap.add_argument("--weightDecay", type=float, default=1e-4)
    ap.add_argument("--classNum", type=int, default=1000)
    ap.add_argument("--colorJitter", action=argparse.BooleanOptionalAction,
                    default=False,
                    help="random brightness/contrast/saturation "
                         "(ColorJitter.scala)")
    ap.add_argument("--lighting", action=argparse.BooleanOptionalAction,
                    default=False,
                    help="AlexNet PCA lighting noise (Lighting.scala)")
    ap.add_argument("--valFolder", default=None,
                    help="ImageNet val folder for per-epoch Top1/Top5 "
                         "(Train.scala:100 valSet)")
    args = ap.parse_args(argv)

    import numpy as np

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models.inception import Inception_v1_NoAuxClassifier
    from bigdl_tpu.optim import (LocalOptimizer, Poly, SGD, Top1Accuracy,
                                 Top5Accuracy)

    bs = args.batchSize or 32
    epochs = args.maxEpoch or 1
    if args.synthetic:
        rng = np.random.RandomState(0)
        imgs = rng.rand(args.synthetic, 3, 224, 224).astype(np.float32)
        lbls = rng.randint(1, args.classNum + 1,
                           args.synthetic).astype(np.float32)
        ds = arrays_to_dataset(imgs, lbls, bs)
        n_train = args.synthetic
        val_ds = None
    else:
        from bigdl_tpu.dataset import ImageFolderDataSet
        ds = ImageFolderDataSet(args.folder, batch_size=bs, crop=224,
                                scale=256, color_jitter=args.colorJitter,
                                lighting=args.lighting)
        n_train = ds.size()
        val_ds = ImageFolderDataSet(args.valFolder, batch_size=bs,
                                    crop=224, scale=256) \
            if args.valFolder else None

    model = load_model_or(
        args, lambda: Inception_v1_NoAuxClassifier(args.classNum))
    max_iter = int(math.ceil(n_train / bs)) * epochs
    optim = SGD(learning_rate=args.learningRate or 0.01,
                learning_rate_decay=0.0, weight_decay=args.weightDecay,
                momentum=0.9, dampening=0.0,
                learning_rate_schedule=Poly(0.5, max_iter))
    opt = LocalOptimizer(model, ds, nn.ClassNLLCriterion(), batch_size=bs)
    wire_optimizer(opt, args, optim, val_ds=val_ds,
                   val_methods=[Top1Accuracy(), Top5Accuracy()],
                   default_epochs=epochs)
    opt.optimize()
    print(f"final loss: {opt.driver_state['Loss']:.4f}")
    return model


if __name__ == "__main__":
    main()
