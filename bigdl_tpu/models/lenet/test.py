"""LeNet-5 MNIST evaluation (models/lenet/Test.scala).

    python -m bigdl_tpu.models.lenet.test -f /path/to/mnist --model snap
"""
from __future__ import annotations


def main(argv=None):
    from bigdl_tpu.models._cli import base_parser, evaluate_cli, mnist_arrays

    args = base_parser("Test LeNet-5 on MNIST").parse_args(argv)
    from bigdl_tpu.models.lenet import LeNet5
    return evaluate_cli(args, lambda: LeNet5(10),
                        mnist_arrays(args.folder, False, args.synthetic))


if __name__ == "__main__":
    main()
