"""LeNet-5 MNIST training recipe (models/lenet/Train.scala:29-90,
Utils.scala flags; BASELINE config 1).

    python -m bigdl_tpu.models.lenet.train -f /path/to/mnist -b 12 -e 15
    python -m bigdl_tpu.models.lenet.train --synthetic 256 -e 1
"""
from __future__ import annotations


def main(argv=None):
    from bigdl_tpu.models._cli import (
        arrays_to_dataset, base_parser, load_model_or, mnist_arrays,
        wire_optimizer)

    ap = base_parser("Train LeNet-5 on MNIST")
    ap.add_argument("-g", "--graphModel", action="store_true",
                    help="use the Graph form of LeNet-5")
    args = ap.parse_args(argv)

    import bigdl_tpu.nn as nn
    from bigdl_tpu.models.lenet import LeNet5, LeNet5_graph
    from bigdl_tpu.optim import (LocalOptimizer, Loss, SGD, Top1Accuracy,
                                 Top5Accuracy)

    bs = args.batchSize or 12
    tr = mnist_arrays(args.folder, True, args.synthetic)
    va = mnist_arrays(args.folder, False, args.synthetic or 0)
    model = load_model_or(
        args, lambda: (LeNet5_graph(10) if args.graphModel else LeNet5(10)))
    optim = SGD(learning_rate=args.learningRate or 0.05,
                learning_rate_decay=args.learningRateDecay or 0.0)
    opt = LocalOptimizer(model, arrays_to_dataset(*tr, bs),
                         nn.ClassNLLCriterion(), batch_size=bs)
    wire_optimizer(opt, args, optim,
                   val_ds=arrays_to_dataset(*va, bs),
                   val_methods=[Top1Accuracy(), Top5Accuracy(), Loss()],
                   default_epochs=15)
    opt.optimize()
    print(f"final loss: {opt.driver_state['Loss']:.4f}")
    return model


if __name__ == "__main__":
    main()
