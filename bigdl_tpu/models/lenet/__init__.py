"""LeNet-5 (reference: models/lenet/LeNet5.scala:23 seq, :39 graph)."""
from __future__ import annotations

import bigdl_tpu.nn as nn


def LeNet5(class_num: int = 10) -> nn.Sequential:
    """Sequential LeNet-5 exactly mirroring LeNet5.scala:23-37."""
    model = nn.Sequential()
    model.add(nn.Reshape((1, 28, 28))) \
        .add(nn.SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5")) \
        .add(nn.Tanh()) \
        .add(nn.SpatialMaxPooling(2, 2, 2, 2)) \
        .add(nn.Tanh()) \
        .add(nn.SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5")) \
        .add(nn.SpatialMaxPooling(2, 2, 2, 2)) \
        .add(nn.Reshape((12 * 4 * 4,))) \
        .add(nn.Linear(12 * 4 * 4, 100).set_name("fc1")) \
        .add(nn.Tanh()) \
        .add(nn.Linear(100, class_num).set_name("fc2")) \
        .add(nn.LogSoftMax())
    return model


def LeNet5_graph(class_num: int = 10) -> nn.Graph:
    """Graph-API variant (LeNet5.scala:39-53)."""
    inp = nn.Input()()
    x = nn.Reshape((1, 28, 28))(inp)
    x = nn.SpatialConvolution(1, 6, 5, 5).set_name("conv1_5x5")(x)
    x = nn.Tanh()(x)
    x = nn.SpatialMaxPooling(2, 2, 2, 2)(x)
    x = nn.Tanh()(x)
    x = nn.SpatialConvolution(6, 12, 5, 5).set_name("conv2_5x5")(x)
    x = nn.SpatialMaxPooling(2, 2, 2, 2)(x)
    x = nn.Reshape((12 * 4 * 4,))(x)
    x = nn.Linear(12 * 4 * 4, 100).set_name("fc1")(x)
    x = nn.Tanh()(x)
    x = nn.Linear(100, class_num).set_name("fc2")(x)
    out = nn.LogSoftMax()(x)
    return nn.Graph(inp, out)
