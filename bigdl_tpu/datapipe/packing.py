"""Sequence packing & length bucketing for variable-length token data.

Padding every sequence to the model's max length wastes the chip: at
PTB-like length distributions most of a ``[B, S]`` slab is pad tokens
that burn attention/FFN FLOPs and then get masked out of the loss. Two
standard remedies, both shape-static (one compiled program):

- **Packing** (:class:`SequencePacker`, :func:`pack_documents`): lay
  several documents head-to-tail in each row of a fixed ``[B, S]`` slab
  and carry a ``segment_ids`` plane so attention can refuse to look
  across document boundaries (the T5/tf.data "pack_dataset" technique).
  Rows also carry a ``positions`` plane that restarts at 0 per document,
  so positional embeddings match the unpacked forward exactly —
  together these make the packed forward **bit-exact** per token
  against running each document alone (asserted in
  tests/test_datapipe.py).
- **Length bucketing** (:class:`LengthBucketBatcher`): group sequences
  into a small ladder of length buckets and pad only to the bucket
  bound — lighter-weight (no segment mask needed, one doc per row),
  costs one compiled program per bucket. Bucket when documents are
  near-uniform or attention masks are unwelcome; pack when lengths are
  ragged and throughput matters (see docs/data.md for the math).

Batches come out as ``MiniBatch(input=[tokens, segment_ids, positions],
target=targets)`` — the 3-plane convention ``TransformerLM`` consumes
directly; ``targets`` are next-token ids inside each document with
``ignore_index`` at pad positions (pair with
``SequenceCrossEntropyCriterion(ignore_index=...)``).

Every emitted slab updates the ``data/packing/padding_efficiency``
gauge (real tokens / slab capacity, cumulative per stage) — the number
the DATA bench row and ``tools.diagnose`` report.
"""
from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu.dataset.sample import MiniBatch

_PAD_EFF = telemetry.gauge(
    "data/packing/padding_efficiency",
    "real tokens / slab capacity of emitted [B, S] token batches")


def padding_efficiency(lengths: Sequence[int], seq_len: int) -> float:
    """Real-token fraction of the pad-to-``seq_len`` layout: what a
    plain padded batcher achieves on documents of these lengths (the
    "before" number; a packer's "after" comes from its emitted slabs)."""
    lengths = [min(int(l), seq_len) for l in lengths]
    if not lengths:
        return 1.0
    return sum(lengths) / (len(lengths) * seq_len)


def _chunk_doc(doc: np.ndarray, max_tokens: int) -> List[np.ndarray]:
    """Split an over-long document into <= max_tokens pieces (the LM
    convention: a document longer than the slab trains as consecutive
    independent windows)."""
    return [doc[i:i + max_tokens] for i in range(0, len(doc), max_tokens)]


class _RowBuilder:
    """One [S] row being filled with consecutive documents."""

    def __init__(self, seq_len: int, pad_id: int, ignore_index: int):
        self.seq_len = seq_len
        self.pad_id = pad_id
        self.ignore_index = ignore_index
        self.tokens = np.full(seq_len, pad_id, np.int32)
        self.segments = np.zeros(seq_len, np.int32)
        self.positions = np.zeros(seq_len, np.int32)
        self.targets = np.full(seq_len, ignore_index, np.int32)
        self.used = 0
        self.n_docs = 0

    def fits(self, n: int) -> bool:
        return self.used + n <= self.seq_len

    def add(self, doc: np.ndarray) -> None:
        # each document of length L contributes x = doc[:-1], y = doc[1:]
        # (L-1 positions): every real token is predicted from its own
        # document's prefix, and no target ever crosses a boundary
        n = len(doc) - 1
        lo = self.used
        self.n_docs += 1
        self.tokens[lo:lo + n] = doc[:-1]
        self.targets[lo:lo + n] = doc[1:]
        self.segments[lo:lo + n] = self.n_docs
        self.positions[lo:lo + n] = np.arange(n, dtype=np.int32)
        self.used += n


def _iter_packed_rows(docs, seq_len: int, pad_id: int,
                      ignore_index: int):
    """THE next-fit packing loop (deterministic, order-preserving),
    shared by :func:`pack_documents` and :class:`SequencePacker` so the
    boundary rules (chunk at ``seq_len + 1``, drop docs shorter than 2
    tokens, close a row when the next piece no longer fits) can never
    drift between them. Yields completed :class:`_RowBuilder` rows."""
    cur = _RowBuilder(seq_len, pad_id, ignore_index)
    for doc in docs:
        doc = np.asarray(doc)
        for piece in _chunk_doc(doc, seq_len + 1):
            if len(piece) < 2:
                continue
            if not cur.fits(len(piece) - 1):
                yield cur
                cur = _RowBuilder(seq_len, pad_id, ignore_index)
            cur.add(piece)
    if cur.used:
        yield cur


def pack_documents(docs: Sequence[np.ndarray], seq_len: int, *,
                   pad_id: int = 0, ignore_index: int = -1
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                              np.ndarray]:
    """Pack integer token documents into fixed-shape slabs.

    Greedy next-fit (deterministic, order-preserving): fill the current
    row until the next document no longer fits, then open a new row.
    Documents shorter than 2 tokens are dropped (no next-token pair);
    longer than ``seq_len + 1`` are chunked.

    Returns ``(tokens, segment_ids, positions, targets)``, each
    ``[rows, seq_len]`` int32 — feed rows in groups of B as the 3-plane
    ``MiniBatch`` convention (see module doc).
    """
    if seq_len < 1:
        raise ValueError(f"seq_len must be >= 1, got {seq_len}")
    rows = list(_iter_packed_rows(docs, seq_len, pad_id, ignore_index))
    if not rows:
        z = np.zeros((0, seq_len), np.int32)
        return z, z.copy(), z.copy(), z.copy()
    _PAD_EFF.set(sum(r.used for r in rows) / (len(rows) * seq_len))
    return (np.stack([r.tokens for r in rows]),
            np.stack([r.segments for r in rows]),
            np.stack([r.positions for r in rows]),
            np.stack([r.targets for r in rows]))


def _emit(rows: List[_RowBuilder], stats, report: bool) -> MiniBatch:
    tokens = np.stack([r.tokens for r in rows])
    segs = np.stack([r.segments for r in rows])
    pos = np.stack([r.positions for r in rows])
    tgt = np.stack([r.targets for r in rows])
    stats[0] += sum(r.used for r in rows)
    stats[1] += len(rows) * rows[0].seq_len
    if report:
        _PAD_EFF.set(stats[0] / stats[1])
    return MiniBatch([tokens, segs, pos], tgt)


class SequencePacker:
    """Pipeline stage: token documents -> packed ``[B, S]`` MiniBatches
    (see module doc for the slab layout and target rules). Flushes at
    epoch end so the packing — like the shuffle — is a pure function of
    the epoch's record stream; a final partial batch is emitted with
    fully-padded spare rows (static shapes) unless ``drop_remainder``.
    """

    def __init__(self, seq_len: int, batch_rows: int, *, pad_id: int = 0,
                 ignore_index: int = -1, drop_remainder: bool = False):
        if seq_len < 1 or batch_rows < 1:
            raise ValueError("seq_len and batch_rows must be >= 1")
        self.seq_len = int(seq_len)
        self.batch_rows = int(batch_rows)
        self.pad_id = int(pad_id)
        self.ignore_index = int(ignore_index)
        self.drop_remainder = drop_remainder
        # cumulative [real_tokens, capacity] across the stage's lifetime
        self._stats = [0, 0]
        # detached (eval/count) copies clear this so validation slabs
        # never pollute the training feed's padding_efficiency gauge
        self.report_gauge = True

    @property
    def efficiency(self) -> float:
        """Cumulative real-token fraction of everything emitted so far
        (the value the ``data/packing/padding_efficiency`` gauge holds)."""
        return self._stats[0] / self._stats[1] if self._stats[1] else 1.0

    def __call__(self, it: Iterator, epoch: int) -> Iterator[MiniBatch]:
        done: List[_RowBuilder] = []
        for row in _iter_packed_rows(it, self.seq_len, self.pad_id,
                                     self.ignore_index):
            done.append(row)
            if len(done) == self.batch_rows:
                yield _emit(done, self._stats, self.report_gauge)
                done = []
        if done and not self.drop_remainder:
            while len(done) < self.batch_rows:  # static shapes: pad rows
                done.append(_RowBuilder(self.seq_len, self.pad_id,
                                        self.ignore_index))
            yield _emit(done, self._stats, self.report_gauge)


class LengthBucketBatcher:
    """Pipeline stage: token documents -> length-bucketed padded
    MiniBatches (one document per row, padded to its bucket's bound).

    ``boundaries`` are ascending inclusive upper bounds; documents
    longer than the last bound are truncated to it. Each bucket fills
    independently and emits ``[batch_size, bound]`` batches in the
    3-plane convention (segment id 1 on real tokens, 0 on pad), so the
    packed and bucketed paths feed the identical model surface. At
    epoch end, partial buckets flush (in boundary order) unless
    ``drop_remainder``."""

    def __init__(self, boundaries: Sequence[int], batch_size: int, *,
                 pad_id: int = 0, ignore_index: int = -1,
                 drop_remainder: bool = False):
        bounds = [int(b) for b in boundaries]
        if not bounds or sorted(bounds) != bounds or bounds[0] < 2:
            raise ValueError(
                f"boundaries must be ascending and >= 2, got {bounds}")
        self.boundaries = bounds
        self.batch_size = int(batch_size)
        self.pad_id = int(pad_id)
        self.ignore_index = int(ignore_index)
        self.drop_remainder = drop_remainder
        self._stats = [0, 0]
        self.report_gauge = True

    @property
    def efficiency(self) -> float:
        """Cumulative real-token fraction of emitted batches."""
        return self._stats[0] / self._stats[1] if self._stats[1] else 1.0

    def _bucket_of(self, n: int) -> int:
        for i, b in enumerate(self.boundaries):
            if n <= b:
                return i
        return len(self.boundaries) - 1

    def _emit_bucket(self, bound: int, docs: List[np.ndarray]) -> MiniBatch:
        b = len(docs)
        tokens = np.full((b, bound), self.pad_id, np.int32)
        segs = np.zeros((b, bound), np.int32)
        pos = np.zeros((b, bound), np.int32)
        tgt = np.full((b, bound), self.ignore_index, np.int32)
        for i, doc in enumerate(docs):
            n = len(doc) - 1
            tokens[i, :n] = doc[:-1]
            tgt[i, :n] = doc[1:]
            segs[i, :n] = 1
            pos[i, :n] = np.arange(n, dtype=np.int32)
            self._stats[0] += n
        self._stats[1] += b * bound
        if self.report_gauge:
            _PAD_EFF.set(self._stats[0] / self._stats[1])
        return MiniBatch([tokens, segs, pos], tgt)

    def __call__(self, it: Iterator, epoch: int) -> Iterator[MiniBatch]:
        buckets: List[List[np.ndarray]] = [[] for _ in self.boundaries]
        top = self.boundaries[-1]
        for doc in it:
            doc = np.asarray(doc)
            if len(doc) < 2:
                continue
            if len(doc) > top + 1:
                doc = doc[:top + 1]
            i = self._bucket_of(len(doc) - 1)
            buckets[i].append(doc)
            if len(buckets[i]) == self.batch_size:
                yield self._emit_bucket(self.boundaries[i], buckets[i])
                buckets[i] = []
        if not self.drop_remainder:
            for i, docs in enumerate(buckets):
                if docs:
                    yield self._emit_bucket(self.boundaries[i], docs)
