"""Device staging — render pipeline output into the window layout.

The last pipeline hop: turn the host-side MiniBatch stream into device
buffers shaped for the consumer. Two renderings:

- :func:`stage_batches` — stage each ``[B, ...]`` MiniBatch to device
  ``size`` steps ahead (the classic double-buffer; rides
  ``dataset.prefetch.device_prefetch`` with its stop-event/drain
  abandonment semantics and the ``prefetch/stage`` faultpoint).
- :func:`stage_windows` — group ``k`` consecutive equal-shape batches
  into ONE ``[K, B, ...]`` stacked buffer (``stack_windows``) and stage
  that: the exact layout a fused ``lax.scan`` over ``k`` train steps
  consumes in one dispatch (``Optimizer.set_steps_per_sync`` /
  ``bench.py``'s scanned chunks).

Both return iterators of device-resident MiniBatches. Note the
Optimizer's own host-feed windowing stacks on the HOST and must see
host arrays — feed it the un-staged pipeline (``Pipeline.as_dataset``)
and let it stage; these stages are for external scan/serving consumers
that own their dispatch loop.
"""
from __future__ import annotations

from typing import Iterator, Optional

from bigdl_tpu.dataset.prefetch import device_prefetch, stack_windows
from bigdl_tpu.dataset.sample import MiniBatch


def stage_batches(it: Iterator[MiniBatch], *, size: int = 2,
                  sharding=None) -> Iterator[MiniBatch]:
    """Stage MiniBatches to device ``size`` steps ahead (see module
    doc); ``sharding`` lays the batch dim across a mesh."""
    return device_prefetch(it, size=size, sharding=sharding)


def stage_windows(it: Iterator[MiniBatch], k: int, *, size: int = 2,
                  sharding: Optional[object] = None
                  ) -> Iterator[MiniBatch]:
    """Stack ``k``-batch windows into ``[K, B, ...]`` buffers and stage
    them to device (see module doc). A shape change (e.g. a short final
    batch) closes a window early, exactly like ``stack_windows``; on a
    mesh pass the axis-1 batch sharding (the window axis stays
    unsharded)."""
    return device_prefetch(stack_windows(it, k), size=size,
                           sharding=sharding)
