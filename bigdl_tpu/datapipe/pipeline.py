"""Composable streaming pipeline: reader -> stages -> (optional) device.

The tf.data-shaped assembly surface over the datapipe pieces::

    from bigdl_tpu import datapipe as dp

    pipe = (dp.Pipeline(dp.TextLineReader(shards, seed=7))
              .map(tokenize_to_ids)
              .shuffle(buffer_size=4096, seed=7)
              .pack(seq_len=512, batch_rows=8))
    ds = pipe.as_dataset(batch_size=8)      # drop-in Optimizer DataSet
    # or drive a scan loop yourself:
    for window in pipe.staged(k=8):          # [K, B, ...] device buffers
        ...

Stages are ``(iterator, epoch) -> iterator`` callables constructed
fresh each epoch, so per-epoch seeding (shuffle permutations, packer
flushes) is structural: the stream is a pure function of
``(seed, epoch, cursor)`` and therefore bit-identical across runs,
across checkpoint/resume, and across the windowed driver's K.

Checkpoint/resume rides the source reader's cursor: ``state()`` /
``restore()`` round-trip through the optimizer's ``driver_state`` JSON
(see ``Optimizer._checkpoint``). The cursor names the next unread
SHARD record — records already pulled into a shuffle buffer or a
partially packed row at snapshot time sit before it and are SKIPPED on
resume (a bounded loss of at most ``buffer_size`` plus one batch's
worth per recovery, not silent reordering). Resume at epoch boundaries
is bit-exact — the determinism contract in docs/data.md.
"""
from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence

from bigdl_tpu.dataset.dataset import PipelineDataSet
from bigdl_tpu.dataset.sample import MiniBatch, Sample
from bigdl_tpu.datapipe.packing import LengthBucketBatcher, SequencePacker
from bigdl_tpu.datapipe.readers import ShardedReader
from bigdl_tpu.datapipe.shuffle import WindowShuffle


class _MapStage:
    def __init__(self, fn: Callable):
        self.fn = fn

    def __call__(self, it: Iterator, epoch: int) -> Iterator:
        return map(self.fn, it)


class _FilterStage:
    def __init__(self, pred: Callable):
        self.pred = pred

    def __call__(self, it: Iterator, epoch: int) -> Iterator:
        return filter(self.pred, it)


class _BatchStage:
    """Samples -> MiniBatches (``SampleToMiniBatch`` with the epoch-
    aware stage signature)."""

    def __init__(self, batch_size: int, drop_remainder: bool = False,
                 feature_padding=None, label_padding=None):
        from bigdl_tpu.dataset.transformer import SampleToMiniBatch
        self.drop_remainder = drop_remainder
        self._b = SampleToMiniBatch(batch_size,
                                    feature_padding=feature_padding,
                                    label_padding=label_padding,
                                    drop_remainder=drop_remainder)

    def __call__(self, it: Iterator, epoch: int) -> Iterator[MiniBatch]:
        return self._b.apply(it)


class Pipeline:
    """Immutable-ish builder: each combinator returns ``self`` with the
    stage appended (chain in one expression; a pipeline instance is ONE
    stream — build a fresh one per concurrent consumer)."""

    def __init__(self, source: ShardedReader,
                 stages: Optional[Sequence] = None):
        self.source = source
        self.stages: List = list(stages or [])

    # ---- combinators -----------------------------------------------------
    def map(self, fn: Callable) -> "Pipeline":
        """Apply ``fn`` per record (tokenize, decode, augment...)."""
        self.stages.append(_MapStage(fn))
        return self

    def filter(self, pred: Callable) -> "Pipeline":
        """Keep records where ``pred(record)`` is true."""
        self.stages.append(_FilterStage(pred))
        return self

    def shuffle(self, buffer_size: int, seed: int = 0) -> "Pipeline":
        """Windowed seeded shuffle (``datapipe.shuffle.WindowShuffle``)."""
        self.stages.append(WindowShuffle(buffer_size, seed))
        return self

    def batch(self, batch_size: int, *, drop_remainder: bool = False,
              feature_padding=None, label_padding=None) -> "Pipeline":
        """Group :class:`Sample` records into MiniBatches."""
        self.stages.append(_BatchStage(batch_size, drop_remainder,
                                       feature_padding, label_padding))
        return self

    def pack(self, seq_len: int, batch_rows: int, **kw) -> "Pipeline":
        """Pack token documents into ``[batch_rows, seq_len]`` slabs
        with segment masks (``datapipe.packing.SequencePacker``)."""
        self.stages.append(SequencePacker(seq_len, batch_rows, **kw))
        return self

    def bucket(self, boundaries: Sequence[int], batch_size: int,
               **kw) -> "Pipeline":
        """Length-bucketed padded batching
        (``datapipe.packing.LengthBucketBatcher``)."""
        self.stages.append(LengthBucketBatcher(boundaries, batch_size,
                                               **kw))
        return self

    # ---- cursor ----------------------------------------------------------
    def state(self) -> dict:
        """Serializable resume point (the source reader's cursor)."""
        return self.source.state()

    def restore(self, state: dict) -> "Pipeline":
        """Continue from a :meth:`state` snapshot (same seeds/shards ⇒
        bit-identical continuation at shard-record granularity)."""
        self.source.restore(state)
        return self

    # ---- iteration -------------------------------------------------------
    def iterate(self, loop: bool = False) -> Iterator:
        """The host-side record/batch stream; ``loop=True`` crosses
        epochs forever (stages rebuilt + reseeded per epoch)."""
        while True:
            epoch = self.source.epoch
            it = self.source.read_epoch()
            for stage in self.stages:
                it = stage(it, epoch)
            yield from it
            if not loop:
                return

    def __iter__(self) -> Iterator:
        return self.iterate(loop=False)

    def iterate_detached(self) -> Iterator:
        """One repeatable epoch-0 pass that does NOT touch this
        pipeline's cursor: the source is shallow-copied (shard lists /
        arrays shared read-only) with its own fresh cursor, so every
        call yields the identical stream — the side-effect-free
        iteration ``PipelineDataSet.data(train=False)`` hands to
        validation/scoring consumers. Stateful stages (packers,
        bucketers) are copied too, with fresh stats and gauge reporting
        off, so an eval pass never folds its slabs into the TRAINING
        feed's cumulative padding_efficiency."""
        import copy
        src = copy.copy(self.source)
        src._cursor = {"epoch": 0, "spos": 0, "offset": 0}
        stages = []
        for stage in self.stages:
            if hasattr(stage, "_stats"):
                stage = copy.copy(stage)
                stage._stats = [0, 0]
                stage.report_gauge = False
            stages.append(stage)
        return Pipeline(src, stages).iterate(loop=False)

    def staged(self, k: Optional[int] = None, *, loop: bool = True,
               size: int = 2, sharding=None) -> Iterator[MiniBatch]:
        """Device-resident stream: plain staged batches, or — with
        ``k`` — ``[K, B, ...]`` stacked windows for a fused scan
        consumer (``datapipe.stage``)."""
        from bigdl_tpu.datapipe.stage import stage_batches, stage_windows
        it = self.iterate(loop=loop)
        if k is None:
            return stage_batches(it, size=size, sharding=sharding)
        return stage_windows(it, k, size=size, sharding=sharding)

    # ---- dataset adapter -------------------------------------------------
    def count_epoch_records(self) -> int:
        """Records (MiniBatch = one record ⇒ its row count) one epoch-0
        pass emits — a detached cold scan that leaves the cursor alone
        (prefer passing ``size=`` to :meth:`as_dataset` when you know
        it)."""
        n = 0
        for item in self.iterate_detached():
            n += item.size() if isinstance(item, MiniBatch) else 1
        return n

    def as_dataset(self, size: Optional[int] = None,
                   batch_size: Optional[int] = None) -> PipelineDataSet:
        """Drop-in ``AbstractDataSet`` over this pipeline (feed it to an
        Optimizer). ``size`` is records per epoch in the units the
        stream yields (MiniBatch rows when batched/packed). Omitted, it
        is derived from the reader's cheap ``num_records()`` when every
        stage is count-preserving — map, shuffle, and non-dropping
        ``batch`` (total MiniBatch ROWS == source records); otherwise
        one cold scan counts an epoch — for a large corpus behind a
        filtering or packing stage, pass ``size=`` explicitly."""
        def preserves_count(stage) -> bool:
            if isinstance(stage, (_MapStage, WindowShuffle)):
                return True
            return isinstance(stage, _BatchStage) \
                and not stage.drop_remainder

        if size is None:
            if all(preserves_count(s) for s in self.stages):
                size = self.source.num_records()
            if size is None:
                size = self.count_epoch_records()
        return PipelineDataSet(self, size, batch_size=batch_size)
