"""Sharded streaming readers — records without materializing an epoch.

The reference's data plane was Spark RDD partitions streamed off
HDFS/S3 per epoch (BigDL paper §data-parallel ingestion; DataSet.scala's
SeqFileFolder reads record shards at cluster rates). The TPU-native
equivalent is a reader over an ordered list of **shards** (text files,
SequenceFile shards, array row-ranges) that yields records one at a
time with a tiny serializable **cursor** — so a terabyte corpus streams
through a bounded amount of host RAM, multi-host runs split shards by
process, and checkpoint/resume carries the exact read position in the
same JSON host-state the optimizer already persists (the
``driver_state`` block of the checkpoint MANIFEST format).

Cursor contract: ``state()`` returns ``{"epoch", "spos", "offset"}`` —
the epoch number, the position in this epoch's (seeded, per-epoch
permuted) shard order, and the record offset inside that shard.
``restore(state)`` on a fresh reader continues the stream bit-exactly:
same seed ⇒ same shard order ⇒ same records in the same order.

Epoch boundaries are explicit (``read_epoch``) so downstream stages —
the windowed shuffle's per-epoch permutation, sequence packers — flush
and reseed per epoch, which is what keeps the stream a pure function of
``(seed, epoch, position)`` no matter how it was paused or windowed.
"""
from __future__ import annotations

import itertools
from typing import Iterator, List, Optional, Sequence

import numpy as np

import bigdl_tpu.telemetry as telemetry
from bigdl_tpu import faults
from bigdl_tpu.dataset.sample import Sample

_RECORDS = telemetry.counter("data/datapipe/records",
                             "records yielded by streaming readers")


class ShardedReader:
    """Base streaming reader over an ordered shard list.

    Subclasses implement :meth:`_open` (shard -> record iterator) and
    optionally :meth:`_shard_len` (for :meth:`num_records` without a
    scan). Multi-host: process ``process_index`` of ``process_count``
    reads shards ``[process_index::process_count]`` — the reader-side
    form of the optimizer's per-process batch-row contribution.

    ``shuffle_shards`` permutes the local shard order with a seeded,
    per-epoch permutation (``fold_in``-style: epoch joins the seed), so
    every epoch visits shards in a fresh but reproducible order.
    """

    def __init__(self, shards: Sequence, *, process_index: int = 0,
                 process_count: int = 1, shuffle_shards: bool = True,
                 seed: int = 0):
        self.all_shards = list(shards)
        if not self.all_shards:
            raise ValueError("reader needs at least one shard")
        if not (0 <= process_index < process_count):
            raise ValueError(
                f"process_index {process_index} out of range for "
                f"process_count {process_count}")
        self.process_index = process_index
        self.process_count = process_count
        self.local_shards = self.all_shards[process_index::process_count]
        if not self.local_shards:
            raise ValueError(
                f"process {process_index}/{process_count} has no shards "
                f"({len(self.all_shards)} total); use fewer processes or "
                "more shards")
        self.shuffle_shards = shuffle_shards
        self.seed = int(seed)
        self._cursor = {"epoch": 0, "spos": 0, "offset": 0}

    # ---- subclass surface ------------------------------------------------
    def _open(self, shard) -> Iterator:
        """Iterate one shard's records from the beginning."""
        raise NotImplementedError

    def _shard_len(self, shard) -> Optional[int]:
        """Record count of one shard, or None when only a scan can tell."""
        return None

    # ---- cursor ----------------------------------------------------------
    def state(self) -> dict:
        """Serializable read position (plain ints — rides the checkpoint
        ``driver_state`` JSON unchanged). Safe to call from a thread
        other than the reading one (the staged() consumer): shard and
        epoch transitions rebind the cursor dict atomically, so a
        snapshot is always internally consistent."""
        return dict(self._cursor)

    def restore(self, state: dict) -> "ShardedReader":
        """Continue from a :meth:`state` snapshot (same seed/shard list
        required for bit-identical continuation)."""
        self._cursor = {k: int(state[k])
                        for k in ("epoch", "spos", "offset")}
        return self

    @property
    def epoch(self) -> int:
        return self._cursor["epoch"]

    def _epoch_order(self, epoch: int) -> List[int]:
        if not self.shuffle_shards or len(self.local_shards) == 1:
            return list(range(len(self.local_shards)))
        rng = np.random.default_rng((self.seed, epoch))
        return [int(i) for i in rng.permutation(len(self.local_shards))]

    # ---- streaming -------------------------------------------------------
    def read_epoch(self) -> Iterator:
        """Yield the rest of the CURRENT epoch from the cursor position,
        then advance the cursor to the next epoch's start. The cursor
        observed between two yields always names the NEXT unread record,
        so a checkpoint taken mid-stream resumes without replay or
        skip."""
        epoch = self._cursor["epoch"]
        order = self._epoch_order(epoch)
        while self._cursor["spos"] < len(order):
            shard = self.local_shards[order[self._cursor["spos"]]]
            it = self._open(shard)
            skip = self._cursor["offset"]
            if skip:
                it = itertools.islice(it, skip, None)
            # the span covers the shard's whole STREAM window (open
            # through exhaustion — pull-based, so it includes consumer
            # time between pulls); the record counter flushes once per
            # shard so the hot loop pays no per-record lock
            n = 0
            with telemetry.span("data/datapipe_shard", shard=str(shard)):
                try:
                    for rec in it:
                        # scripted-death site for the chaos/faults
                        # suite: a read that dies mid-shard must surface
                        # as an error, never as a silently short epoch
                        faults.point("datapipe/read")
                        self._cursor["offset"] += 1
                        n += 1
                        yield rec
                finally:
                    _RECORDS.inc(n)
            # ONE atomic rebind, never spos/offset mutated separately: a
            # state() snapshot from another thread (the staged()
            # prefetch stager runs this generator off-thread) must never
            # pair the next shard's spos with the old shard's offset
            self._cursor = {"epoch": epoch,
                            "spos": self._cursor["spos"] + 1,
                            "offset": 0}
        self._cursor = {"epoch": epoch + 1, "spos": 0, "offset": 0}

    def read(self, *, loop: bool = False) -> Iterator:
        """Stream records; ``loop=True`` crosses epoch boundaries forever
        (each epoch re-permutes the shard order)."""
        while True:
            yield from self.read_epoch()
            if not loop:
                return

    def num_records(self) -> Optional[int]:
        """Records per LOCAL epoch when shard lengths are known cheaply;
        None otherwise (``count_records`` scans)."""
        total = 0
        for s in self.local_shards:
            n = self._shard_len(s)
            if n is None:
                return None
            total += n
        return total

    def count_records(self) -> int:
        """Records per LOCAL epoch, scanning the shards if needed; the
        cursor is left untouched."""
        known = self.num_records()
        if known is not None:
            return known
        return sum(sum(1 for _ in self._open(s)) for s in self.local_shards)


class TextLineReader(ShardedReader):
    """Stream non-empty lines from text files (one shard per file) —
    the streaming replacement for ``read_words``-style whole-file
    materialization; feed it to a tokenizing ``map`` stage."""

    def __init__(self, paths: Sequence[str], *, strip: bool = True,
                 keep_empty: bool = False, encoding: str = "utf-8", **kw):
        super().__init__(paths, **kw)
        self.strip = strip
        self.keep_empty = keep_empty
        self.encoding = encoding

    def _open(self, shard) -> Iterator[str]:
        with open(shard, encoding=self.encoding) as f:
            for line in f:
                if self.strip:
                    line = line.rstrip("\n")
                if line or self.keep_empty:
                    yield line


class ArrayRecordReader(ShardedReader):
    """Stream :class:`Sample` rows from in-memory arrays, sharded into
    row ranges — the streaming face of ``DataSet.array`` (same records,
    but composable with cursors/shuffle/packing and never copied into a
    per-epoch list)."""

    def __init__(self, features: np.ndarray,
                 labels: Optional[np.ndarray] = None, *,
                 shard_size: int = 1024, **kw):
        features = np.asarray(features)
        n = len(features)
        if labels is not None and len(labels) < n:
            raise ValueError("labels shorter than features")
        shard_size = max(1, int(shard_size))
        shards = [(i, min(i + shard_size, n))
                  for i in range(0, n, shard_size)]
        super().__init__(shards, **kw)
        self.features = features
        self.labels = None if labels is None else np.asarray(labels)

    def _shard_len(self, shard) -> int:
        return shard[1] - shard[0]

    def _open(self, shard) -> Iterator[Sample]:
        lo, hi = shard
        for i in range(lo, hi):
            yield Sample(self.features[i],
                         None if self.labels is None else self.labels[i])


class SeqFileImageReader(ShardedReader):
    """Stream ``(jpeg_bytes, label, name)`` records from Hadoop
    SequenceFile shards (the reference's packed-ImageNet wire format,
    ``dataset.seqfile``) — one shard per ``.seq`` file."""

    def _open(self, shard) -> Iterator:
        from bigdl_tpu.dataset.seqfile import read_seq_image_records
        return read_seq_image_records(shard)
