"""Windowed global shuffle — bounded memory, seeded, per-epoch exact.

The reference shuffled per epoch by re-permuting cached RDD partitions
(DataSet.scala CachedDistriDataSet.shuffle); a streaming pipeline cannot
hold an epoch to permute it, so the classic substitute is a **bounded
shuffle buffer** (tf.data's ``shuffle(buffer_size)``): keep ``buffer_size``
records in flight, emit a uniformly chosen one as each new record
arrives, and drain with a final permutation at epoch end.

Two properties the generic version lacks are load-bearing here:

- **Seeded determinism.** The buffer's RNG derives from
  ``(seed, epoch)`` — ``np.random.default_rng((seed, epoch))``, the
  host-side analogue of ``fold_in(key, epoch)`` — so the same seed
  yields a bit-identical record order across runs, across
  checkpoint/resume at epoch boundaries, and across the windowed
  driver's K (the shuffle is host-side and upstream of window
  stacking, so K never reorders it). The ``unseeded-shuffle`` lint
  rule enforces this property across the dataset/datapipe code.
- **Per-epoch reseeding.** Each epoch is an independent deterministic
  permutation — epoch 2 of run A equals epoch 2 of run B without
  replaying epoch 1.

The buffer depth lands in the ``data/shuffle/buffer_depth`` gauge so
``tools.diagnose`` can show a starved shuffle (depth pinned near zero —
upstream too slow) distinctly from compute time.
"""
from __future__ import annotations

from typing import Iterator

import numpy as np

import bigdl_tpu.telemetry as telemetry

_BUFFER_DEPTH = telemetry.gauge(
    "data/shuffle/buffer_depth",
    "records currently held by the windowed shuffle buffer")


class WindowShuffle:
    """Pipeline stage: bounded seeded shuffle (see module doc).

    ``buffer_size`` bounds host memory (records held at once) and the
    mixing radius: a record can move at most ~``buffer_size`` positions
    forward, so size it to several batches at minimum. ``buffer_size=1``
    degenerates to pass-through.
    """

    def __init__(self, buffer_size: int, seed: int = 0):
        if buffer_size < 1:
            raise ValueError(
                f"shuffle buffer_size must be >= 1, got {buffer_size}")
        self.buffer_size = int(buffer_size)
        self.seed = int(seed)

    def __call__(self, it: Iterator, epoch: int) -> Iterator:
        rng = np.random.default_rng((self.seed, int(epoch)))
        buf = []
        # the depth gauge updates on TRANSITIONS (filled, drain start,
        # drained), not per record — the steady-state hot loop pays no
        # instrument lock (the PR-4 hot-path telemetry discipline)
        for rec in it:
            if len(buf) < self.buffer_size:
                buf.append(rec)
                if len(buf) == self.buffer_size:
                    _BUFFER_DEPTH.set(len(buf))
                continue
            j = int(rng.integers(self.buffer_size))
            out, buf[j] = buf[j], rec
            yield out
        # epoch end: drain with one final seeded permutation so the tail
        # is as shuffled as the steady state
        _BUFFER_DEPTH.set(len(buf))
        order = rng.permutation(len(buf))
        for j in order:
            yield buf[int(j)]
        _BUFFER_DEPTH.set(0)
