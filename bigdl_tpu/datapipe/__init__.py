"""bigdl_tpu.datapipe — high-throughput streaming data plane.

The host-feed successor to whole-epoch ``DataSet`` lists, in the
lineage of the reference's Spark-RDD data plane (partitioned, streamed,
shuffled per epoch) and tf.data's composable input pipelines — done
JAX-native with seeded determinism so the framework's K=1-vs-K=8
windowed-exactness guarantees extend through the data feed:

- **Sharded streaming readers** (``readers``): text / SequenceFile /
  array sources streamed record by record with serializable per-shard
  cursors (checkpoint/resume), multi-host shard splitting, per-epoch
  shard-order permutation.
- **Windowed global shuffle** (``shuffle``): bounded buffer, seeded and
  reseeded per epoch — same seed ⇒ bit-identical record order across
  runs and across the windowed driver's K.
- **Sequence packing & length bucketing** (``packing``): variable-length
  token documents into fixed ``[B, S]`` slabs with segment-id masks
  (packed forward bit-exact per token vs each document alone), or
  length-bucketed padded batches — both feed the same 3-plane
  ``TransformerLM`` input convention.
- **Device staging** (``stage``): batches or ``[K, B, ...]`` stacked
  windows staged to device ahead of compute, riding the prefetch
  stager's stop-event/drain semantics.
- **Pipeline** (``pipeline``): the builder tying them together, plus
  ``as_dataset()`` — any pipeline as a drop-in Optimizer ``DataSet``
  with cursor checkpointing through the training loop.

See docs/data.md for the determinism contract and the pack-vs-bucket
decision math; the ``data/packing/padding_efficiency`` and
``data/shuffle/buffer_depth`` gauges feed ``tools.diagnose`` and the
bench DATA row.
"""
from bigdl_tpu.datapipe.readers import (ArrayRecordReader, SeqFileImageReader,
                                        ShardedReader, TextLineReader)
from bigdl_tpu.datapipe.shuffle import WindowShuffle
from bigdl_tpu.datapipe.packing import (LengthBucketBatcher, SequencePacker,
                                        pack_documents, padding_efficiency)
from bigdl_tpu.datapipe.stage import stage_batches, stage_windows
from bigdl_tpu.datapipe.pipeline import Pipeline
from bigdl_tpu.dataset.dataset import PipelineDataSet

__all__ = [
    "ShardedReader", "TextLineReader", "ArrayRecordReader",
    "SeqFileImageReader", "WindowShuffle", "SequencePacker",
    "LengthBucketBatcher", "pack_documents", "padding_efficiency",
    "stage_batches", "stage_windows", "Pipeline", "PipelineDataSet",
]
