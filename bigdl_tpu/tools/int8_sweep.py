"""On-chip int8-vs-bf16 shape sweep (BASELINE.md's quantization verdict
as a MEASUREMENT, not an assertion — the reference's BigQuant was a
measured speed feature on Xeon, nn/quantized/Linear.scala:77-88; this
establishes where, if anywhere, the int8 path wins on this device).

Sweeps Linear (batch x in x out) over the pallas int8 fused matmul and
the plain jnp int8 path vs the bf16 MXU matmul, plus one conv case.
Each timing is a scanned chunk with a value fetch (honest-sync on the
tunnel).

    python -m bigdl_tpu.tools.int8_sweep [iters]

.. deprecated:: PR 9
    Scale estimation moved to ``bigdl_tpu/precision/calibrate.py`` —
    the ONE int8 calibration path (weights via ``calibrate_weight``,
    activations via ``collect_activation_scales``; both derive from
    ``ops/quant.scale_from_amax``). This tool now delegates its weight
    scales there and remains CLI-compatible, but new code should
    calibrate through ``precision.calibrate`` / ``ModelRegistry.load(
    quantize=True, calibration=...)`` rather than calling
    ``quantize_symmetric`` directly. For choosing a precision policy
    from measurements, prefer the profile-guided autotuner:
    ``python -m bigdl_tpu.tools.autotune`` (docs/autotune.md).
"""
import json
import sys
import time



def _time_chunk(fn, args, scan: int, iters: int):
    import functools

    import jax
    import jax.numpy as jnp
    from jax import lax

    @functools.partial(jax.jit, static_argnums=())
    def chunk(*a):
        def body(carry, _):
            # the carry perturbs the first operand so every scan step
            # DEPENDS on the previous one — a loop-invariant body gets
            # hoisted by XLA and the scan would time nothing but adds
            a0 = a[0] + jnp.asarray(carry, a[0].dtype)
            r = fn(a0, *a[1:])
            # the timing carry is a deliberate f32 scalar reduction —
            # it measures the kernel, it is not on a policy's hot path
            return r.astype(jnp.float32).sum() * 1e-30, None  # bigdl: disable=implicit-upcast-in-trace
        out, _ = lax.scan(body, jnp.float32(0.0), None, length=scan)
        return out

    r = chunk(*args)
    float(r)  # compile + warm
    t0 = time.time()
    for _ in range(iters):
        float(chunk(*args))
    return (time.time() - t0) / (iters * scan)


def main(argv=None):
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.ops.pallas_kernels import pallas_quantized_matmul
    from bigdl_tpu.ops.quant import quantized_linear
    # the one scale-estimation path (precision/calibrate.py delegates to
    # ops/quant's max-abs rule): weight AND activation scales below
    # come from here — this tool holds no quantization math of its own
    from bigdl_tpu.precision.calibrate import (calibrate_activation,
                                               calibrate_weight)

    import os
    args = argv if argv is not None else sys.argv[1:]
    iters = int(args[0]) if args else 3
    print("# int8_sweep measures kernels only; to pick a precision "
          "policy from measurements use: python -m "
          "bigdl_tpu.tools.autotune")
    # scan long enough that compute dominates the ~100 ms tunnel
    # roundtrip per chunk; at scan 8 every shape measured ~13 ms/step
    # (pure dispatch latency) regardless of FLOPs
    scan = int(os.environ.get("BENCH_SCAN", 64))
    on_tpu = jax.devices()[0].platform == "tpu"

    shapes = [
        # (batch, in, out) — memory-bound tall/skinny through MXU-bound
        (256, 1024, 1024),
        (1024, 1024, 1024),
        (4096, 1024, 1024),
        (256, 4096, 4096),
        (1024, 4096, 4096),
        (4096, 4096, 4096),
        (16384, 2048, 2048),
        (256, 8192, 8192),
    ]
    from bigdl_tpu.tools.synthetic import gaussian_matrix

    rows = []
    for b, cin, cout in shapes:
        x = jnp.asarray(gaussian_matrix((b, cin)))
        w = jnp.asarray(gaussian_matrix((cout, cin), scale=0.05, seed=1))
        w_q, w_s = calibrate_weight(w, axis=0)  # per-out-channel
        x16 = x.astype(jnp.bfloat16)
        w16 = w.T.astype(jnp.bfloat16)

        def bf16_mm(x16, w16):
            return x16 @ w16

        t_bf16 = _time_chunk(bf16_mm, (x16, w16), scan, iters)

        def jnp_int8(x, w_q, w_s):
            return quantized_linear(x, w_q, w_s)

        t_jnp8 = _time_chunk(jnp_int8, (x, w_q, w_s), scan, iters)

        t_pl8 = None
        if on_tpu:
            x_q, x_s = calibrate_activation(x, axis=0)  # per-sample rows

            def pl8(x_q, w_q, x_s, w_s):
                return pallas_quantized_matmul(x_q, w_q, x_s, w_s)

            try:
                t_pl8 = _time_chunk(pl8, (x_q, w_q, x_s, w_s), scan,
                                    iters)
            except Exception as e:
                t_pl8 = f"failed: {type(e).__name__}"
        best8 = min([t for t in (t_jnp8, t_pl8)
                     if isinstance(t, float)])
        row = {"shape": [b, cin, cout],
               "bf16_ms": round(t_bf16 * 1e3, 3),
               "jnp_int8_ms": round(t_jnp8 * 1e3, 3),
               "pallas_int8_ms": (round(t_pl8 * 1e3, 3)
                                  if isinstance(t_pl8, float) else t_pl8),
               "int8_speedup_vs_bf16": round(t_bf16 / best8, 3)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    # one conv case: ResNet-50's 3x3/256 block conv at eval batch
    from bigdl_tpu.ops.quant import quantized_conv2d
    x = jnp.asarray(gaussian_matrix((64, 256, 28, 28)))
    w = jnp.asarray(gaussian_matrix((256, 256, 3, 3), scale=0.05, seed=1))
    w_q, w_s = calibrate_weight(w, axis=0)  # per-out-channel

    def bf16_conv(x, w):
        from jax import lax
        return lax.conv_general_dilated(
            x.astype(jnp.bfloat16), w.astype(jnp.bfloat16), (1, 1),
            ((1, 1), (1, 1)), dimension_numbers=("NCHW", "OIHW", "NCHW"))

    t_bc = _time_chunk(bf16_conv, (x, w), scan, iters)

    def int8_conv(x, w_q, w_s):
        return quantized_conv2d(x, w_q, w_s, stride=(1, 1),
                                padding=((1, 1), (1, 1)))

    t_ic = _time_chunk(int8_conv, (x, w_q, w_s), scan, iters)
    row = {"shape": "conv 64x256x28x28 3x3/256",
           "bf16_ms": round(t_bc * 1e3, 3),
           "jnp_int8_ms": round(t_ic * 1e3, 3),
           "int8_speedup_vs_bf16": round(t_bc / t_ic, 3)}
    rows.append(row)
    print(json.dumps(row), flush=True)
    wins = [r for r in rows
            if isinstance(r.get("int8_speedup_vs_bf16"), float)
            and r["int8_speedup_vs_bf16"] > 1.05]
    print(json.dumps({"verdict": (
        f"int8 wins at {len(wins)}/{len(rows)} shapes"
        if wins else "bf16 wins at every swept shape — int8 is a "
        "footprint feature on this device class")}))
    return rows


if __name__ == "__main__":
    main()
