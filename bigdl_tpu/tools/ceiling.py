"""On-chip ceiling ablation: framework steps vs hand-rolled raw-JAX
steps of identical semantics (the evidence behind BASELINE.md's
platform-ceiling table; the reference's counterpart is
models/utils/DistriOptimizerPerf.scala:38 leaving nothing on the table).

ResNet-50 modes:
  fw                framework step as shipped pre-r3 (conv biases, no donation)
  fw_donate         + donated scan carry
  fw_nobias         + pre-BN conv biases dropped (models/resnet default now)
  fw_nobias_donate  + both (= bench.py configuration)
  hand              hand-rolled full-semantics step (raw lax convs, one-pass
                    BN with running stats, CE loss, SGD momentum+wd+nesterov)
  hand_fwd          hand-rolled forward only

Zoo-wide modes (same methodology — the framework must meet its own
hand-rolled same-semantics ceiling on every flagship family):
  fw_vgg16 / hand_vgg16   VGG-16 ImageNet (batch BENCH_BATCH, default 128)
  fw_tlm / hand_tlm       TransformerLM 6L/512d/8H seq 512 (batch 16)

Every mode also reports analytic TF/s (XLA's compiled cost analysis)
and MFU against the device peak (BIGDL_DEVICE_TFS, default 197 TF/s —
the v5e bf16 peak; BASELINE.md's measured 25-35 TF/s mid-size-op
envelope is tunnel context, not a peak).

Usage: python -m bigdl_tpu.tools.ceiling <mode> [iters]
"""
import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

import bigdl_tpu.telemetry as telemetry

# module-level registration so `tools.check --telemetry-audit` sees the
# REAL instrument on import
_ITEMS_PER_S = telemetry.histogram(
    "tools/ceiling/items_per_s", "measured throughput per ceiling run")

BATCH = int(os.environ.get("BENCH_BATCH", 256))
SCAN = int(os.environ.get("BENCH_SCAN", 8))
WARMUP = 1
# MFU denominator: v5e peak bf16 (197 TF/s). BASELINE.md's measured
# 25-35 TF/s mid-size-op envelope is tunnel-side context, not a peak.
DEVICE_TFS = float(os.environ.get("BIGDL_DEVICE_TFS", 197.0))

_FLOPS = {"per_chunk": None}


def timed(run_chunk, carry, iters):
    root = jax.random.PRNGKey(0)
    keys0 = jax.random.split(root, SCAN)
    # ONE AOT compile serves both the cost analysis and the timed loop
    # (lower().compile() does not populate the jit dispatch cache, so
    # executing the compiled object avoids paying the compile twice)
    _FLOPS["per_chunk"] = None
    try:
        compiled = run_chunk.lower(carry, keys0).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        _FLOPS["per_chunk"] = float(cost["flops"])
        run_chunk = compiled
    except Exception:
        pass  # backend without AOT cost analysis: plain jit path
    # timing audit: chunks chain through `carry`, so one sync before t0
    # and one at the end bound ALL the dispatched work. The loss fetch
    # alone would not gate the LAST chunk's param-update branch — block
    # on the carry too, or the final update rides outside the window.
    for i in range(WARMUP):
        keys = jax.random.split(jax.random.fold_in(root, i), SCAN)
        carry, losses = run_chunk(carry, keys)
    jax.block_until_ready(carry)
    float(losses.sum())
    t0 = time.time()
    for i in range(iters):
        keys = jax.random.split(jax.random.fold_in(root, 1000 + i), SCAN)
        carry, losses = run_chunk(carry, keys)
    jax.block_until_ready(carry)
    float(losses.sum())
    dt = time.time() - t0
    return BATCH * SCAN * iters / dt


def mfu_fields(rate_per_sec, per_item_flops=None):
    """{achieved_tfs, mfu} from the measured rate and the compiled
    chunk's analytic flops (fallback: caller-supplied per-item flops).

    Thin shim over :func:`bigdl_tpu.telemetry.programs.mfu_fields` —
    the cost-analysis → MFU math (including the scan-body-counted-once
    disambiguation, ``resolve_per_item_flops``) lives in ONE place
    there; this keeps the ceiling CLI's JSON fields byte-compatible."""
    from bigdl_tpu.telemetry import programs

    return programs.mfu_fields(
        rate_per_sec, flops_per_call=_FLOPS["per_chunk"],
        items_per_call=BATCH, scan_length=SCAN,
        per_item_estimate=per_item_flops, peak_tfs=DEVICE_TFS)


def framework(mode, iters):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet as R
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random import RandomGenerator

    Engine.set_compute_dtype(jnp.bfloat16)
    RandomGenerator.set_seed(1)
    # fw/fw_donate reproduce the r2 form (reference parameter set with
    # conv biases); the nobias modes are models/resnet's r3 default
    model = R.ResNet(1000, depth=50, dataset="ImageNet",
                     conv_bias="nobias" not in mode).training()
    model.ensure_initialized()
    criterion = nn.CrossEntropyCriterion()
    optim = SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4,
                nesterov=True, dampening=0.0)
    params = model.get_parameters()
    mstate = model.get_state()
    opt_state = optim.init_state(params)
    step = build_train_step(model, criterion, optim)

    def scan_body(carry, key):
        params, opt_state, mstate = carry
        kx, ky, kr = jax.random.split(key, 3)
        x = jax.random.uniform(kx, (BATCH, 3, 224, 224), jnp.float32)
        y = jax.random.randint(ky, (BATCH,), 1, 1001).astype(jnp.float32)
        params, opt_state, mstate, loss = step(params, opt_state, mstate,
                                               kr, 0.1, x, y)
        return (params, opt_state, mstate), loss

    kw = {"donate_argnums": (0,)} if "donate" in mode else {}

    @functools.partial(jax.jit, **kw)
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, (params, opt_state, mstate), iters)


# ------------------------------------------------------- hand-rolled RN50

CFG50 = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]


def hand_init(key):
    params, state = [], []

    def conv_p(k, cin, cout, kh, kw_):
        fan_in = cin * kh * kw_
        w = jax.random.normal(k, (cout, cin, kh, kw_), jnp.float32) \
            * np.sqrt(2.0 / fan_in)
        return w

    def bn_p(c):
        return {"g": jnp.ones((c,), jnp.float32),
                "b": jnp.zeros((c,), jnp.float32)}

    def bn_s(c):
        return {"m": jnp.zeros((c,), jnp.float32),
                "v": jnp.ones((c,), jnp.float32)}

    ks = iter(jax.random.split(key, 256))
    params.append(conv_p(next(ks), 3, 64, 7, 7))     # stem
    params.append(bn_p(64))
    state.append(bn_s(64))
    cin = 64
    for feats, count, stride in CFG50:
        for i in range(count):
            s = stride if i == 0 else 1
            blk = {"c1": conv_p(next(ks), cin, feats, 1, 1),
                   "bn1": bn_p(feats),
                   "c2": conv_p(next(ks), feats, feats, 3, 3),
                   "bn2": bn_p(feats),
                   "c3": conv_p(next(ks), feats, feats * 4, 1, 1),
                   "bn3": bn_p(feats * 4)}
            st = {"bn1": bn_s(feats), "bn2": bn_s(feats),
                  "bn3": bn_s(feats * 4)}
            if i == 0:
                blk["cs"] = conv_p(next(ks), cin, feats * 4, 1, 1)
                blk["bns"] = bn_p(feats * 4)
                st["bns"] = bn_s(feats * 4)
            params.append(blk)
            state.append(st)
            cin = feats * 4
    wfc = jax.random.normal(next(ks), (2048, 1000), jnp.float32) * 0.01
    params.append({"w": wfc, "b": jnp.zeros((1000,), jnp.float32)})
    return params, state


def conv(x, w, stride=1, pad=0):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride),
        ((pad, pad), (pad, pad)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def bn(x, p, s, mom=0.1):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(0, 2, 3))
    ex2 = jnp.mean(jnp.square(x32), axis=(0, 2, 3))
    var = jnp.maximum(ex2 - jnp.square(mean), 0.0)
    n = x.size // x.shape[1]
    new_s = {"m": (1 - mom) * s["m"] + mom * mean,
             "v": (1 - mom) * s["v"] + mom * var * n / (n - 1)}
    inv = lax.rsqrt(var + 1e-5).astype(x.dtype)
    mean = mean.astype(x.dtype)
    y = (x - mean[None, :, None, None]) * inv[None, :, None, None]
    y = y * p["g"].astype(x.dtype)[None, :, None, None] \
        + p["b"].astype(x.dtype)[None, :, None, None]
    return y, new_s


def hand_forward(params, state, x):
    new_state = []
    x = conv(lax.stop_gradient(x), params[0], 2, 3)
    x, s = bn(x, params[1], state[0])
    new_state.append(s)
    x = jax.nn.relu(x)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, 3, 3),
                          (1, 1, 2, 2), ((0, 0), (0, 0), (1, 1), (1, 1)))
    i = 2
    si = 1
    for feats, count, stride in CFG50:
        for j in range(count):
            blk, st = params[i], state[si]
            s0 = stride if j == 0 else 1
            ns = {}
            h = conv(x, blk["c1"])
            h, ns["bn1"] = bn(h, blk["bn1"], st["bn1"])
            h = jax.nn.relu(h)
            h = conv(h, blk["c2"], s0, 1)
            h, ns["bn2"] = bn(h, blk["bn2"], st["bn2"])
            h = jax.nn.relu(h)
            h = conv(h, blk["c3"])
            h, ns["bn3"] = bn(h, blk["bn3"], st["bn3"])
            if "cs" in blk:
                sc = conv(x, blk["cs"], s0)
                sc, ns["bns"] = bn(sc, blk["bns"], st["bns"])
            else:
                sc = x
            x = jax.nn.relu(h + sc)
            new_state.append(ns)
            i += 1
            si += 1
    x = jnp.mean(x, axis=(2, 3))
    fc = params[i]
    logits = x @ fc["w"].astype(x.dtype) + fc["b"].astype(x.dtype)
    return logits.astype(jnp.float32), new_state


def hand(mode, iters):
    key = jax.random.PRNGKey(1)
    params, state = hand_init(key)
    mom_buf = jax.tree.map(jnp.zeros_like, params)

    def loss_fn(p, s, x, y):
        p16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16), p)
        logits, ns = hand_forward(p16, s, x.astype(jnp.bfloat16))
        lse = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
        return jnp.mean(lse - ll), ns

    fwd_only = mode == "hand_fwd"

    def scan_body(carry, key):
        params, mom, state = carry
        kx, ky = jax.random.split(key)
        x = jax.random.uniform(kx, (BATCH, 3, 224, 224), jnp.float32)
        y = jax.random.randint(ky, (BATCH,), 0, 1000)
        if fwd_only:
            loss, ns = loss_fn(params, state, x, y)
            return (params, mom, ns), loss
        (loss, ns), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, state, x, y)
        grads = jax.tree.map(
            lambda g, p: g.astype(jnp.float32) + 1e-4 * p, grads, params)
        mom = jax.tree.map(lambda m, g: 0.9 * m + g, mom_buf if mom is None
                           else mom, grads)
        upd = jax.tree.map(lambda g, m: g + 0.9 * m, grads, mom)  # nesterov
        params = jax.tree.map(lambda p, u: p - 0.1 * u, params, upd)
        return (params, mom, ns), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, (params, mom_buf, state), iters)


# ----------------------------------------------------------- VGG-16 pair

def _sgd_momentum_tree(params, grads, mom, lr=0.01):
    mom = jax.tree.map(lambda m, g: 0.9 * m + g.astype(jnp.float32),
                       mom, grads)
    params = jax.tree.map(lambda p, m: p - lr * m, params, mom)
    return params, mom


def framework_vgg16(iters):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import Vgg_16
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random import RandomGenerator

    Engine.set_compute_dtype(jnp.bfloat16)
    RandomGenerator.set_seed(1)
    model = Vgg_16(1000).training()
    model.ensure_initialized()
    optim = SGD(learning_rate=0.01, momentum=0.9)
    params = model.get_parameters()
    mstate = model.get_state()
    opt_state = optim.init_state(params)
    step = build_train_step(model, nn.ClassNLLCriterion(), optim)

    def scan_body(carry, key):
        params, opt_state, mstate = carry
        kx, ky, kr = jax.random.split(key, 3)
        x = jax.random.uniform(kx, (BATCH, 3, 224, 224), jnp.float32)
        y = jax.random.randint(ky, (BATCH,), 1, 1001).astype(jnp.float32)
        params, opt_state, mstate, loss = step(params, opt_state, mstate,
                                               kr, 0.01, x, y)
        return (params, opt_state, mstate), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, (params, opt_state, mstate), iters)


VGG_CFG = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
           512, 512, 512, "M", 512, 512, 512, "M"]


def hand_vgg16(iters):
    """Raw-JAX VGG-16 with the framework model's exact semantics: biased
    3x3 convs + ReLU + maxpools, FC 25088-4096-4096-1000 with
    Threshold(0,1e-6) and Dropout(0.5), LogSoftMax + NLL, SGD momentum,
    bf16 compute / f32 master."""
    key = jax.random.PRNGKey(1)
    ks = iter(jax.random.split(key, 64))
    params = []
    cin = 3
    for v in VGG_CFG:
        if v == "M":
            continue
        fan = cin * 9
        params.append({
            "w": jax.random.normal(next(ks), (v, cin, 3, 3), jnp.float32)
            * np.sqrt(2.0 / fan),
            "b": jnp.zeros((v,), jnp.float32)})
        cin = v
    dims = [(512 * 7 * 7, 4096), (4096, 4096), (4096, 1000)]
    for din, dout in dims:
        params.append({
            "w": jax.random.normal(next(ks), (din, dout), jnp.float32)
            * np.sqrt(1.0 / din),
            "b": jnp.zeros((dout,), jnp.float32)})
    mom = jax.tree.map(jnp.zeros_like, params)

    def fwd(p, x, key):
        i = 0
        for v in VGG_CFG:
            if v == "M":
                x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, 2, 2),
                                      (1, 1, 2, 2), "VALID")
                continue
            x = conv(x, p[i]["w"], 1, 1) \
                + p[i]["b"].astype(x.dtype)[None, :, None, None]
            x = jax.nn.relu(x)
            i += 1
        x = x.reshape(x.shape[0], -1)
        for j, (din, dout) in enumerate(dims):
            fc = p[i + j]
            x = x @ fc["w"].astype(x.dtype) + fc["b"].astype(x.dtype)
            if j < 2:
                x = jnp.where(x > 0, x, jnp.asarray(1e-6, x.dtype))
                keep = jax.random.bernoulli(
                    jax.random.fold_in(key, j), 0.5, x.shape)
                x = jnp.where(keep, x / 0.5, 0.0)
        return jax.nn.log_softmax(x.astype(jnp.float32), axis=-1)

    def loss_fn(p, x, y, key):
        p16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16), p)
        logp = fwd(p16, x.astype(jnp.bfloat16), key)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

    def scan_body(carry, key):
        params, mom = carry
        kx, ky, kd = jax.random.split(key, 3)
        x = jax.random.uniform(kx, (BATCH, 3, 224, 224), jnp.float32)
        y = jax.random.randint(ky, (BATCH,), 0, 1000)
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y, kd)
        params, mom = _sgd_momentum_tree(params, grads, mom)
        return (params, mom), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, (params, mom), iters)


# ------------------------------------------------------ TransformerLM pair

TLM = dict(vocab=32000, d=512, layers=6, heads=8, seq=512)


def framework_tlm(iters):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import TransformerLM
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random import RandomGenerator

    Engine.set_compute_dtype(jnp.bfloat16)
    RandomGenerator.set_seed(1)
    model = TransformerLM(TLM["vocab"], hidden_size=TLM["d"],
                          num_layers=TLM["layers"], num_heads=TLM["heads"],
                          max_len=TLM["seq"]).training()
    model.ensure_initialized()
    optim = SGD(learning_rate=0.1)
    params = model.get_parameters()
    mstate = model.get_state()
    opt_state = optim.init_state(params)
    step = build_train_step(model, nn.SequenceCrossEntropyCriterion(),
                            optim)

    def scan_body(carry, key):
        params, opt_state, mstate = carry
        kx, kr = jax.random.split(key)
        x = jax.random.randint(kx, (BATCH, TLM["seq"]), 0, TLM["vocab"])
        params, opt_state, mstate, loss = step(params, opt_state, mstate,
                                               kr, 0.1, x, x)
        return (params, opt_state, mstate), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, (params, opt_state, mstate), iters)


def hand_tlm(iters):
    """Raw-JAX decoder LM with models/transformer's exact semantics:
    learned pos embeddings, pre-norm blocks (uniform-init QKV/O and FFN
    with biases, gelu), ln_f, tied head, sequence CE, plain SGD,
    bf16 compute / f32 master."""
    V, D, L, H, S = (TLM["vocab"], TLM["d"], TLM["layers"], TLM["heads"],
                     TLM["seq"])
    hd = D // H
    key = jax.random.PRNGKey(1)
    ks = iter(jax.random.split(key, 16 + 8 * L))
    s = 1.0 / np.sqrt(D)

    def u(shape, scale):
        return jax.random.uniform(next(ks), shape, jnp.float32,
                                  -scale, scale)

    params = {"embed": jax.random.normal(next(ks), (V, D)) * s,
              "pos": jax.random.normal(next(ks), (S, D)) * s,
              "lnf": (jnp.ones((D,)), jnp.zeros((D,)))}
    blocks = []
    sf = 1.0 / np.sqrt(4 * D)
    # per-block constructions must stay DISTINCT buffers: the carry is
    # donated (donate_argnums), and XLA rejects the same buffer donated
    # twice — hoisting/sharing these zeros breaks run_chunk.
    # bigdl: disable-file=jnp-in-host-loop
    for _ in range(L):
        blocks.append({
            "ln1": (jnp.ones((D,)), jnp.zeros((D,))),
            "qkvo": [(u((D, D), s), jnp.zeros((D,))) for _ in range(4)],
            "ln2": (jnp.ones((D,)), jnp.zeros((D,))),
            "up": (u((D, 4 * D), s), jnp.zeros((4 * D,))),
            "down": (u((4 * D, D), sf), jnp.zeros((D,)))})
    params["blocks"] = blocks

    def ln(x, p):
        g, b = p
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        return (x - mu) * lax.rsqrt(var + 1e-5) * g.astype(x.dtype) \
            + b.astype(x.dtype)

    def fwd(p, toks):
        b = toks.shape[0]
        x = p["embed"][toks] + p["pos"][None, :S]
        cmask = jnp.tril(jnp.ones((S, S), bool))  # hoisted: loop-invariant
        for blk in p["blocks"]:
            h = ln(x, blk["ln1"])
            (qw, qb), (kw, kb), (vw, vb), (ow, ob) = blk["qkvo"]

            def split(t):
                return t.reshape(b, S, H, hd).transpose(0, 2, 1, 3)
            q = split(h @ qw.astype(h.dtype) + qb.astype(h.dtype))
            k = split(h @ kw.astype(h.dtype) + kb.astype(h.dtype))
            v = split(h @ vw.astype(h.dtype) + vb.astype(h.dtype))
            sc = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(hd)
            sc = jnp.where(cmask, sc, jnp.finfo(sc.dtype).min)
            att = jax.nn.softmax(sc, axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd", att, v)
            out = out.transpose(0, 2, 1, 3).reshape(b, S, D)
            x = x + out @ ow.astype(x.dtype) + ob.astype(x.dtype)
            h = ln(x, blk["ln2"])
            uw, ub = blk["up"]
            dw, db = blk["down"]
            h = jax.nn.gelu(h @ uw.astype(h.dtype) + ub.astype(h.dtype))
            x = x + h @ dw.astype(h.dtype) + db.astype(h.dtype)
        x = ln(x, p["lnf"])
        return x @ p["embed"].T.astype(x.dtype)

    def loss_fn(p, toks):
        p16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16), p)
        logits = fwd(p16, toks).astype(jnp.float32).reshape(-1, V)
        t = toks.reshape(-1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, t[:, None], axis=1).mean()

    def scan_body(carry, key):
        params = carry
        x = jax.random.randint(key, (BATCH, S), 0, V)
        loss, grads = jax.value_and_grad(loss_fn)(params, x)
        params = jax.tree.map(
            lambda p, g: p - 0.1 * g.astype(jnp.float32), params, grads)
        return params, loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, params, iters)


# --------------------------------------------------- Inception-v1 pair

def framework_inception(iters):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.models.inception import Inception_v1_NoAuxClassifier
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random import RandomGenerator

    Engine.set_compute_dtype(jnp.bfloat16)
    RandomGenerator.set_seed(1)
    model = Inception_v1_NoAuxClassifier(1000).training()
    model.ensure_initialized()
    optim = SGD(learning_rate=0.01, momentum=0.9)
    params = model.get_parameters()
    mstate = model.get_state()
    opt_state = optim.init_state(params)
    step = build_train_step(model, nn.ClassNLLCriterion(), optim)

    def scan_body(carry, key):
        params, opt_state, mstate = carry
        kx, ky, kr = jax.random.split(key, 3)
        x = jax.random.uniform(kx, (BATCH, 3, 224, 224), jnp.float32)
        y = jax.random.randint(ky, (BATCH,), 1, 1001).astype(jnp.float32)
        params, opt_state, mstate, loss = step(params, opt_state, mstate,
                                               kr, 0.01, x, y)
        return (params, opt_state, mstate), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, (params, opt_state, mstate), iters)


# (input_size, (n1x1, (n3r, n3), (n5r, n5), npool)) per inception block
INC_CFG = [
    ("3a", 192, (64, (96, 128), (16, 32), 32)),
    ("3b", 256, (128, (128, 192), (32, 96), 64)),
    ("P", 0, None),
    ("4a", 480, (192, (96, 208), (16, 48), 64)),
    ("4b", 512, (160, (112, 224), (24, 64), 64)),
    ("4c", 512, (128, (128, 256), (24, 64), 64)),
    ("4d", 512, (112, (144, 288), (32, 64), 64)),
    ("4e", 528, (256, (160, 320), (32, 128), 128)),
    ("P", 0, None),
    ("5a", 832, (256, (160, 320), (32, 128), 128)),
    ("5b", 832, (384, (192, 384), (48, 128), 128)),
]


def _maxpool_ceil(x, k, s, pad=0):
    """Torch ceil-mode maxpool with symmetric base padding: the tail is
    additionally padded with -inf so the last partial window counts
    (matches nn.SpatialMaxPooling(...).ceil())."""
    n = x.shape[2] + 2 * pad
    out = -(-(n - k) // s) + 1
    extra = max((out - 1) * s + k - n, 0)
    return lax.reduce_window(
        x, -jnp.inf, lax.max, (1, 1, k, k), (1, 1, s, s),
        ((0, 0), (0, 0), (pad, pad + extra), (pad, pad + extra)))


def hand_inception(iters):
    """Raw-JAX GoogLeNet with the zoo model's exact op semantics
    (Inception_v1_NoAuxClassifier: biased Xavier convs + ReLU, LRN(5),
    ceil-mode pools, 4-branch channel concat, avgpool 7, Dropout(0.4),
    Linear 1024->1000, LogSoftMax+NLL, SGD momentum, bf16 compute /
    f32 master)."""
    key = jax.random.PRNGKey(1)
    ks = iter(jax.random.split(key, 256))

    def conv_p(cin, cout, k):
        fan_in, fan_out = cin * k * k, cout * k * k
        lim = np.sqrt(6.0 / (fan_in + fan_out))
        return {"w": jax.random.uniform(next(ks), (cout, cin, k, k),
                                        jnp.float32, -lim, lim),
                "b": jnp.zeros((cout,), jnp.float32)}

    params = {"stem1": conv_p(3, 64, 7), "stem2": conv_p(64, 64, 1),
              "stem3": conv_p(64, 192, 3)}
    for name, cin, cfg in INC_CFG:
        if cfg is None:
            continue
        n1, (n3r, n3), (n5r, n5), npool = cfg
        params[name] = {
            "b1": conv_p(cin, n1, 1),
            "b3r": conv_p(cin, n3r, 1), "b3": conv_p(n3r, n3, 3),
            "b5r": conv_p(cin, n5r, 1), "b5": conv_p(n5r, n5, 5),
            "bp": conv_p(cin, npool, 1)}
    lim = np.sqrt(6.0 / (1024 + 1000))
    params["fc"] = {"w": jax.random.uniform(next(ks), (1024, 1000),
                                            jnp.float32, -lim, lim),
                    "b": jnp.zeros((1000,), jnp.float32)}

    def cv(x, p, stride=1, pad=0):
        return conv(x, p["w"].astype(x.dtype), stride, pad) \
            + p["b"].astype(x.dtype)[None, :, None, None]

    def lrn(x, size=5, alpha=1e-4, beta=0.75):
        sq = x * x
        half = (size - 1) // 2
        # init must be a python scalar: a traced init value breaks
        # reduce_window's reverse-mode linearization
        summed = lax.reduce_window(
            sq, 0.0, lax.add, (1, size, 1, 1), (1, 1, 1, 1),
            ((0, 0), (half, size - 1 - half), (0, 0), (0, 0)))
        return x / jnp.power(1.0 + alpha / size * summed, beta)

    def block(x, p):
        b1 = jax.nn.relu(cv(x, p["b1"]))
        b3 = jax.nn.relu(cv(jax.nn.relu(cv(x, p["b3r"])), p["b3"],
                            1, 1))
        b5 = jax.nn.relu(cv(jax.nn.relu(cv(x, p["b5r"])), p["b5"],
                            1, 2))
        bp = jax.nn.relu(cv(_maxpool_ceil(x, 3, 1, pad=1), p["bp"]))
        return jnp.concatenate([b1, b3, b5, bp], axis=1)

    def fwd(p, x, key):
        x = jax.nn.relu(cv(x, p["stem1"], 2, 3))
        x = _maxpool_ceil(x, 3, 2)
        x = lrn(x)
        x = jax.nn.relu(cv(x, p["stem2"]))
        x = jax.nn.relu(cv(x, p["stem3"], 1, 1))
        x = lrn(x)
        x = _maxpool_ceil(x, 3, 2)
        for name, _, cfg in INC_CFG:
            if cfg is None:
                x = _maxpool_ceil(x, 3, 2)
            else:
                x = block(x, p[name])
        x = lax.reduce_window(x, 0.0, lax.add,
                              (1, 1, 7, 7), (1, 1, 1, 1), "VALID") / 49.0
        keep = jax.random.bernoulli(key, 0.6, x.shape)
        x = jnp.where(keep, x / 0.6, 0.0)
        x = x.reshape(x.shape[0], 1024)
        logits = x @ p["fc"]["w"].astype(x.dtype) \
            + p["fc"]["b"].astype(x.dtype)
        return jax.nn.log_softmax(logits.astype(jnp.float32), -1)

    def loss_fn(p, x, y, key):
        p16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16), p)
        logp = fwd(p16, x.astype(jnp.bfloat16), key)
        return -jnp.take_along_axis(logp, y[:, None], axis=-1).mean()

    mom = jax.tree.map(jnp.zeros_like, params)

    def scan_body(carry, key):
        params, mom = carry
        kx, ky, kd = jax.random.split(key, 3)
        x = jax.random.uniform(kx, (BATCH, 3, 224, 224), jnp.float32)
        y = jax.random.randint(ky, (BATCH,), 0, 1000)
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y, kd)
        params, mom = _sgd_momentum_tree(params, grads, mom)
        return (params, mom), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, (params, mom), iters)


# ------------------------------------------------------ PTB LSTM pair

PTB = dict(vocab=10000, hidden=650, layers=2, seq=35)


def framework_lstm(iters):
    """The scan-heavy zoo family: PTBModel (embedding + stacked
    Recurrent(LSTM) + TimeDistributed(Linear)), the recipe's
    TimeDistributedCriterion(CrossEntropy) objective."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.models.rnn import PTBModel
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random import RandomGenerator

    Engine.set_compute_dtype(jnp.bfloat16)
    RandomGenerator.set_seed(1)
    model = PTBModel(PTB["vocab"], PTB["hidden"], PTB["vocab"],
                     num_layers=PTB["layers"]).training()
    model.ensure_initialized()
    optim = SGD(learning_rate=0.1)
    params = model.get_parameters()
    mstate = model.get_state()
    opt_state = optim.init_state(params)
    crit = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion())
    step = build_train_step(model, crit, optim)

    def scan_body(carry, key):
        params, opt_state, mstate = carry
        kx, kr = jax.random.split(key)
        x = jax.random.randint(kx, (BATCH, PTB["seq"]), 1,
                               PTB["vocab"] + 1)
        y = x.astype(jnp.float32)
        params, opt_state, mstate, loss = step(params, opt_state, mstate,
                                               kr, 0.1, x, y)
        return (params, opt_state, mstate), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, (params, opt_state, mstate), iters)


def hand_lstm(iters):
    """Raw-JAX stacked LSTM LM with the zoo model's exact semantics:
    1-based embedding lookup, fused (4H) i,f,g,o gates per step under a
    time-major lax.scan per layer, time-distributed linear head, CE,
    plain SGD, bf16 compute / f32 master."""
    V, H, L, S = PTB["vocab"], PTB["hidden"], PTB["layers"], PTB["seq"]
    key = jax.random.PRNGKey(1)
    ks = iter(jax.random.split(key, 16))
    stdv = 1.0 / np.sqrt(H)

    def u(shape, scale):
        return jax.random.uniform(next(ks), shape, jnp.float32,
                                  -scale, scale)

    params = {"emb": jax.random.normal(next(ks), (V, H)) * 0.1,
              "cells": [{"w_ih": u((4 * H, H), stdv),
                         "w_hh": u((4 * H, H), stdv),
                         "bias": u((4 * H,), stdv)} for _ in range(L)],
              "fc": {"w": u((H, V), stdv), "b": jnp.zeros((V,))}}

    def lstm_layer(p, xs):
        # xs: [S, B, H] time-major
        def step(hc, x):
            h, c = hc
            gates = x @ p["w_ih"].T.astype(x.dtype) \
                + h @ p["w_hh"].T.astype(x.dtype) \
                + p["bias"].astype(x.dtype)
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = (jax.nn.sigmoid(i), jax.nn.sigmoid(f),
                       jax.nn.sigmoid(o))
            c2 = f * c + i * jnp.tanh(g)
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2

        b = xs.shape[1]
        z = jnp.zeros((b, H), xs.dtype)
        _, hs = lax.scan(step, (z, z), xs)
        return hs

    def loss_fn(p, toks):
        p16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16), p)
        x = p16["emb"][toks - 1]                    # 1-based LookupTable
        x = x.transpose(1, 0, 2)                    # [S, B, H]
        for cell in p16["cells"]:
            x = lstm_layer(cell, x)
        logits = x @ p16["fc"]["w"].astype(x.dtype) \
            + p16["fc"]["b"].astype(x.dtype)
        logits = logits.astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        t = (toks - 1).transpose(1, 0)
        return -jnp.take_along_axis(logp, t[..., None], axis=-1).mean()

    def scan_body(carry, key):
        params = carry
        x = jax.random.randint(key, (BATCH, S), 1, V + 1)
        loss, grads = jax.value_and_grad(loss_fn)(params, x)
        params = jax.tree.map(
            lambda p, g: p - 0.1 * g.astype(jnp.float32), params, grads)
        return params, loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, params, iters)


MODES = {"fw_vgg16": framework_vgg16, "hand_vgg16": hand_vgg16,
         "fw_tlm": framework_tlm, "hand_tlm": hand_tlm,
         "fw_inception": framework_inception,
         "hand_inception": hand_inception,
         "fw_lstm": framework_lstm, "hand_lstm": hand_lstm}


if __name__ == "__main__":
    if len(sys.argv) < 2:
        raise SystemExit(__doc__)
    mode = sys.argv[1]
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 6
    if "tlm" in mode and "BENCH_BATCH" not in os.environ:
        BATCH = 16
    if "vgg" in mode and "BENCH_BATCH" not in os.environ:
        BATCH = 128
    if "inception" in mode and "BENCH_BATCH" not in os.environ:
        BATCH = 128
    if "lstm" in mode and "BENCH_BATCH" not in os.environ:
        BATCH = 64
    if mode in MODES:
        r = MODES[mode](iters)
    elif mode.startswith("hand"):
        r = hand(mode, iters)
    else:
        r = framework(mode, iters)
    # steps_per_sync: every ceiling harness dispatches SCAN fused steps
    # per host sync — the same window the Optimizer's set_steps_per_sync
    # knob gives training, so ablations and driver runs are comparable
    out = {"mode": mode, "items_per_sec": round(r, 1),
           "steps_per_sync": SCAN}
    if "tlm" in mode:
        out["tokens_per_sec"] = round(r * TLM["seq"], 1)
    if "lstm" in mode:
        out["tokens_per_sec"] = round(r * PTB["seq"], 1)
    out.update(mfu_fields(r))
    print(json.dumps(out))
    # one flag, default off: append a telemetry snapshot so BENCH
    # trajectories carry phase breakdowns, not just the one total
    jsonl = os.environ.get("BIGDL_METRICS_JSONL")
    if jsonl:
        _ITEMS_PER_S.observe(r, mode=mode)
        telemetry.snapshot_to_jsonl(jsonl,
                                    meta=dict(out, tool="ceiling",
                                              batch=BATCH, scan=SCAN,
                                              iters=iters))
