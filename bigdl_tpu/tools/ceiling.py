"""On-chip ceiling ablation: framework ResNet-50 step vs a hand-rolled
raw-JAX step of identical semantics (the evidence behind BASELINE.md's
platform-ceiling table; the reference's counterpart is
models/utils/DistriOptimizerPerf.scala:38 leaving nothing on the table).

Modes:
  fw                framework step as shipped pre-r3 (conv biases, no donation)
  fw_donate         + donated scan carry
  fw_nobias         + pre-BN conv biases dropped (models/resnet default now)
  fw_nobias_donate  + both (= bench.py configuration)
  hand              hand-rolled full-semantics step (raw lax convs, one-pass
                    BN with running stats, CE loss, SGD momentum+wd+nesterov)
  hand_fwd          hand-rolled forward only

Usage: python -m bigdl_tpu.tools.ceiling <mode> [iters]
"""
import functools
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

BATCH = int(os.environ.get("BENCH_BATCH", 256))
SCAN = int(os.environ.get("BENCH_SCAN", 8))
WARMUP = 1


def timed(run_chunk, carry, iters):
    root = jax.random.PRNGKey(0)
    for i in range(WARMUP):
        keys = jax.random.split(jax.random.fold_in(root, i), SCAN)
        carry, losses = run_chunk(carry, keys)
    float(losses.sum())
    t0 = time.time()
    for i in range(iters):
        keys = jax.random.split(jax.random.fold_in(root, 1000 + i), SCAN)
        carry, losses = run_chunk(carry, keys)
    float(losses.sum())
    dt = time.time() - t0
    return BATCH * SCAN * iters / dt


def framework(mode, iters):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.models import resnet as R
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_train_step
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random import RandomGenerator

    Engine.set_compute_dtype(jnp.bfloat16)
    RandomGenerator.set_seed(1)
    # fw/fw_donate reproduce the r2 form (reference parameter set with
    # conv biases); the nobias modes are models/resnet's r3 default
    model = R.ResNet(1000, depth=50, dataset="ImageNet",
                     conv_bias="nobias" not in mode).training()
    model.ensure_initialized()
    criterion = nn.CrossEntropyCriterion()
    optim = SGD(learning_rate=0.1, momentum=0.9, weight_decay=1e-4,
                nesterov=True, dampening=0.0)
    params = model.get_parameters()
    mstate = model.get_state()
    opt_state = optim.init_state(params)
    step = build_train_step(model, criterion, optim)

    def scan_body(carry, key):
        params, opt_state, mstate = carry
        kx, ky, kr = jax.random.split(key, 3)
        x = jax.random.uniform(kx, (BATCH, 3, 224, 224), jnp.float32)
        y = jax.random.randint(ky, (BATCH,), 1, 1001).astype(jnp.float32)
        params, opt_state, mstate, loss = step(params, opt_state, mstate,
                                               kr, 0.1, x, y)
        return (params, opt_state, mstate), loss

    kw = {"donate_argnums": (0,)} if "donate" in mode else {}

    @functools.partial(jax.jit, **kw)
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, (params, opt_state, mstate), iters)


# ------------------------------------------------------- hand-rolled RN50

CFG50 = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]


def hand_init(key):
    params, state = [], []

    def conv_p(k, cin, cout, kh, kw_):
        fan_in = cin * kh * kw_
        w = jax.random.normal(k, (cout, cin, kh, kw_), jnp.float32) \
            * np.sqrt(2.0 / fan_in)
        return w

    def bn_p(c):
        return {"g": jnp.ones((c,), jnp.float32),
                "b": jnp.zeros((c,), jnp.float32)}

    def bn_s(c):
        return {"m": jnp.zeros((c,), jnp.float32),
                "v": jnp.ones((c,), jnp.float32)}

    ks = iter(jax.random.split(key, 256))
    params.append(conv_p(next(ks), 3, 64, 7, 7))     # stem
    params.append(bn_p(64))
    state.append(bn_s(64))
    cin = 64
    for feats, count, stride in CFG50:
        for i in range(count):
            s = stride if i == 0 else 1
            blk = {"c1": conv_p(next(ks), cin, feats, 1, 1),
                   "bn1": bn_p(feats),
                   "c2": conv_p(next(ks), feats, feats, 3, 3),
                   "bn2": bn_p(feats),
                   "c3": conv_p(next(ks), feats, feats * 4, 1, 1),
                   "bn3": bn_p(feats * 4)}
            st = {"bn1": bn_s(feats), "bn2": bn_s(feats),
                  "bn3": bn_s(feats * 4)}
            if i == 0:
                blk["cs"] = conv_p(next(ks), cin, feats * 4, 1, 1)
                blk["bns"] = bn_p(feats * 4)
                st["bns"] = bn_s(feats * 4)
            params.append(blk)
            state.append(st)
            cin = feats * 4
    wfc = jax.random.normal(next(ks), (2048, 1000), jnp.float32) * 0.01
    params.append({"w": wfc, "b": jnp.zeros((1000,), jnp.float32)})
    return params, state


def conv(x, w, stride=1, pad=0):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride),
        ((pad, pad), (pad, pad)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))


def bn(x, p, s, mom=0.1):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(0, 2, 3))
    ex2 = jnp.mean(jnp.square(x32), axis=(0, 2, 3))
    var = jnp.maximum(ex2 - jnp.square(mean), 0.0)
    n = x.size // x.shape[1]
    new_s = {"m": (1 - mom) * s["m"] + mom * mean,
             "v": (1 - mom) * s["v"] + mom * var * n / (n - 1)}
    inv = lax.rsqrt(var + 1e-5).astype(x.dtype)
    mean = mean.astype(x.dtype)
    y = (x - mean[None, :, None, None]) * inv[None, :, None, None]
    y = y * p["g"].astype(x.dtype)[None, :, None, None] \
        + p["b"].astype(x.dtype)[None, :, None, None]
    return y, new_s


def hand_forward(params, state, x):
    new_state = []
    x = conv(lax.stop_gradient(x), params[0], 2, 3)
    x, s = bn(x, params[1], state[0])
    new_state.append(s)
    x = jax.nn.relu(x)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 1, 3, 3),
                          (1, 1, 2, 2), ((0, 0), (0, 0), (1, 1), (1, 1)))
    i = 2
    si = 1
    for feats, count, stride in CFG50:
        for j in range(count):
            blk, st = params[i], state[si]
            s0 = stride if j == 0 else 1
            ns = {}
            h = conv(x, blk["c1"])
            h, ns["bn1"] = bn(h, blk["bn1"], st["bn1"])
            h = jax.nn.relu(h)
            h = conv(h, blk["c2"], s0, 1)
            h, ns["bn2"] = bn(h, blk["bn2"], st["bn2"])
            h = jax.nn.relu(h)
            h = conv(h, blk["c3"])
            h, ns["bn3"] = bn(h, blk["bn3"], st["bn3"])
            if "cs" in blk:
                sc = conv(x, blk["cs"], s0)
                sc, ns["bns"] = bn(sc, blk["bns"], st["bns"])
            else:
                sc = x
            x = jax.nn.relu(h + sc)
            new_state.append(ns)
            i += 1
            si += 1
    x = jnp.mean(x, axis=(2, 3))
    fc = params[i]
    logits = x @ fc["w"].astype(x.dtype) + fc["b"].astype(x.dtype)
    return logits.astype(jnp.float32), new_state


def hand(mode, iters):
    key = jax.random.PRNGKey(1)
    params, state = hand_init(key)
    mom_buf = jax.tree.map(jnp.zeros_like, params)

    def loss_fn(p, s, x, y):
        p16 = jax.tree.map(lambda a: a.astype(jnp.bfloat16), p)
        logits, ns = hand_forward(p16, s, x.astype(jnp.bfloat16))
        lse = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
        return jnp.mean(lse - ll), ns

    fwd_only = mode == "hand_fwd"

    def scan_body(carry, key):
        params, mom, state = carry
        kx, ky = jax.random.split(key)
        x = jax.random.uniform(kx, (BATCH, 3, 224, 224), jnp.float32)
        y = jax.random.randint(ky, (BATCH,), 0, 1000)
        if fwd_only:
            loss, ns = loss_fn(params, state, x, y)
            return (params, mom, ns), loss
        (loss, ns), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, state, x, y)
        grads = jax.tree.map(
            lambda g, p: g.astype(jnp.float32) + 1e-4 * p, grads, params)
        mom = jax.tree.map(lambda m, g: 0.9 * m + g, mom_buf if mom is None
                           else mom, grads)
        upd = jax.tree.map(lambda g, m: g + 0.9 * m, grads, mom)  # nesterov
        params = jax.tree.map(lambda p, u: p - 0.1 * u, params, upd)
        return (params, mom, ns), loss

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run_chunk(carry, keys):
        return lax.scan(scan_body, carry, keys)

    return timed(run_chunk, (params, mom_buf, state), iters)


if __name__ == "__main__":
    if len(sys.argv) < 2:
        raise SystemExit(__doc__)
    mode = sys.argv[1]
    iters = int(sys.argv[2]) if len(sys.argv) > 2 else 6
    if mode.startswith("hand"):
        r = hand(mode, iters)
    else:
        r = framework(mode, iters)
    print(json.dumps({"mode": mode, "imgs_per_sec": round(r, 1)}))
