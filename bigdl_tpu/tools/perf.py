"""Throughput harness for zoo models on synthetic data (reference:
models/utils/DistriOptimizerPerf.scala:38 / LocalOptimizerPerf.scala —
the de-facto benchmark tool; SURVEY.md §6).

Usage:
    python -m bigdl_tpu.tools.perf --model resnet50 --batch-size 64 \
        --iterations 20 [--mode train|inference] [--dtype bf16]
Prints per-iteration and summary images/sec.
"""
from __future__ import annotations

import argparse
import time

import numpy as np

import bigdl_tpu.telemetry as telemetry

# module-level registration so `tools.check --telemetry-audit` sees the
# REAL instruments on import, not a hand-maintained name list
_ITER_S = telemetry.histogram(
    "tools/perf/iteration_s", "seconds per timed perf iteration")
_WARMUP_S = telemetry.histogram(
    "tools/perf/warmup_s",
    "seconds per warmup iteration (includes the compile)")


def build_model(name: str, class_num: int = 1000):
    from bigdl_tpu import models
    name = name.lower()
    if name in ("lenet", "lenet5"):
        return models.LeNet5(10), (1, 28, 28), 10
    if name in ("vgg16", "vgg_16"):
        return models.Vgg_16(class_num), (3, 224, 224), class_num
    if name in ("vgg19", "vgg_19"):
        return models.Vgg_19(class_num), (3, 224, 224), class_num
    if name.startswith("resnet"):
        depth = int(name[len("resnet"):] or 50)
        return (models.ResNet(class_num, depth=depth, dataset="ImageNet"),
                (3, 224, 224), class_num)
    if name in ("alexnet", "alexnetowt", "alexnet_owt"):
        # DistriOptimizerPerf.scala:44 offers both forms
        builder = models.AlexNet if name == "alexnet" else models.AlexNet_OWT
        size = 227 if name == "alexnet" else 224
        return builder(class_num), (3, size, size), class_num
    if name in ("inception_v2", "inception-v2", "inceptionv2"):
        return (models.Inception_v2_NoAuxClassifier(class_num),
                (3, 224, 224), class_num)
    if name.startswith("inception"):
        return models.Inception_v1(class_num), (3, 224, 224), class_num
    if name.startswith("transformer"):
        return (models.TransformerLM(vocab_size=32000, hidden_size=512,
                                     num_layers=6, num_heads=8,
                                     max_len=512), (512,), 32000)
    raise ValueError(f"unknown model {name}")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--iterations", type=int, default=20)
    ap.add_argument("--warmup", type=int, default=3)
    ap.add_argument("--mode", choices=["train", "inference"],
                    default="train")
    ap.add_argument("--dtype", choices=["f32", "bf16"], default="bf16",
                    help="legacy Engine compute-dtype knob; ignored "
                    "when --precision names a full policy")
    ap.add_argument("--precision", default=None,
                    choices=["f32", "bf16_mixed", "f16_mixed"],
                    metavar="POLICY",
                    help="explicit precision policy "
                    "(bigdl_tpu.precision.PrecisionPolicy preset): "
                    "param/compute/output/accum dtypes compiled into "
                    "the step, f32 master copy + dynamic loss scaling "
                    "for f16_mixed — the policy twin of "
                    "Optimizer.set_precision")
    ap.add_argument("--quantize", action="store_true",
                    help="int8 inference rewrite (inference mode only — "
                    "the reference's quantized serving story, "
                    "nn/quantized/Quantization.scala:168)")
    ap.add_argument("--metrics-jsonl", default=None, metavar="PATH",
                    help="append a telemetry metrics snapshot (per-"
                    "iteration phase histograms + run meta) to PATH as "
                    "one JSONL line; default off (BIGDL_METRICS_JSONL "
                    "env var also enables it)")
    ap.add_argument("--steps-per-sync", type=int, default=1, metavar="K",
                    help="train mode: fuse K steps into one scanned "
                    "dispatch and sync the host once per window "
                    "(Optimizer.set_steps_per_sync's measurement twin); "
                    "1 = classic per-step dispatch")
    ap.add_argument("--sync-compare", action="store_true",
                    help="train mode: additionally measure steps/sec at "
                    "K=1 vs K=8 fused windows and report both in the "
                    "JSON tail line")
    ap.add_argument("--kernels", choices=["on", "off"], default=None,
                    metavar="{on,off}",
                    help="pallas kernel layer (bigdl_tpu.kernels): "
                    "'on' enables flash attention / ragged decode / "
                    "int8 GEMM dispatch (interpret mode off-TPU), "
                    "'off' forces the pure-jnp reference everywhere; "
                    "default: the backend/BIGDL_KERNELS policy. The "
                    "JSON tail carries kernels= and the program's "
                    "kernel label so a KERNELS on-vs-off pair is "
                    "attributable")
    ap.add_argument("--zero", type=int, choices=(0, 1, 2, 3), default=0,
                    metavar="STAGE",
                    help="train mode: ZeRO weight-update sharding stage "
                    "over a data-parallel mesh of ALL devices (parallel/"
                    "zero.py — 1: sharded opt state, 2: + gradient "
                    "reduce-scatter, 3: + params sharded at rest); the "
                    "JSON tail reports opt_state/params bytes per chip")
    ap.add_argument("--config", default=None, metavar="TUNED_JSON",
                    help="apply a tuned.json artifact from `python -m "
                    "bigdl_tpu.tools.autotune` — its train winner "
                    "overrides --steps-per-sync/--zero/--precision/"
                    "--batch-size/--kernels; refused (typed error) if "
                    "the artifact's environment fingerprint mismatches "
                    "this machine")
    args = ap.parse_args(argv)
    tuned_applied = []
    if args.config is not None:
        from bigdl_tpu.autotune.config import (apply_to_perf_args,
                                               load_tuned)
        tuned = load_tuned(args.config)
        tuned_applied = apply_to_perf_args(tuned, args)
        print(f"# tuned config {args.config}: applied "
              f"{','.join(tuned_applied) or 'nothing'}")
    if args.steps_per_sync < 1:
        raise SystemExit("--steps-per-sync must be >= 1")

    import jax
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    from bigdl_tpu.optim import SGD
    from bigdl_tpu.optim.optimizer import build_eval_step, build_train_step
    from bigdl_tpu.utils.engine import Engine
    from bigdl_tpu.utils.random import RandomGenerator

    Engine.init()
    if args.kernels is not None:
        from bigdl_tpu import kernels as _kernels
        _kernels.configure(_kernels.KernelConfig.all_on()
                           if args.kernels == "on"
                           else _kernels.KernelConfig.off())
    if args.dtype == "bf16":
        Engine.set_compute_dtype(jnp.bfloat16)
    policy = None
    if args.precision is not None:
        from bigdl_tpu.precision import PrecisionPolicy
        policy = PrecisionPolicy.named(args.precision)
    RandomGenerator.set_seed(42)

    from bigdl_tpu.tools import synthetic

    model, in_shape, class_num = build_model(args.model)
    is_lm = len(in_shape) == 1
    if is_lm:
        xs, ys = synthetic.token_batch(args.batch_size, in_shape[0],
                                       class_num)
        criterion = nn.SequenceCrossEntropyCriterion()
    else:
        xs, ys = synthetic.image_batch(args.batch_size, in_shape,
                                       class_num)
        criterion = nn.CrossEntropyCriterion()
    x, y = jnp.asarray(xs), jnp.asarray(ys)

    model.training() if args.mode == "train" else model.evaluate()
    model.ensure_initialized()
    if args.quantize:
        if args.mode != "inference":
            raise SystemExit("--quantize is inference-only")
        model = model.quantize().evaluate()
        model.ensure_initialized()
    params = model.get_parameters()
    mstate = model.get_state()

    # ONE AOT compile serves both the timed loop and the MFU cost
    # analysis (a post-hoc step.lower().compile() would re-compile the
    # whole program a second time just to read the flop count)
    compiled_for_cost = None
    sync_k = args.steps_per_sync if args.mode == "train" else 1
    zero_meta = {}
    if args.mode == "train":
        import functools
        from jax import lax

        optim = SGD(learning_rate=0.01, momentum=0.9)
        opt_state = optim.init_state(params)
        if policy is not None:
            # seed the policy's opt-state keys the way
            # Optimizer.set_precision does (master copy, scaler state)
            from bigdl_tpu.precision import (MASTER_KEY, SCALER_KEY,
                                             DynamicLossScaler)
            if policy.needs_master:
                opt_state[MASTER_KEY] = params
                params = policy.cast_to_param(params)
            if policy.needs_loss_scaling:
                opt_state[SCALER_KEY] = DynamicLossScaler().init_state()
        zero_cfg, zero_mesh = None, None
        if args.zero:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from bigdl_tpu.parallel import (ZeroConfig,
                                            data_parallel_mesh,
                                            place_zero_state,
                                            record_memory_gauges)
            zero_mesh = data_parallel_mesh()
            ndev = zero_mesh.shape["data"]
            if args.batch_size % ndev:
                raise SystemExit(
                    f"--zero needs --batch-size divisible by the "
                    f"{ndev}-device data mesh, got {args.batch_size}")
            zero_cfg = ZeroConfig(stage=args.zero)
            repl = NamedSharding(zero_mesh, P())
            bsh = NamedSharding(zero_mesh, P("data"))
            params, opt_state = place_zero_state(params, opt_state,
                                                 zero_mesh, zero_cfg)
            mstate = jax.device_put(mstate, repl)
            x = jax.device_put(x, bsh)
            y = jax.device_put(y, bsh)
            zero_meta = dict(record_memory_gauges(params, opt_state),
                             zero_stage=args.zero, zero_devices=ndev)
        jit_step = build_train_step(model, criterion, optim,
                                    zero=zero_cfg, mesh=zero_mesh,
                                    precision=policy)
        key = jax.random.PRNGKey(0)

        def make_chunk(k):
            # k fused train steps over the SAME resident batch, per-step
            # keys threaded as scan xs — measures what bounded async
            # dispatch amortizes (per-dispatch + per-sync host cost),
            # with zero feed variance
            def body(carry, kk):
                p, o, m = carry
                p, o, m, loss = jit_step(p, o, m, kk, 0.01, x, y)
                return (p, o, m), loss

            @functools.partial(jax.jit, donate_argnums=(0,))
            def chunk(carry, keys):
                return lax.scan(body, carry, keys)
            return chunk

        if sync_k > 1:
            chunk = make_chunk(sync_k)
            keys0 = jax.random.split(key, sync_k)
            carry = (params, opt_state, mstate)
            try:
                chunk = chunk.lower(carry, keys0).compile()
                compiled_for_cost = chunk
            except Exception as e:
                print(f"# cost-analysis unavailable ({type(e).__name__})")

            def run():
                nonlocal carry
                carry, losses = chunk(carry, keys0)
                # close the window on the full carry, not the loss path
                jax.block_until_ready(carry[0])
                return losses
        else:
            step = jit_step
            try:
                step = step.lower(params, opt_state, mstate, key, 0.01,
                                  x, y).compile()
                compiled_for_cost = step
            except Exception as e:
                print(f"# cost-analysis unavailable ({type(e).__name__})")

            def run():
                nonlocal params, opt_state, mstate
                params, opt_state, mstate, loss = step(
                    params, opt_state, mstate, key, 0.01, x, y)
                # the loss fetch in sync() does not gate on the param
                # update branch of the program; block here so
                # per-iteration timings cover the WHOLE step, not just
                # the loss path
                jax.block_until_ready(params)
                return loss
    else:
        eval_step = build_eval_step(model, precision=policy)
        try:
            eval_step = eval_step.lower(params, mstate, x).compile()
            compiled_for_cost = eval_step
        except Exception as e:
            print(f"# cost-analysis unavailable ({type(e).__name__})")

        def run():
            return eval_step(params, mstate, x)

    def sync(out):
        # fetch a VALUE, not just block_until_ready: on tunneled
        # backends readiness can signal before execution completes
        # (BASELINE.md feed note) — dispatch-only timings read 100x fast
        leaf = jax.tree_util.tree_leaves(out)[0]
        return float(jnp.sum(jnp.asarray(leaf).astype(jnp.float32)))

    recs_per_iter = (args.batch_size * sync_k
                     * (in_shape[0] if is_lm else 1))
    prec_tag = args.precision if args.precision else args.dtype
    print(f"# {args.model} {args.mode} batch={args.batch_size} "
          f"dtype={prec_tag} steps_per_sync={sync_k} "
          f"backend={jax.default_backend()}")
    for i in range(args.warmup):
        t0 = time.perf_counter()
        sync(run())
        _WARMUP_S.observe(time.perf_counter() - t0, model=args.model,
                          mode=args.mode)
    times = []
    for i in range(args.iterations):
        t0 = time.perf_counter()
        with telemetry.span("tools/perf_iteration", i=i):
            sync(run())
        dt = time.perf_counter() - t0
        _ITER_S.observe(dt, model=args.model, mode=args.mode)
        times.append(dt)
        unit = "tok/s" if is_lm else "img/s"
        rate = recs_per_iter / dt
        print(f"iter {i}: {dt*1000:.1f} ms  {rate:.1f} {unit}")
    med = float(np.median(times))
    rate = recs_per_iter / med
    line = (f"median: {med*1000:.1f} ms  {rate:.1f} "
            f"{'tok/s' if is_lm else 'img/s'}")
    # analytic MFU vs the measured device envelope (BASELINE.md platform
    # note; override with BIGDL_DEVICE_TFS) from the one compiled
    # program, through the shared telemetry.programs API — the same
    # math ceiling/bench consume, plus the HBM footprint the cost line
    # alone never showed
    import os
    program_fields = {}
    # one label serves the program profile AND the JSON tail, so the
    # two can never disagree: "pallas" only on trace EVIDENCE (a
    # dispatch actually taken while this process traced — a model
    # with no kernel-eligible ops stays honest), "reference" for the
    # forced-off leg, unset otherwise
    kern_label = None
    if args.kernels == "off":
        kern_label = "reference"
    elif args.kernels == "on":
        from bigdl_tpu.kernels.dispatch import taken_in_thread
        kern_label = "pallas" if taken_in_thread() > 0 else None
    if compiled_for_cost is not None:
        from bigdl_tpu.telemetry import programs
        prog_name = f"perf/{args.model}/{args.mode}"
        prof = programs.registry().register(
            prog_name, "train" if args.mode == "train" else "serving",
            compiled=compiled_for_cost, scan_length=sync_k,
            items_per_call=recs_per_iter, kernel=kern_label)
        rated = programs.registry().record_rate(prog_name,
                                                recs_per_iter / med)
        if rated is not None and rated.achieved_tfs is not None:
            line += (f"  |  {rated.achieved_tfs:.2f} TF/s analytic, "
                     f"MFU {100 * rated.mfu:.1f}% of "
                     f"{programs.DEVICE_TFS:.0f} TF/s peak")
            program_fields = {"achieved_tfs": rated.achieved_tfs,
                              "mfu_vs_peak": rated.mfu}
        else:
            line += "  |  cost-analysis unavailable on this backend"
        if prof.hbm_bytes:
            program_fields["program_hbm_bytes"] = int(prof.hbm_bytes)
            program_fields["program_flops_per_call"] = prof.flops
    print(line)

    # machine-readable JSON tail (the driver's scoreboard hook): the
    # run's steps/sec at its window size, plus the K=1-vs-K=8 dispatch
    # comparison when requested
    from bigdl_tpu import kernels as _kernels_tail
    tail = {"tool": "perf", "model": args.model, "mode": args.mode,
            "batch_size": args.batch_size, "dtype": prec_tag,
            "backend": jax.default_backend(), "median_s": med,
            "rate": rate, "steps_per_sync": sync_k,
            "kernels": ("on" if _kernels_tail.get_config().any_enabled
                        else "off"),
            "kernel_label": kern_label}
    if args.config is not None:
        tail["tuned_config"] = args.config
        tail["tuned_applied"] = tuned_applied
    tail.update(zero_meta)
    tail.update(program_fields)
    if args.mode == "train":
        tail["steps_per_sec"] = sync_k / med
        if args.sync_compare:
            from bigdl_tpu.tools.sync_compare import measure_sync_compare
            carry2 = carry if sync_k > 1 else (params, opt_state, mstate)

            def build(k):
                # the main loop's compiled window is the same program
                # when k matches — reuse it instead of recompiling
                # (sync_k == 1 ran the plain per-step path: no chunk)
                return chunk if sync_k > 1 and k == sync_k \
                    else make_chunk(k)

            rates, carry2 = measure_sync_compare(
                build, carry2,
                lambda k, i: jax.random.split(
                    jax.random.fold_in(key, 100 * k + i + 1), k),
                total=max(8, args.iterations))
            tail.update(rates)
    import json
    print(json.dumps(tail))

    jsonl = args.metrics_jsonl or os.environ.get("BIGDL_METRICS_JSONL")
    if jsonl:
        telemetry.snapshot_to_jsonl(jsonl, meta=tail)
        print(f"# metrics snapshot appended to {jsonl}")


if __name__ == "__main__":
    main()
